"""MoE training on the real chip - the one compute subsystem with no
hardware number (Mixtral routing/dispatch ran only on CPU meshes and
the virtual-device dryruns). Bench-scale Mixtral: 8 experts top-2,
~470M params total (~117M active/token), flash attention, one v5e
chip; expert axis stays size-1 so this measures the ROUTING + einsum
DISPATCH cost, not cross-chip all-to-all."""
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tpufw.utils.profiling import enable_compile_cache

enable_compile_cache()

import jax.numpy as jnp

from tpufw.mesh import MeshConfig
from tpufw.models import Mixtral, MixtralConfig
from tpufw.train import Trainer, TrainerConfig, synthetic_batches

cfg = MixtralConfig(
    vocab_size=32_768,
    d_model=1024,
    n_layers=8,
    n_heads=8,
    n_kv_heads=4,
    head_dim=128,
    d_ff=2048,
    max_seq_len=2048,
    n_experts=8,
    experts_per_token=2,
    dtype=jnp.bfloat16,
    param_dtype=jnp.float32,
    attention_backend="flash",
    remat_policy="nothing",
)
if os.environ.get("MOE_PROBE_SORTED") == "1":
    import dataclasses as _dc
    cfg = _dc.replace(cfg, moe_dispatch="sorted")
print("dispatch:", cfg.moe_dispatch)
print("params:", cfg.n_params())
for batch in ((64,) if os.environ.get("MOE_PROBE_B64") else (32, 16, 8) if os.environ.get("MOE_PROBE_B32") else (16, 8)):
    try:
        trainer = Trainer(
            Mixtral(cfg),
            TrainerConfig(
                batch_size=batch, seq_len=2048, total_steps=6,
                lr=1e-4, warmup_steps=2, loss_chunk_size=512,
                log_every=1, sync_every=4,
            ),
            MeshConfig(),
        )
        trainer.init_state()
        hist = trainer.run(
            synthetic_batches(batch, 2048, cfg.vocab_size),
            model_flops_per_token=cfg.flops_per_token(2047),
        )
        print("MOE_PROBE b%d" % batch,
              [round(m.tokens_per_sec_per_chip, 1) for m in hist],
              [round(m.mfu, 4) for m in hist])
        break
    except Exception as e:
        print("MOE_PROBE b%d failed: %s: %s" % (batch, type(e).__name__, str(e)[:200]))
