#!/usr/bin/env bash
# Lint gate, two stages:
#
#   1. ruff over the library, workloads, and tests. Degrades
#      gracefully where ruff isn't installed (the training container
#      bakes only the runtime deps): prints a skip notice so local
#      pre-commit hooks and container smoke runs don't fail on
#      tooling absence. CI installs ruff explicitly
#      (.github/workflows/ci.yml), so that stage is real where it
#      matters.
#   2. tpulint (python -m tpufw.analysis) — the repo's own stdlib-ast
#      JAX/TPU rules (docs/ANALYSIS.md): hot-loop purity, mesh-axis
#      names, RNG discipline, env + observability registry hygiene.
#      No dependencies, so it always runs; exits non-zero on any
#      finding not absorbed by analysis_baseline.json.
set -euo pipefail
cd "$(dirname "$0")/.."

if command -v ruff >/dev/null 2>&1; then
    ruff check tpufw tests bench.py scripts "$@"
else
    echo "lint: ruff not installed; skipping (pip install ruff to enable)" >&2
fi

python -m tpufw.analysis
