#!/usr/bin/env bash
# Lint gate, two stages:
#
#   1. ruff over the library, workloads, and tests. Degrades
#      gracefully where ruff isn't installed (the training container
#      bakes only the runtime deps): prints a skip notice so local
#      pre-commit hooks and container smoke runs don't fail on
#      tooling absence. CI installs ruff explicitly
#      (.github/workflows/ci.yml), so that stage is real where it
#      matters.
#   2. tpulint (python -m tpufw.analysis) — the repo's own stdlib-ast
#      JAX/TPU rules (docs/ANALYSIS.md): hot-loop purity, mesh-axis
#      names, RNG discipline, env + observability registry hygiene,
#      jit donation, recompile churn, dtype drift, lock discipline,
#      the distributed-protocol layer (wire contracts, SPMD
#      divergence, HTTP surface, metric cardinality), and the
#      resource-lifetime layer (acquire/release pairing, CV
#      discipline, counter balance, donation windows). No
#      dependencies, so it always runs; exits non-zero on any
#      finding not absorbed by analysis_baseline.json.
#
# Fast path (pre-commit): `scripts/lint.sh --fast` runs tpulint with
# the replay cache (an unchanged tree replays the previous result in
# milliseconds) and gates only on findings in files you changed since
# HEAD — see docs/ANALYSIS.md "Incremental mode".
#
# `--layer {python,deploy,protocol,lifetime,all}` is forwarded to
# tpulint (deploy runs the cross-layer manifest rules TPU010-014,
# needs pyyaml; protocol runs the distributed-protocol rules
# TPU015-018; lifetime runs the resource-lifetime rules TPU019-022).
# Without --layer, tpulint also honors TPUFW_LINT_LAYERS (comma
# list) — see docs/ENV.md. Any other extra args are forwarded to
# ruff.
set -euo pipefail
cd "$(dirname "$0")/.."

FAST=0
LAYER_ARGS=()
RUFF_ARGS=()
EXPECT_LAYER=0
for arg in "$@"; do
    if [ "$EXPECT_LAYER" = "1" ]; then
        LAYER_ARGS+=("$arg")
        EXPECT_LAYER=0
    elif [ "$arg" = "--fast" ]; then
        FAST=1
    elif [ "$arg" = "--layer" ]; then
        LAYER_ARGS+=("$arg")
        EXPECT_LAYER=1
    elif [[ "$arg" == --layer=* ]]; then
        LAYER_ARGS+=("--layer" "${arg#--layer=}")
    else
        RUFF_ARGS+=("$arg")
    fi
done

if command -v ruff >/dev/null 2>&1; then
    ruff check tpufw tests bench.py scripts "${RUFF_ARGS[@]+"${RUFF_ARGS[@]}"}"
else
    echo "lint: ruff not installed; skipping (pip install ruff to enable)" >&2
fi

if [ "$FAST" = "1" ]; then
    python -m tpufw.analysis --cache --since HEAD \
        "${LAYER_ARGS[@]+"${LAYER_ARGS[@]}"}"
else
    python -m tpufw.analysis "${LAYER_ARGS[@]+"${LAYER_ARGS[@]}"}"
fi
