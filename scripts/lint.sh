#!/usr/bin/env bash
# Lint gate: ruff over the library, workloads, and tests.
#
# Degrades gracefully where ruff isn't installed (the training
# container bakes only the runtime deps): prints a skip notice and
# exits 0 so local pre-commit hooks and container smoke runs don't
# fail on tooling absence. CI installs ruff explicitly
# (.github/workflows/ci.yml), so the gate is real where it matters.
set -euo pipefail
cd "$(dirname "$0")/.."

if ! command -v ruff >/dev/null 2>&1; then
    echo "lint: ruff not installed; skipping (pip install ruff to enable)" >&2
    exit 0
fi

ruff check tpufw tests bench.py scripts "$@"
