"""CI smoke for disaggregated serving: prefill replica + decode
replica + front-door router ganged in ONE process on CPU.

The replicas are real engines (tpufw.serve.roles, llama3_tiny random
init, int8 KV so the quantized codes + scales travel the bundle) —
only the wire is elided: the router talks to them through
``LocalReplica``, the same client interface TcpReplica gives it in a
cluster. What must hold:

- a prefix-shared prompt pair completes THROUGH migration: both
  requests prefill on the prefill replica (the second attaching the
  first's pages from the prefix trie), export page bundles, splice
  into the decode replica, and emit exactly ``max_new`` tokens;
- a router fronting an artificially page-capped decode replica
  answers an oversized request with 429 + Retry-After (admission
  control, not a stall), while a small request still lands;
- a speculative decode replica (TPUFW_SERVE_SPEC_K semantics via the
  spec_k ctor kwarg: n-gram self-draft, accept-masked verify) serves
  the same migrated request BIT-EQUAL to the plain replica — greedy
  verify is exact, so disagg migration parity holds with speculation
  on, and the serve_spec events digest through obs_summary;
- request tracing stitches: the three per-role trace files merge
  (scripts/trace_merge.py) into per-request flame rows where one
  request's spans cross router, prefill, AND decode under one
  trace_id with monotone aligned timestamps, and the per-stage
  durations sum (within slack) to the router-observed TTFT;
- the SLO layer scores the run: /metrics exposes
  tpufw_slo_ttft_attainment with a per-tenant label, and
  obs_summary prints the SLO attainment table;
- prefill/decode fungibility: a chunked prefill replica serves the
  same request bit-equal (stages gain prefill_queue_chunks), a
  router with NO prefill replica steers the raw prompt onto a
  piggyback-enabled decode replica (response carries
  ``piggyback: true``, zero migration pages), and /healthz surfaces
  the chunk-occupancy signals the policy steers on;
- the router ledger (events-router.jsonl) digests cleanly through
  scripts/obs_summary.py, and /metrics exposes the router counters.

Exit 0 on success; any assertion or HTTP failure exits nonzero.
Honors TPUFW_TELEMETRY_DIR so CI can upload the artifacts.
"""

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

MAX_NEW = 6
PAGE = 16

# http: claims


def _post(base: str, body: dict):
    """(status, parsed-body, headers) — 4xx/5xx included, not raised."""
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def main() -> int:
    # wire: produces router-request
    # wire: consumes router-response via body, first_body
    import jax
    import jax.numpy as jnp

    from tpufw.infer import SamplingConfig
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.obs.events import EventLog, read_events
    from tpufw.obs.trace import Tracer
    from tpufw.serve.roles import DecodeEngine, PrefillEngine
    from tpufw.serve.router import LocalReplica, RouterServer

    greedy = SamplingConfig(temperature=0.0)
    cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3_tiny"].decode_config(), max_seq_len=64
    )
    model = Llama(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    from tpufw.workloads.env import env_opt_str

    tdir = env_opt_str("telemetry_dir") or tempfile.mkdtemp(
        prefix="tpufw-router-smoke-"
    )
    os.makedirs(tdir, exist_ok=True)
    events = EventLog(os.path.join(tdir, "events-router.jsonl"))
    # One tracer per role, exactly as the three pods would write them;
    # trace_merge stitches these by trace_id below.
    tracers = {
        role: Tracer(
            os.path.join(tdir, f"trace-{role}.json"), process_name=role
        )
        for role in ("router", "prefill", "decode")
    }
    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        print(("ok: " if ok else "FAILED: ") + what)
        if not ok:
            failures.append(what)

    common = dict(sampling=greedy, page=PAGE, kv_quant="int8",
                  events=events)
    pe = PrefillEngine(model, params, n_slots=2,
                       tracer=tracers["prefill"], **common)
    de = DecodeEngine(model, params, n_slots=4, chunk=2,
                      tracer=tracers["decode"], **common)
    router = RouterServer(
        [LocalReplica("prefill-0", pe)],
        [LocalReplica("decode-0", de)],
        port=0, page=PAGE, events=events, tracer=tracers["router"],
    )
    base = f"http://127.0.0.1:{router.port}"

    # ---- prefix-shared pair, completed through migration ----
    first_body: dict = {}
    shared = list(range(40, 72))  # 32 tokens = 2 full pages in the trie
    for i, tail in enumerate(([7, 9], [11, 3])):
        status, body, _h = _post(base, {
            "prompt": shared + tail, "max_new": MAX_NEW,
            "tenant": "smoke", "session": f"s{i}",
        })
        check(status == 200, f"request {i} routed (got {status}: {body})")
        if status == 200:
            if not first_body:
                first_body = body
            check(
                len(body["tokens"]) == MAX_NEW,
                f"request {i} decoded {MAX_NEW} tokens through migration "
                f"(pages={body['migration_pages']}, "
                f"replica={body['replica']})",
            )
            check(
                bool(body.get("prefill_replica")),
                f"request {i} names the prefill replica it rode "
                f"(prefill_replica={body.get('prefill_replica')})",
            )
    check(
        pe.migrations == 2 and de.migrations == 2,
        f"both requests migrated (exported={pe.migrations}, "
        f"imported={de.migrations})",
    )
    shared_exports = [
        e for e in read_events(os.path.join(tdir, "events-router.jsonl"))
        if e.get("kind") == "serve_migration"
        and e.get("direction") == "export"
        and (e.get("shared_pages") or 0) > 0
    ]
    check(
        len(shared_exports) >= 1,
        "second prefill attached the shared prefix from the trie "
        f"({len(shared_exports)} shared-page export(s))",
    )

    # ---- admission control against a page-capped decode arena ----
    de_cap = DecodeEngine(
        model, params, n_slots=2, chunk=2, arena_pages=4,  # 3 usable
        sampling=greedy, page=PAGE, kv_quant="int8",
    )
    capped = RouterServer(
        [LocalReplica("prefill-0", pe)],
        [LocalReplica("decode-cap", de_cap)],
        port=0, page=PAGE, events=events,
    )
    cbase = f"http://127.0.0.1:{capped.port}"
    status, body, headers = _post(cbase, {
        "prompt": list(range(1, 57)), "max_new": MAX_NEW,  # 4 pages
        "tenant": "smoke",
    })
    check(
        status == 429 and headers.get("Retry-After") is not None,
        f"oversized request 429s with Retry-After="
        f"{headers.get('Retry-After')} (got {status}: {body})",
    )
    check(
        bool(body.get("error")),
        f"429 body says why it was turned away (error={body.get('error')})",
    )
    # Client-supplied trace in the request body (the no-header path a
    # curl user takes): the router must join it, not mint a new one.
    client_trace = "deadbeefdeadbeef-cafe0123-smoke"
    status, body, _h = _post(cbase, {
        "prompt": [1, 2, 3], "max_new": 4, "tenant": "smoke",
        "trace": client_trace,
    })
    check(
        status == 200 and len(body.get("tokens", [])) == 4,
        f"small request still fits the capped arena (got {status})",
    )
    check(
        body.get("trace") == client_trace.split("-")[0],
        f"router joined the client-supplied trace id "
        f"(got trace={body.get('trace')})",
    )

    # ---- speculation on the decode replica: migration parity ----
    # Fresh prefill replica on purpose: ``pe``'s trie already holds the
    # shared prefix, and an int8 trie hit recomputes the suffix over
    # DEQUANTIZED prefix KV — approximate by design, so shared-vs-cold
    # bit-parity doesn't hold under int8. A cold export keeps this
    # check about what it claims: spec verify vs plain decode.
    pe_spec = PrefillEngine(model, params, n_slots=2, **common)
    de_spec = DecodeEngine(
        model, params, n_slots=4, chunk=2, spec_k=4,
        sampling=greedy, page=PAGE, kv_quant="int8", events=events,
    )
    spec_router = RouterServer(
        [LocalReplica("prefill-spec", pe_spec)],
        [LocalReplica("decode-spec", de_spec)],
        port=0, page=PAGE, events=events,
    )
    sbase = f"http://127.0.0.1:{spec_router.port}"
    status, body, _h = _post(sbase, {
        "prompt": shared + [7, 9], "max_new": MAX_NEW,
        "tenant": "smoke",
    })
    check(
        status == 200
        and body.get("tokens") == first_body.get("tokens"),
        "spec-enabled decode replica is bit-equal to the plain one "
        f"through migration (spec_passes={de_spec.spec_passes}, "
        f"got {body.get('tokens')} vs {first_body.get('tokens')})",
    )
    check(
        de_spec.pool.allocator.in_use == 0,
        "spec replica returned every page after retire "
        f"(in_use={de_spec.pool.allocator.in_use})",
    )
    spec_router.close()

    # ---- chunked prefill + raw-prompt piggyback fungibility ----
    # Separate RouterServers on purpose: the main router's /metrics
    # assertion below counts exactly its own 2 requests.
    pe_ck = PrefillEngine(
        model, params, n_slots=2, prefill_chunk_pages=1, **common
    )
    de_pig = DecodeEngine(
        model, params, n_slots=4, chunk=2,
        prefill_chunk_pages=1, piggyback=0.05,
        sampling=greedy, page=PAGE, kv_quant="int8", events=events,
    )
    ck_router = RouterServer(
        [LocalReplica("prefill-ck", pe_ck)],
        [LocalReplica("decode-pig", de_pig)],
        port=0, page=PAGE, events=events,
    )
    kbase = f"http://127.0.0.1:{ck_router.port}"
    status, body, _h = _post(kbase, {
        "prompt": shared + [7, 9], "max_new": MAX_NEW, "tenant": "smoke",
    })
    check(
        status == 200
        and body.get("tokens") == first_body.get("tokens"),
        "chunked prefill replica is bit-equal to the monolithic one "
        f"through migration (got {body.get('tokens')})",
    )
    check(
        "prefill_queue_chunks" in body.get("stages", {}),
        "TTFT decomposition gained the prefill_queue_chunks stage "
        f"(stages={sorted(body.get('stages', {}))})",
    )
    ck_router.close()
    # No prefill replica at all: the router must steer the raw prompt
    # straight onto the piggyback-enabled decode replica.
    pig_router = RouterServer(
        [], [LocalReplica("decode-pig", de_pig)],
        port=0, page=PAGE, events=events,
    )
    gbase = f"http://127.0.0.1:{pig_router.port}"
    status, body, _h = _post(gbase, {
        "prompt": shared + [7, 9], "max_new": MAX_NEW, "tenant": "smoke",
    })
    check(
        status == 200 and body.get("piggyback") is True
        and body.get("migration_pages") == 0,
        "raw prompt piggybacked onto the decode replica — no prefill "
        f"hop, no migration (got {status}, "
        f"piggyback={body.get('piggyback')})",
    )
    check(
        body.get("tokens") == first_body.get("tokens"),
        "piggybacked request is bit-equal to the migrated one "
        f"(got {body.get('tokens')})",
    )
    with urllib.request.urlopen(gbase + "/healthz", timeout=60) as resp:
        health = json.loads(resp.read())
    rep = health.get("replicas", {}).get("decode-pig", {})
    chunk_sig = {
        k: rep.get(k)
        for k in ("prefill_chunk_pages", "piggyback_waterline",
                  "prefill_inflight")
    }
    check(
        rep.get("prefill_chunk_pages") == 1
        and "piggyback_waterline" in rep
        and "prefill_inflight" in rep,
        "/healthz surfaces the chunk-occupancy signals the policy "
        f"steers on ({chunk_sig})",
    )
    with urllib.request.urlopen(gbase + "/metrics", timeout=60) as resp:
        pig_metrics = resp.read().decode()
    check(
        "tpufw_router_piggyback_total 1" in pig_metrics,
        "router counted the piggyback admission on /metrics",
    )
    pig_router.close()

    # ---- request tracing: merge per-role traces, check the stitch ----
    for tr in tracers.values():
        tr.close()
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run(
        [sys.executable, os.path.join(scripts_dir, "trace_merge.py"),
         tdir],
        capture_output=True, text=True, timeout=120,
    )
    print(proc.stdout, end="")
    reqs_path = os.path.join(tdir, "trace-requests.json")
    check(
        proc.returncode == 0 and os.path.exists(reqs_path),
        "trace_merge produced the per-request flame rows",
    )
    tid = str(first_body.get("trace", ""))
    spans_by_name: dict = {}
    roles_hit: set = set()
    if os.path.exists(reqs_path):
        with open(reqs_path, encoding="utf-8") as f:
            reqdoc = json.load(f)
        summary = reqdoc.get("otherData", {}).get("requests", {})
        entry = summary.get(tid, {})
        check(
            len(entry.get("roles", [])) >= 3,
            f"request {tid[:8]} has spans from all three roles "
            f"under one trace_id (roles={entry.get('roles')}, "
            f"spans={entry.get('spans')})",
        )
        with open(os.path.join(tdir, "trace-merged.json"),
                  encoding="utf-8") as f:
            merged = json.load(f)
        for ev in merged.get("traceEvents", []):
            if (
                ev.get("ph") == "X"
                and (ev.get("args") or {}).get("trace") == tid
            ):
                spans_by_name.setdefault(ev["name"], []).append(ev)
                roles_hit.add(ev.get("pid"))
        causal = [
            "req_queue_wait", "req_prefill_compute",
            "req_splice", "req_first_token",
        ]
        check(
            all(n in spans_by_name for n in causal),
            f"per-stage spans present for {tid[:8]} "
            f"({sorted(spans_by_name)})",
        )
        starts = [
            min(e["ts"] for e in spans_by_name[n])
            for n in causal if n in spans_by_name
        ]
        # Aligned clocks are wall-quality: allow 1ms of jitter, the
        # stages themselves are orders of magnitude longer on CPU.
        check(
            all(b >= a - 1000.0 for a, b in zip(starts, starts[1:])),
            f"aligned stage timestamps are monotone ({starts})",
        )
    stages = first_body.get("stages", {})
    ttft = float(first_body.get("ttft_s", 0.0))
    stage_sum = sum(
        float(v) for k, v in stages.items() if k != "first_decode"
    )
    check(
        ttft > 0.0 and abs(stage_sum - ttft) <= max(0.05, 0.25 * ttft),
        f"per-stage durations sum to the router TTFT "
        f"(sum={stage_sum:.4f}s vs ttft={ttft:.4f}s, stages={stages})",
    )

    # ---- ledger digests + router/SLO series on /metrics ----
    with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
        metrics = resp.read().decode()
    check(
        "tpufw_router_requests_total 2" in metrics,
        "router counted its 2 routed requests on /metrics",
    )
    check(
        'tpufw_slo_ttft_attainment{tenant="smoke"}' in metrics,
        "SLO attainment gauge scrapes with the per-tenant label",
    )
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "obs_summary.py"),
         tdir],
        capture_output=True, text=True, timeout=120,
    )
    print(proc.stdout, end="")
    check(
        proc.returncode == 0 and "router / migration" in proc.stdout
        and "rejected" in proc.stdout
        and "SLO attainment" in proc.stdout,
        "obs_summary digests the router ledger + SLO table",
    )

    router.close()
    capped.close()
    if failures:
        print(f"router-smoke FAILED ({len(failures)} check(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("router-smoke OK: migration served end-to-end, saturation "
          "admission held the door")
    return 0


if __name__ == "__main__":
    sys.exit(main())
