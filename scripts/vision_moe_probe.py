"""Two more first-hardware-numbers: ViT-B/16 training (the MXU-native
vision path - does the vision stack escape ResNet's conv ceiling?) and
bench-scale DeepSeek-MoE (fine-grained routed experts + shared expert,
MLA attention) through BOTH dispatch paths. JSON rows to
docs/evidence/VISION_MOE_r5.jsonl."""
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "VISION_MOE_r5.jsonl",
)
_TAGS: dict = {}


def emit(row):
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main():
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import gc
    import statistics

    import jax
    import jax.numpy as jnp

    from tpufw.mesh import MeshConfig
    from tpufw.train import (
        Trainer,
        TrainerConfig,
        VisionTrainer,
        VisionTrainerConfig,
        synthetic_batches,
        synthetic_images,
    )

    d = jax.devices()[0]
    _TAGS.update(platform=d.platform)
    emit({"event": "start", "kind": d.device_kind})

    # 1. ViT-B/16 at 224px, bf16, batch ladder.
    from tpufw.models import VIT_CONFIGS, ViT

    for batch in (256, 128):
        try:
            vcfg = VIT_CONFIGS["vit_b16"]
            vt = VisionTrainer(
                ViT(vcfg),
                VisionTrainerConfig(
                    batch_size=batch, image_size=224,
                    total_steps=9, sync_every=4,
                ),
                MeshConfig(),
            )
            vt.init_state()
            hist = vt.run(
                synthetic_images(batch, 224, 1000, on_device=True),
                flops_per_image=vcfg.flops_per_image(224),
            )
            steady = [m for m in hist if m.step > 1]
            emit({
                "case": f"vit_b16_b{batch}",
                "img_per_s": round(statistics.median(
                    m.tokens_per_sec_per_chip for m in steady
                ), 1),
                "mfu": round(statistics.median(
                    m.mfu for m in steady
                ), 4),
            })
            del vt
            break
        except Exception as e:  # noqa: BLE001
            emit({"case": f"vit_b16_b{batch}",
                  "error": f"{type(e).__name__}: {e}"[:300]})
    gc.collect()
    jax.clear_caches()

    # 2. Bench-scale DeepSeek-MoE: MLA attention (flash), 32 routed
    # fine-grained experts top-6 + 1 shared, ~60M/token active.
    from tpufw.models import Deepseek, DeepseekConfig

    for dispatch in ("sorted", "einsum"):
        try:
            dcfg = DeepseekConfig(
                vocab_size=32_768,
                d_model=1024,
                n_layers=8,
                n_heads=8,
                kv_lora_rank=256,
                qk_nope_head_dim=64,
                qk_rope_head_dim=32,
                v_head_dim=64,
                d_ff=2048,
                n_routed_experts=32,
                experts_per_token=6,
                moe_d_ff=256,
                n_shared_experts=1,
                capacity_factor=1.25,
                max_seq_len=2048,
                dtype=jnp.bfloat16,
                param_dtype=jnp.float32,
                attention_backend="flash",
                remat_policy="nothing",
                moe_dispatch=dispatch,
            )
            batch = 32 if dispatch == "sorted" else 8
            tr = Trainer(
                Deepseek(dcfg),
                TrainerConfig(
                    batch_size=batch, seq_len=2048, total_steps=6,
                    lr=1e-4, warmup_steps=2, loss_chunk_size=512,
                    log_every=1, sync_every=4,
                ),
                MeshConfig(),
            )
            tr.init_state()
            hist = tr.run(
                synthetic_batches(batch, 2048, dcfg.vocab_size),
                model_flops_per_token=dcfg.flops_per_token(2047),
            )
            steady = [
                m for m in hist if m.step - m.window_steps + 1 > 1
            ] or hist[-1:]
            emit({
                "case": f"deepseek_moe_{dispatch}",
                "batch": batch,
                "params": dcfg.n_params(),
                "tok_per_s": round(statistics.median(
                    m.tokens_per_sec_per_chip for m in steady
                ), 1),
                "mfu_active": round(statistics.median(
                    m.mfu for m in steady
                ), 4),
            })
            del tr
        except Exception as e:  # noqa: BLE001
            emit({"case": f"deepseek_moe_{dispatch}",
                  "error": f"{type(e).__name__}: {e}"[:300]})
        gc.collect()
        jax.clear_caches()
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
