"""CI smoke for the slot scheduler's continuous batching.

Starts the CPU HTTP server (llama3_tiny, random init — weight values
don't matter for scheduling behavior), then overlaps three requests:

- LONG:   max_new 60 — submitted first, holds a slot the whole run;
- SHORT:  max_new 4  — submitted after the long one has started;
- STREAM: max_new 16 — SSE, sharing decode chunks with both.

The assertion that matters: the SHORT request COMPLETES while the
LONG one is still decoding. Under the old tick batcher this is
impossible (the short rows ride the tick to the long request's
bucketed max_new, or wait for the solo stream tick); under the slot
scheduler the short row joins mid-flight and retires at its own
max_new. TPUFW_SERVE_CHUNK=2 keeps chunk boundaries (= join/retire
opportunities) frequent on a tiny model.

The run uses the PAGED KV pool (TPUFW_SERVE_PAGE=16): after the
overlap test, two sequential requests share a 36-token prefix — the
second must hit the prefix cache (tpufw_serve_prefix_hits_total >= 1
on /metrics), and by the end retired rows must have returned pages
to the arena (pages_freed_total > 0, pages_in_use < pages_total).

Speculation rides the whole smoke: TPUFW_SERVE_SPEC_K=4 turns on
n-gram self-drafting for every request above (greedy verify is
bit-exact, so the length/ordering assertions double as a parity
check), and a final section runs one more request end-to-end and
asserts the spec metrics are exposed on /metrics.

Chunked prefill rides the whole smoke too (TPUFW_SERVE_PREFILL_CHUNK
=1: every admission drains page-by-page through the shared passes —
chunked-vs-monolithic is bit-equal under greedy, so every assertion
above doubles as a parity check), and a final section submits a
1-page prompt AFTER a 6-page prompt and asserts the short request's
first streamed token lands BEFORE the long one's — a long prompt no
longer head-of-line-blocks admission.

Exit 0 on success; any assertion or HTTP failure exits nonzero.
"""

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)
os.environ.setdefault("TPUFW_MODEL", "llama3_tiny")
os.environ.setdefault("TPUFW_SERVE_CHUNK", "2")
os.environ.setdefault("TPUFW_SERVE_PAGE", "16")
os.environ.setdefault("TPUFW_SERVE_SPEC_K", "4")
os.environ.setdefault("TPUFW_SERVE_PREFILL_CHUNK", "1")

LONG_NEW, SHORT_NEW, STREAM_NEW = 60, 4, 16


def main() -> int:
    from tpufw.workloads.serve import _Server

    srv = _Server(port=0, max_new_tokens=LONG_NEW)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    deadline = time.time() + 60
    while not hasattr(srv, "httpd") and time.time() < deadline:
        time.sleep(0.05)
    base = f"http://127.0.0.1:{srv.port}"

    done_at: dict[str, float] = {}
    errors: list[str] = []

    def post(name: str, body: dict) -> None:
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                out = json.loads(resp.read())
            assert len(out["outputs"][0]) == body["max_new_tokens"], out
        except Exception as e:  # noqa: BLE001 — report, don't hang CI
            errors.append(f"{name}: {type(e).__name__}: {e}")
        done_at[name] = time.time()

    def post_stream(name: str, body: dict) -> None:
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            events = []
            with urllib.request.urlopen(req, timeout=600) as resp:
                for line in resp:
                    line = line.strip()
                    if line.startswith(b"data: "):
                        events.append(json.loads(line[len(b"data: "):]))
            chunks = [e["outputs"] for e in events if "outputs" in e]
            # chunk 2 over 16 tokens: it must have actually streamed.
            assert len(chunks) >= 2, events
            assert events[-1] == {"done": True}, events
            got = sum(len(r) for rows in chunks for r in rows)
            assert got == body["max_new_tokens"], (got, events)
        except Exception as e:  # noqa: BLE001
            errors.append(f"{name}: {type(e).__name__}: {e}")
        done_at[name] = time.time()

    long_t = threading.Thread(
        target=post,
        args=("long", {"prompts": [[1, 2, 3]], "max_new_tokens": LONG_NEW}),
    )
    long_t.start()
    time.sleep(0.3)  # let the long request occupy its slot first
    short_t = threading.Thread(
        target=post,
        args=("short", {"prompts": [[4, 5]], "max_new_tokens": SHORT_NEW}),
    )
    stream_t = threading.Thread(
        target=post_stream,
        args=(
            "stream",
            {
                "prompts": [[6, 7, 8]],
                "max_new_tokens": STREAM_NEW,
                "stream": True,
            },
        ),
    )
    short_t.start()
    stream_t.start()
    for t in (long_t, short_t, stream_t):
        t.join(timeout=600)

    if errors:
        print("serve-smoke FAILED:\n  " + "\n  ".join(errors))
        return 1
    order = sorted(done_at, key=done_at.get)
    print(
        "completion order:",
        " -> ".join(f"{n}@{done_at[n] - min(done_at.values()):.2f}s"
                    for n in order),
    )
    if done_at["short"] >= done_at["long"]:
        print(
            "serve-smoke FAILED: short request did not complete before "
            "the long one — continuous batching is not interleaving"
        )
        return 1
    print("serve-smoke OK: short joined and retired mid-flight")

    # ---- paged KV: prefix sharing + page reclamation ----
    from tpufw.workloads.env import env_int

    if not env_int("serve_page", 0):
        print("serve-smoke: paged-KV section skipped (TPUFW_SERVE_PAGE=0)")
        srv.httpd.shutdown()
        return 0
    # Sequential on purpose: the second request must be admitted after
    # the first registered its prompt pages in the trie.
    shared = list(range(40, 76))  # 36 tokens = 2 full 16-token pages
    post("prefix_a", {"prompts": [shared + [7, 9]], "max_new_tokens": 8})
    post("prefix_b", {"prompts": [shared + [11, 3]], "max_new_tokens": 8})
    if errors:
        print("serve-smoke FAILED:\n  " + "\n  ".join(errors))
        return 1
    with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
        metrics = {}
        for line in resp.read().decode().splitlines():
            if line and not line.startswith("#"):
                name, _, val = line.partition(" ")
                metrics[name] = float(val)
    hits = metrics.get("tpufw_serve_prefix_hits_total", 0.0)
    freed = metrics.get("tpufw_serve_pages_freed_total", 0.0)
    in_use = metrics.get("tpufw_serve_pages_in_use", -1.0)
    total = metrics.get("tpufw_serve_pages_total", 0.0)
    print(
        f"paged KV: prefix_hits={hits:.0f} pages_freed={freed:.0f} "
        f"pages_in_use={in_use:.0f}/{total:.0f}"
    )
    if hits < 1:
        print("serve-smoke FAILED: no prefix cache hit on the shared "
              "36-token prefix")
        return 1
    if freed <= 0 or not (0 <= in_use < total):
        print("serve-smoke FAILED: retired rows did not return pages "
              "to the arena")
        return 1
    print("serve-smoke OK: prefix shared and pages reclaimed")

    # ---- speculative decoding: one request end-to-end + metrics ----
    from tpufw.workloads.env import env_int as _env_int

    if not _env_int("serve_spec_k", 0):
        print("serve-smoke: spec section skipped (TPUFW_SERVE_SPEC_K=0)")
        srv.httpd.shutdown()
        return 0
    # A self-similar prompt gives the n-gram draft something to mine;
    # whatever it accepts, greedy verify keeps the output exact.
    post(
        "spec",
        {"prompts": [[5, 9, 5, 9, 5, 9, 5, 9, 5, 9]],
         "max_new_tokens": 12},
    )
    if errors:
        print("serve-smoke FAILED:\n  " + "\n  ".join(errors))
        return 1
    with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
        metrics = {}
        for line in resp.read().decode().splitlines():
            if line and not line.startswith("#"):
                name, _, val = line.partition(" ")
                metrics[name] = float(val)
    missing = [
        n for n in (
            "tpufw_spec_accept_rate",
            "tpufw_spec_fallback_slots",
            "tpufw_spec_wasted_draft_flops_total",
        ) if n not in metrics
    ]
    if missing:
        print(f"serve-smoke FAILED: spec metrics absent: {missing}")
        return 1
    print(
        "spec: accept_rate="
        f"{metrics['tpufw_spec_accept_rate']:.3f} "
        f"fallback_slots={metrics['tpufw_spec_fallback_slots']:.0f} "
        "wasted_draft_flops="
        f"{metrics['tpufw_spec_wasted_draft_flops_total']:.0f}"
    )
    print("serve-smoke OK: speculative request served end-to-end")

    # ---- chunked prefill: no head-of-line blocking on admission ----
    if not env_int("serve_prefill_chunk", 0):
        print("serve-smoke: chunked-prefill section skipped "
              "(TPUFW_SERVE_PREFILL_CHUNK=0)")
        srv.httpd.shutdown()
        return 0
    # A 6-page prompt submitted FIRST, a 1-page prompt AFTER it: with
    # chunked admission the short prompt's single prefill chunk
    # interleaves between the long one's six, so its first streamed
    # token must land before the long prompt even finishes prefilling
    # (and therefore before the long one's first token).
    first_chunk_at: dict[str, float] = {}

    def post_stream_timed(name: str, body: dict) -> None:
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=600) as resp:
                for line in resp:
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    ev = json.loads(line[len(b"data: "):])
                    if (
                        name not in first_chunk_at
                        and any(ev.get("outputs") or [])
                    ):
                        first_chunk_at[name] = time.time()
        except Exception as e:  # noqa: BLE001
            errors.append(f"{name}: {type(e).__name__}: {e}")

    long_prompt = list(range(2, 98))  # 96 tokens = 6 pages
    hol_long = threading.Thread(
        target=post_stream_timed,
        args=("hol_long", {
            "prompts": [long_prompt], "max_new_tokens": 12,
            "stream": True,
        }),
    )
    hol_long.start()
    time.sleep(0.05)  # long admission grabs its slot first
    hol_short = threading.Thread(
        target=post_stream_timed,
        args=("hol_short", {
            "prompts": [[9, 8, 7, 6, 5, 4, 3, 2]], "max_new_tokens": 4,
            "stream": True,
        }),
    )
    hol_short.start()
    hol_long.join(timeout=600)
    hol_short.join(timeout=600)
    if errors:
        print("serve-smoke FAILED:\n  " + "\n  ".join(errors))
        return 1
    if not ("hol_long" in first_chunk_at and "hol_short" in first_chunk_at):
        print(f"serve-smoke FAILED: missing first tokens "
              f"({sorted(first_chunk_at)})")
        return 1
    gap = first_chunk_at["hol_long"] - first_chunk_at["hol_short"]
    print(f"chunked prefill: short first token {gap:.3f}s before long's")
    if gap <= 0:
        print("serve-smoke FAILED: 1-page prompt head-of-line blocked "
              "behind the 6-page prompt's prefill")
        return 1
    with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
        metrics = {}
        for line in resp.read().decode().splitlines():
            if line and not line.startswith("#"):
                name, _, val = line.partition(" ")
                metrics[name] = float(val)
    chunks = metrics.get("tpufw_prefill_chunks_total", 0.0)
    inflight = metrics.get("tpufw_prefill_inflight", -1.0)
    if chunks < 7 or "tpufw_prefill_resumes_total" not in metrics \
            or inflight != 0:
        print(f"serve-smoke FAILED: chunked-prefill series wrong "
              f"(chunks={chunks}, inflight={inflight}, "
              f"resumes_present="
              f"{'tpufw_prefill_resumes_total' in metrics})")
        return 1
    print(f"serve-smoke OK: chunked prefill interleaved "
          f"({chunks:.0f} chunks, no HOL blocking)")
    srv.httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
