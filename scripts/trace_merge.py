#!/usr/bin/env python
"""Merge per-host Chrome trace files into one Perfetto timeline.

Every tpufw process writes its own span trace (``trace.json`` from the
trainer, ``trace-p{N}.json`` from pipeline stages, ``trace-serve.json``
from the serving loop) with timestamps on its process-local
``perf_counter`` clock — epoch-arbitrary, so side-by-side loading in
Perfetto shows unrelated time axes. Each file also records its
run-start wall clock (``otherData.wall_epoch_s``, stamped when the
tracer was created). This script uses that anchor to shift every
file's events onto one shared axis (the earliest host is t=0), remaps
pids so hosts get separate tracks, and writes a single merged
Perfetto-loadable document.

Alignment is wall-clock quality, not PTP: good to NTP skew (typically
low milliseconds on a cluster), which is enough to see cross-host
stalls, stragglers, and lock-step barriers at step granularity.

Usage:
    python scripts/trace_merge.py <telemetry_dir>            # glob trace*.json
    python scripts/trace_merge.py a.json b.json -o out.json  # explicit files

Torn or unparsable files (a host died mid-write) are skipped with a
warning; the merge proceeds with whatever loads.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional, Tuple

MERGED_BASENAME = "trace-merged.json"
REQUESTS_BASENAME = "trace-requests.json"


def discover(path: str) -> List[str]:
    """Trace files in a telemetry dir: trace.json, trace-p*.json,
    trace-serve.json, trace-{router,prefill,decode}.json — everything
    matching trace*.json except previous merge outputs."""
    hits = sorted(glob.glob(os.path.join(path, "trace*.json")))
    skip = {MERGED_BASENAME, REQUESTS_BASENAME}
    return [h for h in hits if os.path.basename(h) not in skip]


def load_trace(path: str) -> Optional[dict]:
    """One trace document, or None (with a stderr warning) when the
    file is torn, truncated, or not a trace."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        print(f"trace_merge: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        print(
            f"trace_merge: skipping {path}: no traceEvents list",
            file=sys.stderr,
        )
        return None
    return doc


def _anchor(doc: dict) -> Optional[float]:
    other = doc.get("otherData")
    if isinstance(other, dict):
        w = other.get("wall_epoch_s")
        if isinstance(w, (int, float)):
            return float(w)
    return None


def merge(
    docs: List[Tuple[str, dict]],
) -> dict:
    """Clock-align and combine trace documents.

    ``docs`` is [(source_path, doc), ...]. The earliest
    ``wall_epoch_s`` across inputs becomes the merged t=0; each file's
    events shift by (its anchor - earliest) in microseconds. Files
    missing the anchor (pre-PR-9 traces) merge unshifted at t=0 with a
    warning. Each file gets its own pid so hosts land on separate
    Perfetto tracks regardless of what pid they recorded."""
    anchors = [_anchor(doc) for _, doc in docs]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0
    events: List[dict] = []
    dropped_total = 0
    for idx, ((path, doc), anchor) in enumerate(zip(docs, anchors)):
        if anchor is None:
            print(
                f"trace_merge: {path} has no wall_epoch_s anchor; "
                "merging unshifted",
                file=sys.stderr,
            )
        shift_us = ((anchor - base) * 1e6) if anchor is not None else 0.0
        name = os.path.splitext(os.path.basename(path))[0]
        for ev in doc["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = idx
            if ev.get("ph") == "M":
                # Keep one process_name row per source file; qualify
                # it so "trainer" from two hosts stays tellable-apart.
                if ev.get("name") == "process_name":
                    orig = (ev.get("args") or {}).get("name", "")
                    label = f"{name}:{orig}" if orig else name
                    ev["args"] = {"name": label}
            elif "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            events.append(ev)
        other = doc.get("otherData")
        if isinstance(other, dict):
            dropped_total += int(other.get("dropped_events", 0) or 0)
    # Metadata first, then by aligned timestamp: Perfetto tolerates any
    # order, but a sorted merge makes the cross-host interleaving
    # checkable by eye (and by the tests).
    events.sort(
        key=lambda e: (0, 0.0) if e.get("ph") == "M" else (1, e.get("ts", 0.0))
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_epoch_s": base,
            "merged_from": [os.path.basename(p) for p, _ in docs],
            "dropped_events": dropped_total,
        },
    }


def request_rows(merged: dict) -> dict:
    """Regroup an already-aligned merged document into per-request
    flame rows: every complete span carrying a reqtrace correlation
    (``args.trace``) lands on a track named for its trace_id, with one
    sub-row (tid) per source role. Loading the result in Perfetto
    shows each request as one left-to-right cascade — queue_wait →
    admit → prefill stages → wire → splice → decode chunks — instead
    of three disjoint per-process timelines.

    Returns a Perfetto-loadable doc; its ``otherData.requests`` maps
    trace_id -> {"spans": N, "roles": [source pids], "tenant": ...}
    (the CI smoke asserts one request's spans cross all three
    roles)."""
    # Source-file labels from the merged metadata: pid -> name.
    src_names = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            src_names[ev.get("pid")] = (ev.get("args") or {}).get(
                "name", str(ev.get("pid"))
            )
    by_trace: dict = {}
    for ev in merged.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args")
        if not isinstance(args, dict) or "trace" not in args:
            continue
        by_trace.setdefault(str(args["trace"]), []).append(ev)
    events: List[dict] = []
    summary: dict = {}
    ordered = sorted(
        by_trace.items(),
        key=lambda kv: min(e.get("ts", 0.0) for e in kv[1]),
    )
    for pid, (trace_id, evs) in enumerate(ordered):
        evs = sorted(evs, key=lambda e: e.get("ts", 0.0))
        tenant = next(
            (
                e["args"].get("tenant")
                for e in evs
                if e["args"].get("tenant")
            ),
            "",
        )
        label = f"req {trace_id[:8]}"
        if tenant:
            label += f" [{tenant}]"
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": label},
            }
        )
        roles = sorted({e.get("pid", 0) for e in evs})
        for src_pid in roles:
            events.append(
                {
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": src_pid,
                    "args": {
                        "name": src_names.get(src_pid, str(src_pid))
                    },
                }
            )
        for ev in evs:
            out = dict(ev)
            out["tid"] = ev.get("pid", 0)  # sub-row = source role
            out["pid"] = pid
            events.append(out)
        summary[trace_id] = {
            "spans": len(evs),
            "roles": roles,
            "tenant": tenant,
            "start_ts": evs[0].get("ts", 0.0),
            "end_ts": max(
                e.get("ts", 0.0) + e.get("dur", 0.0) for e in evs
            ),
        }
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_epoch_s": (merged.get("otherData") or {}).get(
                "wall_epoch_s", 0.0
            ),
            "requests": summary,
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "inputs",
        nargs="+",
        help="telemetry dir (globbed for trace*.json) or trace files",
    )
    ap.add_argument(
        "-o",
        "--out",
        default="",
        help=f"output path (default: <dir>/{MERGED_BASENAME})",
    )
    args = ap.parse_args(argv)

    files: List[str] = []
    out_default = MERGED_BASENAME
    for inp in args.inputs:
        if os.path.isdir(inp):
            files.extend(discover(inp))
            out_default = os.path.join(inp, MERGED_BASENAME)
        else:
            files.append(inp)
    if not files:
        print("trace_merge: no trace files found", file=sys.stderr)
        return 1
    docs = [(p, d) for p in files for d in [load_trace(p)] if d is not None]
    if not docs:
        print("trace_merge: no loadable trace files", file=sys.stderr)
        return 1
    merged = merge(docs)
    out = args.out or out_default
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    n_ev = len(merged["traceEvents"])
    print(f"trace_merge: {len(docs)} file(s), {n_ev} events -> {out}")
    reqdoc = request_rows(merged)
    n_req = len(reqdoc["otherData"]["requests"])
    if n_req:
        req_out = os.path.join(
            os.path.dirname(out) or ".", REQUESTS_BASENAME
        )
        tmp = req_out + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(reqdoc, f)
        os.replace(tmp, req_out)
        print(
            f"trace_merge: {n_req} traced request(s) -> {req_out}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
