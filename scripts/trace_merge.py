#!/usr/bin/env python
"""Merge per-host Chrome trace files into one Perfetto timeline.

Every tpufw process writes its own span trace (``trace.json`` from the
trainer, ``trace-p{N}.json`` from pipeline stages, ``trace-serve.json``
from the serving loop) with timestamps on its process-local
``perf_counter`` clock — epoch-arbitrary, so side-by-side loading in
Perfetto shows unrelated time axes. Each file also records its
run-start wall clock (``otherData.wall_epoch_s``, stamped when the
tracer was created). This script uses that anchor to shift every
file's events onto one shared axis (the earliest host is t=0), remaps
pids so hosts get separate tracks, and writes a single merged
Perfetto-loadable document.

Alignment is wall-clock quality, not PTP: good to NTP skew (typically
low milliseconds on a cluster), which is enough to see cross-host
stalls, stragglers, and lock-step barriers at step granularity.

Usage:
    python scripts/trace_merge.py <telemetry_dir>            # glob trace*.json
    python scripts/trace_merge.py a.json b.json -o out.json  # explicit files

Torn or unparsable files (a host died mid-write) are skipped with a
warning; the merge proceeds with whatever loads.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional, Tuple

MERGED_BASENAME = "trace-merged.json"


def discover(path: str) -> List[str]:
    """Trace files in a telemetry dir: trace.json, trace-p*.json,
    trace-serve.json — everything matching trace*.json except a
    previous merge output."""
    hits = sorted(glob.glob(os.path.join(path, "trace*.json")))
    return [h for h in hits if os.path.basename(h) != MERGED_BASENAME]


def load_trace(path: str) -> Optional[dict]:
    """One trace document, or None (with a stderr warning) when the
    file is torn, truncated, or not a trace."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        print(f"trace_merge: skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        print(
            f"trace_merge: skipping {path}: no traceEvents list",
            file=sys.stderr,
        )
        return None
    return doc


def _anchor(doc: dict) -> Optional[float]:
    other = doc.get("otherData")
    if isinstance(other, dict):
        w = other.get("wall_epoch_s")
        if isinstance(w, (int, float)):
            return float(w)
    return None


def merge(
    docs: List[Tuple[str, dict]],
) -> dict:
    """Clock-align and combine trace documents.

    ``docs`` is [(source_path, doc), ...]. The earliest
    ``wall_epoch_s`` across inputs becomes the merged t=0; each file's
    events shift by (its anchor - earliest) in microseconds. Files
    missing the anchor (pre-PR-9 traces) merge unshifted at t=0 with a
    warning. Each file gets its own pid so hosts land on separate
    Perfetto tracks regardless of what pid they recorded."""
    anchors = [_anchor(doc) for _, doc in docs]
    known = [a for a in anchors if a is not None]
    base = min(known) if known else 0.0
    events: List[dict] = []
    dropped_total = 0
    for idx, ((path, doc), anchor) in enumerate(zip(docs, anchors)):
        if anchor is None:
            print(
                f"trace_merge: {path} has no wall_epoch_s anchor; "
                "merging unshifted",
                file=sys.stderr,
            )
        shift_us = ((anchor - base) * 1e6) if anchor is not None else 0.0
        name = os.path.splitext(os.path.basename(path))[0]
        for ev in doc["traceEvents"]:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = idx
            if ev.get("ph") == "M":
                # Keep one process_name row per source file; qualify
                # it so "trainer" from two hosts stays tellable-apart.
                if ev.get("name") == "process_name":
                    orig = (ev.get("args") or {}).get("name", "")
                    label = f"{name}:{orig}" if orig else name
                    ev["args"] = {"name": label}
            elif "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            events.append(ev)
        other = doc.get("otherData")
        if isinstance(other, dict):
            dropped_total += int(other.get("dropped_events", 0) or 0)
    # Metadata first, then by aligned timestamp: Perfetto tolerates any
    # order, but a sorted merge makes the cross-host interleaving
    # checkable by eye (and by the tests).
    events.sort(
        key=lambda e: (0, 0.0) if e.get("ph") == "M" else (1, e.get("ts", 0.0))
    )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_epoch_s": base,
            "merged_from": [os.path.basename(p) for p, _ in docs],
            "dropped_events": dropped_total,
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "inputs",
        nargs="+",
        help="telemetry dir (globbed for trace*.json) or trace files",
    )
    ap.add_argument(
        "-o",
        "--out",
        default="",
        help=f"output path (default: <dir>/{MERGED_BASENAME})",
    )
    args = ap.parse_args(argv)

    files: List[str] = []
    out_default = MERGED_BASENAME
    for inp in args.inputs:
        if os.path.isdir(inp):
            files.extend(discover(inp))
            out_default = os.path.join(inp, MERGED_BASENAME)
        else:
            files.append(inp)
    if not files:
        print("trace_merge: no trace files found", file=sys.stderr)
        return 1
    docs = [(p, d) for p in files for d in [load_trace(p)] if d is not None]
    if not docs:
        print("trace_merge: no loadable trace files", file=sys.stderr)
        return 1
    merged = merge(docs)
    out = args.out or out_default
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    os.replace(tmp, out)
    n_ev = len(merged["traceEvents"])
    print(f"trace_merge: {len(docs)} file(s), {n_ev} events -> {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
