"""CI smoke for the load observatory: one full CLOSED autoscaling
cycle — measured burn rate in, replica membership change out — on a
real CPU gang in a single process.

A 3-role serving gang (llama3_tiny random init behind LocalReplica +
RouterServer) runs a seeded MMPP burst mix through the real HTTP
surface via tpufw.load's ReplayClient. The "burst" tenant carries an
impossibly tight per-token target (0.1 µs), so every burst request
violates deterministically and the fast/slow burn-rate pair — on
compressed 4s/12s windows — pegs at 1/(1−goal) = 100. What must
hold, in causal order:

- pre-traffic sweep: both roles live, no alerts;
- burst replay lands real load-trace.jsonl records and the
  re-aggregated ``tpufw_fleet_slo_burn_rate`` crosses the pair →
  ``load_tok_burn`` fires → ScalingRecommender emits ONE decision
  (decode +1) → the subscribed GangExecutor spawns a REAL decode
  engine, registers it with the router (membership visible in
  /healthz), and stamps a ``scale_action`` add event carrying the
  burn rate at decision time;
- recovery: the burst tenant's target is relaxed (standing in for
  restored capacity — CPU latency is too noisy to assert the real
  thing), violations age out of the 4s window, good traffic lands,
  and ``poll_recovery()`` stamps ``scale_action`` recovered;
- scale-in: traffic stops, ``tpufw_fleet_requests_per_s`` falls to
  ~0, the idle rule fires after its hold, the recommender (cooldown
  elapsed) steps decode −1, and the executor drains + deregisters
  the replica IT spawned (the base gang is untouchable);
- the whole cycle completes in < 90 s, obs_summary digests the dir
  (per-rung table + scale-action timeline), and a torn trace tail
  degrades gracefully.

Exit 0 on success. Honors TPUFW_LOAD_DIR so CI can upload the trace,
series, events, and decision artifacts.
"""

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

PAGE = 16
CYCLE_BUDGET_S = 90.0


def _post(base: str, body: dict) -> dict:
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=600) as resp:
        return json.loads(resp.read())


def main() -> int:
    import jax
    import jax.numpy as jnp

    from tpufw.infer import SamplingConfig
    from tpufw.load import (
        GangExecutor, MixConfig, ReplayClient, TraceWriter,
        read_trace, schedule,
    )
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.obs import fleet
    from tpufw.obs.events import EventLog, read_events
    from tpufw.obs.registry import Registry
    from tpufw.obs.slo import SloTracker
    from tpufw.serve.roles import DecodeEngine, PrefillEngine
    from tpufw.serve.router import LocalReplica, RouterServer
    from tpufw.workloads.env import env_opt_str

    t_cycle0 = time.monotonic()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest = os.path.join(
        repo, "deploy", "manifests", "13-serve-disagg-v5e8-jobset.yaml"
    )
    fdir = env_opt_str("load_dir") or tempfile.mkdtemp(
        prefix="tpufw-load-smoke-"
    )
    os.makedirs(fdir, exist_ok=True)

    failures: list = []

    def check(ok: bool, what: str) -> None:
        print(("ok: " if ok else "FAILED: ") + what)
        if not ok:
            failures.append(what)

    # ---- the gang -------------------------------------------------
    greedy = SamplingConfig(temperature=0.0)
    cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3_tiny"].decode_config(), max_seq_len=64
    )
    model = Llama(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    events = EventLog(os.path.join(fdir, fleet.EVENTS_FILENAME))
    common = dict(sampling=greedy, page=PAGE, kv_quant="int8")
    pe = PrefillEngine(model, params, n_slots=2, **common)
    de = DecodeEngine(model, params, n_slots=4, chunk=2, **common)
    reg = Registry()
    # Generous defaults, one poisoned tenant: every "burst" request
    # (max_new >= 3, so per-token latency is judged) misses the 0.1 µs
    # tok target by construction — the deterministic CPU stand-in for
    # a genuinely overloaded pool. Compressed 4s/12s windows keep the
    # whole burn->recover cycle inside the CI budget.
    slo = SloTracker(
        reg, events, ttft_ms=60000.0, tok_ms=60000.0, goal=0.99,
        windows=(4.0, 12.0), tenants={"burst": (60000.0, 0.0001)},
    )
    router = RouterServer(
        [LocalReplica("prefill-0", pe)], [LocalReplica("decode-0", de)],
        port=0, page=PAGE, events=events, registry=reg, slo=slo,
    )
    base = f"http://127.0.0.1:{router.port}"

    # ---- observatory + the closed loop ----------------------------
    store = fleet.SeriesStore(
        os.path.join(fdir, fleet.SERIES_FILENAME), max_records=4096
    )
    try:
        recommender = fleet.ScalingRecommender(
            fdir, manifest, cooldown_s=3.0, events=events
        )
        rules = (
            fleet.BurnRateRule(
                name="load_tok_burn", metric="tok",
                fast_window="4s", slow_window="12s",
                severity="page", scale="decode:+1",
            ),
            # Scale-in signal: requests_per_s derives from the sweep-
            # over-sweep counter delta, so it is absent pre-traffic
            # (no instance -> no pending), high under the burst, and
            # ~0 two sweeps after traffic stops.
            fleet.AlertRule(
                name="load_idle_traffic",
                series="tpufw_fleet_requests_per_s",
                op="<", threshold=0.05, for_s=2.0,
                severity="info", scale="decode:-1",
            ),
        )
        collector = fleet.FleetCollector(
            [
                fleet.Target("router", "router", router.render_metrics),
            ],
            store,
            events=events,
            rules=rules,
            recommender=recommender,
            health_fn=router.health,
        )
    except BaseException:
        store.close()  # wiring raising must not strand the handle
        raise

    def spawn_decode(name: str):
        # jit cache is process-wide and warm, so the new engine joins
        # in milliseconds — the CPU analog of a pod passing readiness.
        return LocalReplica(
            name, DecodeEngine(model, params, n_slots=4, chunk=2,
                               **common)
        )

    executor = GangExecutor(
        router, spawn={"decode": spawn_decode}, events=events,
        slo=slo, burn_window="4s",
    )
    executor.subscribe(recommender)

    def decode_count() -> int:
        return sum(
            1 for r in router.health()["replicas"].values()
            if r["role"] == "decode"
        )

    # ---- warm the jit caches under the generous default tenant ----
    body = _post(base, {"prompt": [3, 5, 7, 9], "max_new": 6,
                        "tenant": "default"})
    check(len(body.get("tokens", [])) == 6, "warmup request served")

    # ---- sweep 1: pre-traffic baseline ----------------------------
    derived0 = collector.scrape_once()
    check(
        derived0.get('tpufw_fleet_replicas{role="prefill"}') == 1.0
        and derived0.get('tpufw_fleet_replicas{role="decode"}') == 1.0,
        "sweep 1 sees both roles live",
    )
    ev_path = os.path.join(fdir, fleet.EVENTS_FILENAME)
    check(
        not [e for e in read_events(ev_path)
             if e.get("kind") == "fleet_alert"],
        "no alerts before traffic",
    )

    # ---- burst: seeded MMPP mix through the real HTTP surface -----
    events.emit("load_phase", phase="burst")
    slo.set_phase("burst")
    mix = MixConfig(
        seed=20, process="mmpp", rate_rps=5.0, duration_s=2.5,
        tenants=(("burst", 1.0),),
        prompt_len_base=8, prompt_len_cap=24,
        prefix_len=8, n_prefixes=2,
        max_new_base=6, max_new_cap=8,
        session_ratio=0.2, mmpp_burst_factor=4.0, mmpp_dwell_s=0.8,
    )
    trace = TraceWriter(os.path.join(fdir, "load-trace.jsonl"))
    try:
        client = ReplayClient(base, trace, threads=4, rung=0,
                              offered_rps=mix.rate_rps)
        summary = client.run(schedule(mix))
        check(
            summary["completed"] > 0,
            f"burst replay served through the router ({summary})",
        )

        # ---- sweep 2: burn crosses the pair -> decision -> scale-up ---
        derived1 = collector.scrape_once()
        fast = derived1.get(
            'tpufw_fleet_slo_burn_rate{metric="tok",tenant="burst",window="4s"}'
        )
        slow = derived1.get(
            'tpufw_fleet_slo_burn_rate{metric="tok",tenant="burst",window="12s"}'
        )
        check(
            fast is not None and fast > 14.4
            and slow is not None and slow > 6.0,
            f"burn rate crossed the fast/slow pair (4s={fast}, 12s={slow})",
        )
        check(decode_count() == 2, "executor scaled the decode pool up")
        adds = [e for e in read_events(ev_path)
                if e.get("kind") == "scale_action"
                and e.get("action") == "add"]
        check(
            len(adds) == 1 and adds[0]["pool"] == "decode"
            and adds[0].get("burn", 0.0) > 14.4,
            f"scale_action add carries burn-rate-at-decision ({adds})",
        )
        check(
            reg.counter("tpufw_router_replica_changes_total").value(
                role="decode", op="add"
            ) == 1.0,
            "membership change counted on the router",
        )
        spawned = adds[0]["replica"] if adds else ""

        # ---- recovery: capacity "restored", burn falls under 1 --------
        # Relaxing the tenant target stands in for restored capacity —
        # asserting a real CPU latency drop from +1 replica would flake.
        slo.tenants["burst"] = (60000.0, 60000.0)
        time.sleep(4.2)  # violations age out of the 4s fast window
        for i in range(2):
            _post(base, {"prompt": [11 + i, 13, 17], "max_new": 6,
                         "tenant": "burst"})
        recovered = executor.poll_recovery()
        check(
            recovered is not None
            and recovered["action"] == "recovered"
            and recovered["replica"] == spawned
            and recovered.get("burn", 1.0) < 1.0,
            f"burn recovery observed and linked to the decision "
            f"({recovered})",
        )

        # ---- scale-in: idle rule -> decision -> drain + deregister ----
        events.emit("load_phase", phase="idle")
        slo.set_phase("")
        deadline = time.monotonic() + 30.0
        while decode_count() > 1 and time.monotonic() < deadline:
            collector.scrape_once()
            time.sleep(0.7)
        check(decode_count() == 1, "idle cooldown scaled the pool back in")
        removes = [e for e in read_events(ev_path)
                   if e.get("kind") == "scale_action"
                   and e.get("action") == "remove"]
        check(
            len(removes) == 1 and removes[0]["replica"] == spawned,
            f"executor drained and removed ITS replica, not the base gang "
            f"({removes})",
        )
        decisions = sorted(
            f for f in os.listdir(fdir)
            if f.startswith("fleet-rec-") and f.endswith(".json")
        )
        check(
            len(decisions) == 2,
            f"one decision up, one decision down ({decisions})",
        )

        # ---- the causal chain, reconstructed from the event log alone -
        kinds = [
            (e["kind"], e.get("action") or e.get("state") or e.get("phase"))
            for e in read_events(ev_path)
            if e.get("kind") in (
                "fleet_alert", "fleet_recommendation", "scale_action",
                "load_phase",
            )
        ]
        want = [
            ("load_phase", "burst"),
            ("fleet_alert", "firing"),
            ("fleet_recommendation", None),
            ("scale_action", "add"),
            ("scale_action", "recovered"),
            ("load_phase", "idle"),
            ("scale_action", "remove"),
        ]
        it = iter(kinds)
        ordered = all(
            any(k == wk and (wa is None or a == wa) for k, a in it)
            for wk, wa in want
        )
        check(ordered, f"event log tells the full causal story ({kinds})")

    finally:
        trace.close()

    # ---- trace file: real records, torn tail degrades -------------
    trace_path = os.path.join(fdir, "load-trace.jsonl")
    n_recs = len(read_trace(trace_path))
    check(
        n_recs == summary["offered"],
        f"every burst request landed a trace record ({n_recs})",
    )
    with open(trace_path, "a", encoding="utf-8") as f:
        f.write('{"ts_offered": 9e9, "tenant": "to')  # SIGKILL mid-write
    check(
        len(read_trace(trace_path)) == n_recs,
        "torn trace tail drops only the torn line",
    )

    # ---- digest ---------------------------------------------------
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "obs_summary.py"),
         fdir],
        capture_output=True, text=True, timeout=120,
    )
    print(proc.stdout, end="")
    check(
        proc.returncode == 0 and "load observatory" in proc.stdout
        and "scale actions" in proc.stdout,
        "obs_summary digests the load dir (rung table + timeline)",
    )

    cycle_s = time.monotonic() - t_cycle0
    check(
        cycle_s < CYCLE_BUDGET_S,
        f"full closed cycle in {cycle_s:.1f}s < {CYCLE_BUDGET_S:.0f}s",
    )

    executor.close()
    store.close()
    events.close()
    router.close()
    if failures:
        print(f"load-smoke FAILED ({len(failures)} check(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("load-smoke OK: burst -> burn -> recommendation -> scale-up "
          "-> recovery -> idle -> scale-down, closed end to end in "
          f"{cycle_s:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
