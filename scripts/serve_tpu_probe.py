"""The serving stack end-to-end on the real chip: _Server (warmup,
continuous batching, unrolled decode default) + live HTTP requests.
Records warmup time, single-request latency, coalesced-batch
throughput, and a streamed request, to
docs/evidence/SERVE_TPU_r5.jsonl."""
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "SERVE_TPU_r5.jsonl",
)
_TAGS: dict = {}


def emit(row):
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def post(base, body, timeout=600):
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    return urllib.request.urlopen(req, timeout=timeout)


def main():
    os.environ.setdefault("TPUFW_MODEL", "llama3_600m_bench")
    os.environ.setdefault("TPUFW_MAX_NEW_TOKENS", "64")
    os.environ.setdefault("TPUFW_DECODE_DTYPE", "bfloat16")

    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax

    d = jax.devices()[0]
    _TAGS.update(platform=d.platform)
    emit({"event": "start", "kind": d.device_kind})

    from tpufw.workloads.serve import _Server

    t0 = time.perf_counter()
    srv = _Server(port=0, max_new_tokens=64)
    init_s = time.perf_counter() - t0
    emit({
        "case": "server_init_with_warmup",
        "seconds": round(init_s, 1),
        "model": "llama3_600m_bench (596M), bf16, unrolled default",
    })
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    deadline = time.time() + 30
    while not hasattr(srv, "httpd") and time.time() < deadline:
        time.sleep(0.05)
    base = f"http://127.0.0.1:{srv.port}"

    # 1. Single request, the warmed default bucket.
    prompt = list(range(1, 33))
    t0 = time.perf_counter()
    with post(base, {"prompts": [prompt], "max_new_tokens": 64}) as r:
        out = json.loads(r.read())
    dt = time.perf_counter() - t0
    emit({
        "case": "single_request_warm_bucket",
        "latency_s": round(dt, 3),
        "new_tokens": len(out["outputs"][0]),
        "tok_per_s": round(64 / dt, 1),
    })

    # 2. 16 concurrent requests -> coalesced ticks.
    results = []

    def one(i):
        t = time.perf_counter()
        with post(
            base,
            {"prompts": [[i + 1] * 32], "max_new_tokens": 64},
        ) as r:
            out = json.loads(r.read())
        results.append(
            (time.perf_counter() - t, out["batched_with"][0]
             if isinstance(out.get("batched_with"), list)
             else out.get("batched_with", 1))
        )

    threads = [
        threading.Thread(target=one, args=(i,)) for i in range(16)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    emit({
        "case": "concurrent_16",
        "wall_s": round(wall, 3),
        "throughput_tok_per_s": round(16 * 64 / wall, 1),
        "max_batched_with": max(b for _, b in results),
        "p50_latency_s": round(
            sorted(t for t, _ in results)[len(results) // 2], 3
        ),
    })

    # 3. Streamed request: time-to-first-chunk vs total.
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps({
            "prompts": [prompt], "max_new_tokens": 64, "stream": True,
        }).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    t0 = time.perf_counter()
    first = None
    n_events = 0
    with urllib.request.urlopen(req, timeout=600) as r:
        for line in r:
            if line.strip().startswith(b"data: "):
                n_events += 1
                if first is None:
                    first = time.perf_counter() - t0
    total = time.perf_counter() - t0
    emit({
        "case": "stream_request",
        "time_to_first_chunk_s": round(first, 3),
        "total_s": round(total, 3),
        "events": n_events,
    })

    # 4. Metrics surface sanity.
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    wanted = [
        ln for ln in text.splitlines()
        if ln.startswith("tpufw_serve_tokens_generated_total")
        or ln.startswith("tpufw_serve_ticks_total")
    ]
    emit({"case": "metrics", "lines": wanted})
    srv.httpd.shutdown()
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
