"""CI smoke for the global KV fabric: prefix-affinity routing and
zero-divergence session migration across a two-replica decode pool,
ganged in ONE process on CPU.

The replicas are real engines (tpufw.serve.roles, llama3_tiny random
init, int8 KV so quantized codes + scales cross every boundary); the
router talks to them through ``LocalReplica``, the same client
interface TcpReplica gives it in a cluster. Drain is invoked directly
(``DecodeEngine.drain()`` — the exact body the SIGTERM handler runs)
because killing the shared CI process would end the smoke too. What
must hold:

- prefix-affinity routing: after one piggybacked request builds a
  replica's radix trie, a COLD prompt sharing the prefix (different
  session, different tail) routes to THAT replica — even though pure
  occupancy scoring would pick the emptier peer — and its chunked
  prefill attaches the shared pages (pool.prefix_hits advances, and
  the router counts the steer on
  tpufw_router_prefix_affinity_hits_total);
- zero-divergence resumption: a sticky session decoding on replica A
  is drained mid-request (scale-in semantics); A exports the
  session's slot to the shared spill directory, the router re-homes
  the request onto surviving replica B through the normal splice
  path, and the client receives EXACTLY the token stream an
  undisturbed control run produces — plus ``resumed: true`` and the
  survivor's name;
- the drained replica leaves rotation (/healthz shows ``draining``)
  and the router's /metrics counts the re-home;
- the KV-fabric ledger digests: serve_spill + router_rehome events
  land in events-router.jsonl and obs_summary prints the kv fabric
  section.

Exit 0 on success; any assertion failure exits nonzero. Honors
TPUFW_TELEMETRY_DIR so CI can upload the artifacts.
"""

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

MAX_NEW = 6
RESUME_NEW = 24
PAGE = 16

# http: claims


def _post(base: str, body: dict):
    """(status, parsed-body, headers) — 4xx/5xx included, not raised."""
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def main() -> int:
    # wire: produces router-request
    # wire: consumes router-response via body
    import jax
    import jax.numpy as jnp

    from tpufw.infer import SamplingConfig
    from tpufw.infer.spill import SpillTier
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.obs.events import EventLog, read_events
    from tpufw.serve.roles import DecodeEngine, PrefillEngine
    from tpufw.serve.router import (
        LocalReplica,
        RouterPolicy,
        RouterServer,
    )

    greedy = SamplingConfig(temperature=0.0)
    cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3_tiny"].decode_config(), max_seq_len=64
    )
    model = Llama(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]

    from tpufw.workloads.env import env_opt_str

    tdir = env_opt_str("telemetry_dir") or tempfile.mkdtemp(
        prefix="tpufw-kv-smoke-"
    )
    os.makedirs(tdir, exist_ok=True)
    events = EventLog(os.path.join(tdir, "events-router.jsonl"))
    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        print(("ok: " if ok else "FAILED: ") + what)
        if not ok:
            failures.append(what)

    shared = list(range(40, 72))  # 32 tokens = 2 full trie pages

    # ---- prefix-affinity routing across a two-replica pool ----
    # No dedicated prefill replica: every request piggybacks, so the
    # serving replica's chunked prefill checkpoints the prompt into
    # its OWN trie — the state the affinity digests advertise.
    aff_dir = os.path.join(tdir, "spill-aff")
    pig = dict(
        n_slots=4, chunk=2, prefill_chunk_pages=1, piggyback=0.05,
        affinity_k=2, sampling=greedy, page=PAGE, kv_quant="int8",
        events=events,
    )
    de_a = DecodeEngine(
        model, params, spill=SpillTier(64, aff_dir), **pig
    )
    de_b = DecodeEngine(
        model, params, spill=SpillTier(64, aff_dir), **pig
    )
    aff_router = RouterServer(
        [],
        [LocalReplica("decode-a", de_a), LocalReplica("decode-b", de_b)],
        policy=RouterPolicy(affinity_k=2),
        port=0, page=PAGE, events=events, spill_dir=aff_dir,
    )
    abase = f"http://127.0.0.1:{aff_router.port}"
    status, warm, _h = _post(abase, {
        "prompt": shared + [7, 9], "max_new": MAX_NEW,
        "tenant": "smoke", "session": "aff0",
    })
    check(
        status == 200 and warm.get("piggyback") is True,
        f"warm request piggybacked onto {warm.get('replica')} "
        f"(got {status})",
    )
    first_home = warm.get("replica")
    status, body, _h = _post(abase, {
        "prompt": shared + [11, 3], "max_new": MAX_NEW,
        "tenant": "smoke", "session": "aff1",
    })
    check(
        status == 200 and body.get("replica") == first_home,
        "cold prompt sharing the prefix steered to the replica "
        f"holding it (got {body.get('replica')}, "
        f"trie home {first_home}) — occupancy alone would pick the "
        "emptier peer",
    )
    holder = de_a if first_home == "decode-a" else de_b
    check(
        holder.pool.prefix_hits >= 1,
        "affinity landed on a real trie hit "
        f"(prefix_hits={holder.pool.prefix_hits}, "
        f"prefix_misses={holder.pool.prefix_misses})",
    )
    with urllib.request.urlopen(abase + "/metrics", timeout=60) as resp:
        aff_metrics = resp.read().decode()
    aff_line = next(
        (
            line for line in aff_metrics.splitlines()
            if line.startswith("tpufw_router_prefix_affinity_hits_total")
        ),
        "",
    )
    check(
        aff_line and float(aff_line.split()[-1]) >= 1,
        f"router counted the affinity steer ({aff_line!r})",
    )
    aff_router.close()

    # ---- zero-divergence drain -> re-home -> resume ----
    # Control: an undisturbed run of the same prompt through fresh
    # engines (fresh prefill on purpose: a trie hit under int8
    # recomputes the suffix over dequantized KV, so only COLD-vs-COLD
    # prefills are comparable bit-for-bit).
    mig_prompt = shared + [7, 9]
    common = dict(sampling=greedy, page=PAGE, kv_quant="int8",
                  events=events)
    pe_ctl = PrefillEngine(model, params, n_slots=2, **common)
    de_ctl = DecodeEngine(model, params, n_slots=4, chunk=2, **common)
    ctl_router = RouterServer(
        [LocalReplica("prefill-0", pe_ctl)],
        [LocalReplica("decode-0", de_ctl)],
        port=0, page=PAGE, events=events,
    )
    status, ctl, _h = _post(
        f"http://127.0.0.1:{ctl_router.port}",
        {"prompt": mig_prompt, "max_new": RESUME_NEW, "tenant": "smoke"},
    )
    ctl_router.close()
    check(
        status == 200 and len(ctl.get("tokens", [])) == RESUME_NEW,
        f"control run decoded {RESUME_NEW} tokens (got {status})",
    )

    mig_dir = os.path.join(tdir, "spill-mig")
    pe_live = PrefillEngine(model, params, n_slots=2, **common)
    de_live_a = DecodeEngine(
        model, params, n_slots=4, chunk=2,
        spill=SpillTier(64, mig_dir), **common
    )
    de_live_b = DecodeEngine(
        model, params, n_slots=4, chunk=2,
        spill=SpillTier(64, mig_dir), **common
    )
    live_router = RouterServer(
        [LocalReplica("prefill-0", pe_live)],
        [
            LocalReplica("decode-a", de_live_a),
            LocalReplica("decode-b", de_live_b),
        ],
        port=0, page=PAGE, events=events, spill_dir=mig_dir,
    )
    lbase = f"http://127.0.0.1:{live_router.port}"
    result: dict = {}

    def _request():
        result["resp"] = _post(lbase, {
            "prompt": mig_prompt, "max_new": RESUME_NEW,
            "tenant": "smoke", "session": "mig",
        })

    t = threading.Thread(target=_request)
    t.start()
    # decode-a wins the tie-broken pick; drain it the moment the
    # session's slot is live (splice landed, decode chunks running —
    # on a cold replica the chunk compiles mid-request, so the window
    # is wide). Scale-in never waits for a quiet moment either.
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        with de_live_a._cv:
            busy = any(
                not j["done"] for j in de_live_a._jobs.values()
            )
        if busy:
            break
        time.sleep(0.002)
    check(busy, "session went live on decode-a before the drain")
    drained = de_live_a.drain()  # the SIGTERM handler's exact body
    t.join(timeout=600.0)
    status, body, _h = result.get("resp", (0, {}, None))
    check(
        "mig" in drained.get("sessions", []),
        f"drain exported the live session ({drained})",
    )
    check(
        status == 200 and body.get("resumed") is True
        and body.get("replica") == "decode-b",
        "request survived the drain: re-homed onto decode-b "
        f"(got {status}, resumed={body.get('resumed')}, "
        f"replica={body.get('replica')})",
    )
    check(
        body.get("tokens") == ctl.get("tokens"),
        "ZERO token divergence vs the undisturbed control "
        f"(got {body.get('tokens')} vs {ctl.get('tokens')})",
    )
    check(
        de_live_a.sessions_drained == 1
        and de_live_b.sessions_resumed == 1,
        "both engines account the migration "
        f"(drained={de_live_a.sessions_drained}, "
        f"resumed={de_live_b.sessions_resumed})",
    )
    check(
        de_live_b.pool.allocator.in_use == 0,
        "survivor returned every page after retire "
        f"(in_use={de_live_b.pool.allocator.in_use})",
    )
    with urllib.request.urlopen(lbase + "/healthz", timeout=60) as resp:
        health = json.loads(resp.read())
    check(
        health["replicas"]["decode-a"].get("draining") is True,
        "/healthz shows decode-a out of rotation (draining)",
    )
    with urllib.request.urlopen(lbase + "/metrics", timeout=60) as resp:
        metrics = resp.read().decode()
    check(
        "tpufw_router_session_rehomes_total 1" in metrics,
        "router counted the re-home on /metrics",
    )
    live_router.close()

    # ---- KV-fabric ledger digests ----
    ev = read_events(os.path.join(tdir, "events-router.jsonl"))
    spills = [e for e in ev if e.get("kind") == "serve_spill"]
    rehomes = [e for e in ev if e.get("kind") == "router_rehome"]
    check(
        any(
            e.get("entry") == "session" and e.get("direction") == "out"
            for e in spills
        ),
        f"drain emitted the session spill event ({len(spills)} "
        "serve_spill record(s))",
    )
    check(
        len(rehomes) == 1 and rehomes[0].get("replica") == "decode-b",
        f"router emitted the re-home event ({rehomes})",
    )
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "obs_summary.py"),
         tdir],
        capture_output=True, text=True, timeout=120,
    )
    print(proc.stdout, end="")
    check(
        proc.returncode == 0 and "kv fabric" in proc.stdout
        and "re-home" in proc.stdout,
        "obs_summary digests the kv-fabric ledger",
    )

    events.close()
    if failures:
        print(f"kv-smoke FAILED ({len(failures)} check(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("kv-smoke OK: affinity steered the shared prefix home, and "
          "a drained replica's session resumed with zero divergence")
    return 0


if __name__ == "__main__":
    sys.exit(main())
