"""Sliding-window flash attention on real Mosaic (r4 landed the
in-kernel window masks with CPU-interpreter tests only). Trains the
bench model with a Mistral-style 1024-token window at seq 2048 and
checks (a) it compiles+runs on the chip, (b) the window costs less
than full causal at long seq (8192, window 1024 - the case the
skip-block logic exists for)."""
import dataclasses
import sys

sys.path.insert(0, "/root/repo")
from tpufw.utils.profiling import enable_compile_cache

enable_compile_cache()

from tpufw.configs.presets import bench_model_config
from tpufw.mesh import MeshConfig
from tpufw.models import Llama
from tpufw.train import Trainer, TrainerConfig, synthetic_batches

for tag, seq, batch, window in (
    ("w1024_seq2048", 2048, 16, 1024),
    ("full_seq8192", 8192, 4, None),
    ("w1024_seq8192", 8192, 4, 1024),
):
    cfg = dataclasses.replace(
        bench_model_config(),
        max_seq_len=seq,
        sliding_window=window,
        remat_policy="attn_out" if seq == 2048 else "nothing",
    )
    trainer = Trainer(
        Llama(cfg),
        TrainerConfig(
            batch_size=batch, seq_len=seq, total_steps=6, lr=1e-4,
            warmup_steps=2, loss_chunk_size=512, log_every=1,
            sync_every=4,
        ),
        MeshConfig(),
    )
    trainer.init_state()
    hist = trainer.run(
        synthetic_batches(batch, seq, cfg.vocab_size),
        model_flops_per_token=cfg.flops_per_token(seq - 1),
    )
    print("WINDOW_PROBE", tag,
          [round(m.tokens_per_sec_per_chip, 1) for m in hist],
          [round(m.mfu, 4) for m in hist])
