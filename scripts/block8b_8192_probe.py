"""Standalone probe for the block8b seq-8192 compile-helper failure
(BENCH_r5_watch*.json: HTTP 500 at every batch). Runs the exact bench
tier config at batch 1 and lets the full compile error reach stderr,
which the bench's 400-char truncation cuts off."""
import dataclasses
import sys

sys.path.insert(0, "/root/repo")

from tpufw.utils.profiling import enable_compile_cache

enable_compile_cache()

from tpufw.mesh import MeshConfig
from tpufw.models import LLAMA_CONFIGS, Llama
from tpufw.train import Trainer, TrainerConfig, synthetic_batches

cfg = dataclasses.replace(
    LLAMA_CONFIGS["llama3_8b"],
    vocab_size=2048,
    n_layers=1,
    max_seq_len=8192,
    remat_policy="attn_out",
    attention_backend="flash",
)
trainer = Trainer(
    Llama(cfg),
    TrainerConfig(
        batch_size=1, seq_len=8192, total_steps=3, lr=1e-4,
        warmup_steps=2, loss_chunk_size=512, log_every=1, sync_every=2,
    ),
    MeshConfig(),
)
trainer.init_state()
hist = trainer.run(
    synthetic_batches(1, 8192, cfg.vocab_size),
    model_flops_per_token=cfg.flops_per_token(8191),
)
print("OK", [round(m.mfu, 4) for m in hist])
