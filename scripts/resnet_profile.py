#!/usr/bin/env python
"""ResNet-50 MFU component profile (VERDICT r3 item 3 / weak 3).

Round-3 record: 15.8% MFU best-case (2524 img/s, b256, bf16 BN) with
"conv input/filter gradients identified as the remaining slow path" —
analysis done, optimization not. This script measures the pieces so the
optimization is aimed, one JSON line per experiment:

  1. train step    — the bench tier (b256, bf16 BN): the reference point
  2. forward only  — inference pass: how much of the step is backward
  3. batch sweep   — 128 / 512: does conv-backward efficiency scale
  4. conv micro    — fwd / input-grad / filter-grad TFLOP/s for the
                     three canonical ResNet conv shapes (7x7s2 stem,
                     3x3 mid, 1x1 wide), bf16 vs f32: WHERE the
                     backward cliff is, layout NHWC (XLA-native)

Timing is value-fetch based (np.asarray). Run from /root/repo on a
healthy TPU:  python scripts/resnet_profile.py   (--smoke for a tiny
CPU wiring check). Results append to
docs/evidence/RESNET_PROFILE_r5.jsonl as they complete.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "RESNET_PROFILE_r5.jsonl",
)
SMOKE = "--smoke" in sys.argv
# Every row carries the platform so a --smoke wiring check appended to
# the same evidence file can never be mistaken for hardware numbers.
_TAGS: dict = {}


def emit(row: dict) -> None:
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main() -> int:
    if SMOKE:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    if SMOKE:
        jax.config.update("jax_platforms", "cpu")

    from tpufw.mesh import MeshConfig
    from tpufw.models import ResNetConfig, resnet50
    from tpufw.train import (
        VisionTrainer,
        VisionTrainerConfig,
        synthetic_images,
    )

    devices = jax.devices()
    _TAGS.update(platform=devices[0].platform, smoke=SMOKE)
    emit({"event": "start", "kind": devices[0].device_kind})

    img = 64 if SMOKE else 224
    classes = 10 if SMOKE else 1000
    flops_per_image = ResNetConfig().flops_per_image(img)
    peak = 197e12 if not SMOKE else 1e12  # v5e bf16

    # 1 + 3. Train step at batch sweep through the bench path.
    for batch in ([8] if SMOKE else [128, 256, 512]):
        try:
            vt = VisionTrainer(
                resnet50(classes, norm_dtype=jnp.bfloat16),
                VisionTrainerConfig(
                    batch_size=batch, image_size=img,
                    total_steps=9, sync_every=4,
                ),
                MeshConfig(),
            )
            vt.init_state()
            hist = vt.run(
                synthetic_images(batch, img, classes, on_device=True),
                flops_per_image=flops_per_image,
            )
            steady = [m for m in hist if m.step > 1]
            import statistics

            emit({
                "case": f"train_b{batch}",
                "img_per_s": round(statistics.median(
                    m.tokens_per_sec_per_chip for m in steady
                ), 1),
                "mfu": round(statistics.median(
                    m.mfu for m in steady
                ), 4),
            })
            del vt
        except Exception as e:  # noqa: BLE001
            emit({"case": f"train_b{batch}",
                  "error": f"{type(e).__name__}: {e}"[:300]})

    # 2. Forward only (same model/batch as the b256 tier).
    batch = 8 if SMOKE else 256
    model = resnet50(classes, norm_dtype=jnp.bfloat16)
    x = jnp.ones((batch, img, img, 3), jnp.bfloat16)
    variables = jax.jit(
        lambda k, x: model.init(k, x, train=False)
    )(jax.random.key(0), x)

    fwd = jax.jit(
        lambda v, x: model.apply(v, x, train=False)
    )
    np.asarray(fwd(variables, x))  # compile+warm
    t0 = time.perf_counter()
    np.asarray(fwd(variables, x))
    dt = time.perf_counter() - t0
    emit({
        "case": "forward_only", "batch": batch,
        "img_per_s": round(batch / dt, 1),
        # Forward is ~1/3 of train FLOPs.
        "mfu_fwd": round(
            (flops_per_image / 3.0) * batch / dt / peak, 4
        ),
    })

    # 4. Conv microbench: canonical shapes, fwd + both grads.
    from functools import partial

    def conv(x, w, stride):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    shapes = [
        # (name, H, Cin, Cout, k, stride) at the profile batch
        ("stem7x7s2", img, 3, 64, 7, 2),
        ("mid3x3", img // 8, 128, 128, 3, 1),
        ("wide1x1", img // 16, 1024, 256, 1, 1),
    ]
    for dt_name, dtype in (("bf16", jnp.bfloat16), ("f32", jnp.float32)):
        for name, h, cin, cout, k, stride in shapes:
            x = jnp.ones((batch, h, h, cin), dtype)
            w = jnp.ones((k, k, cin, cout), dtype)
            flops = (
                2.0 * batch * (h / stride) ** 2 * cin * cout * k * k
            )

            def loss(x, w, stride=stride):
                return jnp.sum(conv(x, w, stride).astype(jnp.float32))

            cases = {
                "fwd": jax.jit(partial(conv, stride=stride)),
                "dx": jax.jit(jax.grad(loss, argnums=0)),
                "dw": jax.jit(jax.grad(loss, argnums=1)),
            }
            for kind, fn in cases.items():
                try:
                    np.asarray(fn(x, w))  # compile+warm
                    t0 = time.perf_counter()
                    np.asarray(fn(x, w))
                    d = time.perf_counter() - t0
                    emit({
                        "case": f"conv_{name}_{kind}_{dt_name}",
                        "tflop_per_s": round(flops / d / 1e12, 2),
                        "ms": round(d * 1e3, 2),
                    })
                except Exception as e:  # noqa: BLE001
                    emit({
                        "case": f"conv_{name}_{kind}_{dt_name}",
                        "error": f"{type(e).__name__}: {e}"[:200],
                    })
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
