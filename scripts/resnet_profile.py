#!/usr/bin/env python
"""ResNet-50 MFU component profile (VERDICT r3 item 3 / weak 3).

Round-3 record: 15.8% MFU best-case (2524 img/s, b256, bf16 BN) with
"conv input/filter gradients identified as the remaining slow path" —
analysis done, optimization not. This script measures the pieces so the
optimization is aimed, one JSON line per experiment:

  1. train step    — the bench tier (b256, bf16 BN): the reference point
  2. forward only  — inference pass: how much of the step is backward
  3. batch sweep   — 128 / 512: does conv-backward efficiency scale
  4. conv micro    — fwd / input-grad / filter-grad TFLOP/s for the
                     three canonical ResNet conv shapes (7x7s2 stem,
                     3x3 mid, 1x1 wide), bf16 vs f32: WHERE the
                     backward cliff is, layout NHWC (XLA-native)

Timing is value-fetch based (np.asarray). Run from /root/repo on a
healthy TPU:  python scripts/resnet_profile.py   (--smoke for a tiny
CPU wiring check). Results append to
docs/evidence/RESNET_PROFILE_r5.jsonl as they complete.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "RESNET_PROFILE_r5.jsonl",
)
SMOKE = "--smoke" in sys.argv
# Every row carries the platform so a --smoke wiring check appended to
# the same evidence file can never be mistaken for hardware numbers.
_TAGS: dict = {}


def emit(row: dict) -> None:
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def _forward_only(jax, jnp, np, batch, img, classes,
                  flops_per_image, peak):
    """Kf serialized forwards inside one jit, ONE scalar fetched (the
    v1 single-call number was ~70 ms round-trip + 0.5 MB logits
    transfer on top of the actual forward; see the conv-micro
    methodology note in main)."""
    import time

    from tpufw.models import resnet50

    model = resnet50(classes, norm_dtype=jnp.bfloat16)
    x = jnp.ones((batch, img, img, 3), jnp.bfloat16)
    variables = jax.jit(
        lambda k, x: model.init(k, x, train=False)
    )(jax.random.key(0), x)
    Kf = 2 if SMOKE else 8

    def fwd_chain(v, x):
        acc = jnp.float32(0.0)
        for _ in range(Kf):
            s = jnp.sum(
                model.apply(v, x, train=False).astype(jnp.float32)
            )
            acc = acc + s
            x = x + (s * jnp.float32(1e-38)).astype(x.dtype)
        return acc

    fwd = jax.jit(fwd_chain)
    np.asarray(fwd(variables, x))  # compile+warm
    t0 = time.perf_counter()
    np.asarray(fwd(variables, x))
    dt = (time.perf_counter() - t0) / Kf
    emit({
        "case": "forward_only", "batch": batch,
        "img_per_s": round(batch / dt, 1),
        # Forward is ~1/3 of train FLOPs.
        "mfu_fwd": round(
            (flops_per_image / 3.0) * batch / dt / peak, 4
        ),
    })


def main() -> int:
    if SMOKE:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    if SMOKE:
        jax.config.update("jax_platforms", "cpu")

    from tpufw.mesh import MeshConfig
    from tpufw.models import ResNetConfig, resnet50
    from tpufw.train import (
        VisionTrainer,
        VisionTrainerConfig,
        synthetic_images,
    )

    devices = jax.devices()
    _TAGS.update(platform=devices[0].platform, smoke=SMOKE)
    emit({"event": "start", "kind": devices[0].device_kind})

    img = 64 if SMOKE else 224
    classes = 10 if SMOKE else 1000
    flops_per_image = ResNetConfig().flops_per_image(img)
    peak = 197e12 if not SMOKE else 1e12  # v5e bf16

    # TPUFW_RESNET_MICRO_ONLY=1: skip the train/forward sections (e.g.
    # re-running only a fixed conv-micro methodology on banked tiers).
    from tpufw.workloads.env import env_bool

    micro_only = env_bool("resnet_micro_only", False)

    # 1 + 3. Train step at batch sweep through the bench path.
    for batch in ([] if micro_only else [8] if SMOKE else
                  [128, 256, 512]):
        try:
            vt = VisionTrainer(
                resnet50(classes, norm_dtype=jnp.bfloat16),
                VisionTrainerConfig(
                    batch_size=batch, image_size=img,
                    total_steps=9, sync_every=4,
                ),
                MeshConfig(),
            )
            vt.init_state()
            hist = vt.run(
                synthetic_images(batch, img, classes, on_device=True),
                flops_per_image=flops_per_image,
            )
            steady = [m for m in hist if m.step > 1]
            import statistics

            emit({
                "case": f"train_b{batch}",
                "img_per_s": round(statistics.median(
                    m.tokens_per_sec_per_chip for m in steady
                ), 1),
                "mfu": round(statistics.median(
                    m.mfu for m in steady
                ), 4),
            })
            del vt
        except Exception as e:  # noqa: BLE001
            emit({"case": f"train_b{batch}",
                  "error": f"{type(e).__name__}: {e}"[:300]})

    # 2. Forward only (same model/batch as the b256 tier).
    batch = 8 if SMOKE else 256
    if not micro_only:
        _forward_only(jax, jnp, np, batch, img, classes,
                      flops_per_image, peak)

    # 4. Conv microbench: canonical shapes, fwd + both grads.
    #
    # Methodology v3. v1 (single dispatch + np.asarray of the raw conv
    # output) measured the tunnel, not the chip: big outputs (stem fwd,
    # 411 MB) were transfer-bound (72 s!) and tiny outputs sat at the
    # dispatch+fetch round trip (~70 ms) regardless of shape. v2
    # (K=16 Python-unrolled serial iterations, scalar fetch, null
    # subtraction) fixed the transfer but not the VARIANCE: the round
    # trip swings 26-107 ms between calls, so fast cases measured
    # d - null <= 0 and one stem row read an impossible 349 TFLOP/s
    # (> the 197 peak). v3: a lax.fori_loop chain (constant compile
    # cost) with a FLOP-targeted per-case K, sized so device time
    # >= ~300 ms at 25% efficiency — round-trip noise becomes < 15%.
    # bf16 only (the production dtype). Each iteration's scalar
    # perturbs the next iteration's input by scalar*1e-38 (numerically
    # a no-op at these magnitudes, but data-dependent, so the compiler
    # cannot CSE or reorder the K convs).
    target_flops = 2e10 if SMOKE else 8e12

    def conv(x, w, stride):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def timed_chain(step_fn, arr, k_iters):
        """Wall seconds for k_iters serial evaluations of
        step_fn(arr) inside one jit. Each iteration's scalar perturbs
        ONE element of the next iteration's input — data-dependent, so
        the compiler can neither CSE nor loop-hoist the k_iters
        evaluations, and O(1) bytes, so the perturbation itself is
        unmeasurable (v3 added the scalar to the FULL tensor, up to
        ~100 MB of extra HBM traffic per iteration on the big
        activations — tens of percent of bias on the fast convs)."""

        def body(_, carry):
            a, acc = carry
            s = step_fn(a)
            return (
                a.at[(0,) * a.ndim].add(
                    (s * jnp.float32(1e-38)).astype(a.dtype)
                ),
                acc + s,
            )

        def chain(a):
            _, acc = jax.lax.fori_loop(
                0, k_iters, body, (a, jnp.float32(0.0))
            )
            return acc

        fn = jax.jit(chain)
        np.asarray(fn(arr))  # compile+warm
        t0 = time.perf_counter()
        np.asarray(fn(arr))
        return time.perf_counter() - t0

    shapes = [
        # (name, H, Cin, Cout, k, stride) at the profile batch
        ("stem7x7s2", img, 3, 64, 7, 2),
        # Space-to-depth stem equivalent (MLPerf-style): s2d(2) folds
        # 224x224x3 -> 112x112x12 on the host/data side; the stem
        # becomes a stride-1 4x4x12 conv at the SAME output shape and
        # ~same FLOPs, but with 4x the MXU lane occupancy (Cin 12 vs 3).
        ("stem_s2d2_4x4", img // 2, 12, 64, 4, 1),
        ("mid3x3", img // 8, 128, 128, 3, 1),
        ("wide1x1", img // 16, 1024, 256, 1, 1),
    ]
    dtype, dt_name = jnp.bfloat16, "bf16"
    for name, h, cin, cout, k, stride in shapes:
        x = jnp.ones((batch, h, h, cin), dtype)
        w = jnp.ones((k, k, cin, cout), dtype)
        flops = 2.0 * batch * (h / stride) ** 2 * cin * cout * k * k
        k_iters = max(8, min(2048, int(target_flops / flops)))

        def fwd_step(x, w=w, stride=stride):
            return jnp.sum(conv(x, w, stride).astype(jnp.float32))

        # dx: the cotangent of a LINEAR op is x-independent, so a
        # grad-of-sum formulation is loop-invariant no matter how x is
        # perturbed (v3's dx cells were hoistable — review finding).
        # Take ONE vjp outside the loop and time the transposed conv
        # applied to a perturbed cotangent instead.
        y, conv_vjp = jax.vjp(
            lambda x, w=w, stride=stride: conv(x, w, stride), x
        )
        ct0 = jnp.ones_like(y)

        def dx_step(ct, conv_vjp=conv_vjp):
            return jnp.sum(conv_vjp(ct)[0].astype(jnp.float32))

        def dw_step(x, w=w, stride=stride):
            def loss(w):
                return jnp.sum(
                    conv(x, w, stride).astype(jnp.float32)
                )

            return jnp.sum(jax.grad(loss)(w).astype(jnp.float32))

        for kind, step_fn, arr in (
            ("fwd", fwd_step, x),
            ("dx", dx_step, ct0),
            ("dw", dw_step, x),
        ):
            try:
                d = timed_chain(step_fn, arr, k_iters)
                emit({
                    "case": f"conv_{name}_{kind}_{dt_name}",
                    "k_iters": k_iters,
                    "tflop_per_s": round(
                        k_iters * flops / d / 1e12, 2
                    ),
                    "ms_per_call": round(d / k_iters * 1e3, 3),
                    "raw_ms": round(d * 1e3, 2),
                })
            except Exception as e:  # noqa: BLE001
                emit({
                    "case": f"conv_{name}_{kind}_{dt_name}",
                    "error": f"{type(e).__name__}: {e}"[:200],
                })
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
