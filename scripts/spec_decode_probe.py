"""Speculative decoding on the real chip: component costs + realized
throughput with a random-weight draft.

Random weights make ACCEPTANCE adversarial (draft/target argmax
agreement over a 32k vocab is ~chance), so the realized tok/s here is
the implementation's floor, not a speedup claim. What the probe
actually pins on hardware:
  - draft-step and verify-step costs (time/iteration = k*draft +
    verify + host glue), measured through the REAL speculative path;
  - measured acceptance (stats emitted/iterations);
  - plain-decode tok/s on the same target for the break-even algebra:
    speculation wins when E[accepted+1] / iter_time > 1 / plain_step.
One JSON row per case to docs/evidence/SPEC_DECODE_r5.jsonl.
"""
import dataclasses
import json
import sys
import time

sys.path.insert(0, "/root/repo")

OUT = "/root/repo/docs/evidence/SPEC_DECODE_r5.jsonl"
_TAGS: dict = {}


def emit(row):
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main():
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax
    import numpy as np

    from tpufw.configs.presets import bench_model_config
    from tpufw.infer import (
        SamplingConfig,
        cast_decode_params,
        generate_text,
        speculative_generate_text,
    )
    from tpufw.models import Llama

    d = jax.devices()[0]
    _TAGS.update(platform=d.platform)
    emit({"event": "start", "kind": d.device_kind})

    b, prompt_len, new = 8, 128, 128
    tcfg = dataclasses.replace(
        bench_model_config().decode_config(),
        max_seq_len=prompt_len + new + 9,  # + k headroom for the spec verify window
    )
    target = Llama(tcfg)
    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, tcfg.vocab_size, prompt_len)]
        for _ in range(b)
    ]
    tparams = cast_decode_params(
        jax.jit(target.init)(
            jax.random.key(1),
            jax.numpy.zeros((1, prompt_len), jax.numpy.int32),
        )["params"]
    )
    # Small draft, same vocab/rope family: ~1/20 the target FLOPs.
    dcfg = dataclasses.replace(
        tcfg, d_model=512, n_layers=4, n_heads=8, n_kv_heads=4,
        head_dim=64, d_ff=1536,
    )
    draft = Llama(dcfg)
    dparams = cast_decode_params(
        jax.jit(draft.init)(
            jax.random.key(2),
            jax.numpy.zeros((1, prompt_len), jax.numpy.int32),
        )["params"]
    )
    sampling = SamplingConfig(temperature=0.0)

    def timed(fn):
        fn()  # compile+warm
        t0 = time.perf_counter()
        out = fn()
        return time.perf_counter() - t0, out

    dt, outs = timed(lambda: generate_text(
        target, tparams, prompts, max_new_tokens=new,
        sampling=sampling,
    ))
    plain_step_ms = dt / new * 1e3
    emit({
        "case": "plain_decode", "batch": b,
        "tok_per_s": round(b * new / dt, 1),
        "step_ms": round(plain_step_ms, 3),
    })

    for k in (2, 4, 8):
        dt, (souts, stats) = timed(lambda k=k: speculative_generate_text(
            draft, dparams, target, tparams, prompts,
            max_new_tokens=new, k=k, sampling=sampling,
        ))
        iters = stats["iterations"]
        emit({
            "case": f"speculative_k{k}", "batch": b,
            "tok_per_s": round(b * new / dt, 1),
            "iterations": iters,
            "emitted": stats["emitted"],
            # stats["emitted"] counts PER-ROW new tokens; tokens per
            # iteration = emitted/iters (1.0 = verify-only, i.e. zero
            # draft acceptance; k+1 = all drafts accepted).
            "tokens_per_iter": round(
                stats["emitted"] / max(iters, 1), 3
            ),
            "iter_ms": round(dt / max(iters, 1) * 1e3, 3),
            "iter_vs_plain_steps": round(
                dt / max(iters, 1) * 1e3 / plain_step_ms, 2
            ),
        })
        # Greedy agreement on hardware, reported as a FRACTION: with
        # random weights the logits are near-uniform, and in bf16 the
        # k+1-token verify forward reduces in a different order than
        # the 1-token decode step, so argmax ties flip tokens and the
        # sequences diverge at the first flip. The suite pins exact
        # parity in f32 (tests/test_speculative.py); this row records
        # how far bf16 tie-flipping carries identical prefixes on
        # near-uniform logits - a numerics observation, not a
        # correctness gate.
        if k == 4:
            agree = [
                sum(1 for x, y in zip(a, c) if x == y) / len(a)
                for a, c in zip(souts, outs)
            ]
            emit({
                "case": "greedy_agreement_k4",
                "exact_rows": sum(a == c for a, c in zip(souts, outs)),
                "mean_token_agreement": round(
                    sum(agree) / len(agree), 3
                ),
            })
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
