"""CI smoke for the fleet observatory: the full causal chain —
scrape -> bounded series -> derived fleet signals -> burn-rate alert
-> deploy-lint-clean scaling recommendation -> retrospective query —
proven end-to-end in ONE process on CPU.

A real 3-role serving gang (tpufw.serve.roles engines behind
LocalReplica + RouterServer, llama3_tiny random init) runs under
scripted load with impossibly tight SLO targets, so every request
violates TTFT and per-token latency and the multi-window burn rate
pegs at 1/(1-goal). The FleetCollector scrapes the gang exactly as it
would a cluster — router /metrics exposition through the tolerant
parser, replica framed-signal dicts, /healthz backfill — with
``scrape_once()`` driven manually so every assertion is deterministic.
What must hold:

- sweep 1 (pre-traffic) records all three replicas live, no alerts;
- under load, the re-aggregated ``tpufw_fleet_slo_burn_rate`` series
  cross the fast+slow thresholds and BOTH burn-rate pairs fire,
  landing schema'd ``fleet_alert`` events in events-fleet.jsonl;
- the ScalingRecommender turns the sustained alerts into ONE
  decision artifact (prefill +1, decode +1 — independent pools) whose
  manifest-shaped YAML passes ``tpulint --layer deploy --manifest``
  with an empty baseline, via subprocess like an operator would run it;
- the query CLI (``python -m tpufw.obs.fleet query``) reconstructs the
  PRE-alert instant (alerts_firing empty, all replicas present) and
  the post-alert instant (burn alerts firing) from the series dir
  alone — and still does after the series file gains a torn tail;
- the collector's own registry re-exports the derived series, and
  scripts/obs_summary.py digests the fleet dir.

Exit 0 on success; any failed check exits nonzero. Honors
TPUFW_FLEET_DIR so CI can upload the series dir.
"""

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
)

MAX_NEW = 6
PAGE = 16
N_REQUESTS = 4


def _post(base: str, body: dict) -> dict:
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=600) as resp:
        return json.loads(resp.read())


def main() -> int:
    import jax
    import jax.numpy as jnp

    from tpufw.infer import SamplingConfig
    from tpufw.models import LLAMA_CONFIGS, Llama
    from tpufw.obs import fleet
    from tpufw.obs.events import EventLog, read_events
    from tpufw.obs.registry import Registry
    from tpufw.obs.slo import SloTracker
    from tpufw.serve.roles import DecodeEngine, PrefillEngine
    from tpufw.serve.router import LocalReplica, RouterServer
    from tpufw.workloads.env import env_opt_str

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    manifest = os.path.join(
        repo, "deploy", "manifests", "13-serve-disagg-v5e8-jobset.yaml"
    )
    fdir = env_opt_str("fleet_dir") or tempfile.mkdtemp(
        prefix="tpufw-fleet-smoke-"
    )
    os.makedirs(fdir, exist_ok=True)

    failures: list = []

    def check(ok: bool, what: str) -> None:
        print(("ok: " if ok else "FAILED: ") + what)
        if not ok:
            failures.append(what)

    # ---- the gang: real engines, tight SLO so every request burns ----
    greedy = SamplingConfig(temperature=0.0)
    cfg = dataclasses.replace(
        LLAMA_CONFIGS["llama3_tiny"].decode_config(), max_seq_len=64
    )
    model = Llama(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    events = EventLog(os.path.join(fdir, fleet.EVENTS_FILENAME))
    common = dict(sampling=greedy, page=PAGE, kv_quant="int8")
    pe = PrefillEngine(model, params, n_slots=2, **common)
    de = DecodeEngine(model, params, n_slots=4, chunk=2, **common)
    pe_client = LocalReplica("prefill-0", pe)
    de_client = LocalReplica("decode-0", de)
    reg = Registry()
    # 1 microsecond targets: unattainable by construction, so the burn
    # rate pegs at 1/(1-goal) = 100 on every window — far past the
    # 14.4/6.0 fast/slow pair.
    slo = SloTracker(reg, events, ttft_ms=0.001, tok_ms=0.001, goal=0.99)
    router = RouterServer(
        [pe_client], [de_client],
        port=0, page=PAGE, events=events, registry=reg, slo=slo,
    )
    base = f"http://127.0.0.1:{router.port}"

    # ---- the observatory, wired exactly like main_router wires it ----
    store = fleet.SeriesStore(
        os.path.join(fdir, fleet.SERIES_FILENAME), max_records=4096
    )
    try:
        recommender = fleet.ScalingRecommender(
            fdir, manifest, cooldown_s=60.0, events=events
        )
        collector = fleet.FleetCollector(
            [
                fleet.Target("router", "router", router.render_metrics),
                fleet.Target("prefill-0", "prefill", pe_client.signals),
                fleet.Target("decode-0", "decode", de_client.signals),
            ],
            store,
            events=events,
            recommender=recommender,
            health_fn=router.health,
        )
    except BaseException:
        # Recommender/collector wiring raising must not strand the
        # series handle (TPU019).
        store.close()
        raise

    # ---- sweep 1: pre-traffic baseline (the instant queries revisit)
    derived0 = collector.scrape_once()
    t_quiet = store.read()[-1]["ts"]
    check(
        derived0.get('tpufw_fleet_replicas{role="router"}') == 1.0
        and derived0.get('tpufw_fleet_replicas{role="prefill"}') == 1.0
        and derived0.get('tpufw_fleet_replicas{role="decode"}') == 1.0,
        "sweep 1 sees all three roles live "
        f"(replicas={ {k: v for k, v in derived0.items() if 'replicas' in k} })",
    )
    check(
        not collector.alerts.evaluate(derived0),
        "no alerts firing before traffic",
    )

    # ---- scripted load: every request misses both targets ----
    shared = list(range(40, 72))
    for i in range(N_REQUESTS):
        body = _post(base, {
            "prompt": shared + [7, 9 + i], "max_new": MAX_NEW,
            "tenant": "smoke", "session": f"s{i}",
        })
        check(
            len(body.get("tokens", [])) == MAX_NEW,
            f"request {i} served through migration",
        )
    time.sleep(0.25)  # strict ts ordering: quiet record < alert event

    # ---- sweep 2: burn crosses the pair, alerts fire, one decision
    derived1 = collector.scrape_once()
    fast = derived1.get(
        'tpufw_fleet_slo_burn_rate{metric="ttft",tenant="smoke",window="60s"}'
    )
    slow = derived1.get(
        'tpufw_fleet_slo_burn_rate{metric="ttft",tenant="smoke",window="300s"}'
    )
    check(
        fast is not None and fast > 14.4 and slow is not None and slow > 6.0,
        f"re-aggregated burn rate crossed the fast/slow pair "
        f"(60s={fast}, 300s={slow})",
    )
    check(
        derived1.get("tpufw_fleet_tokens_per_s", 0.0) > 0.0
        and derived1.get("tpufw_fleet_requests_per_s", 0.0) > 0.0,
        "counter-rate series derived from the sweep-over-sweep delta "
        f"(tokens/s={derived1.get('tpufw_fleet_tokens_per_s'):.1f})",
    )
    time.sleep(0.25)
    collector.scrape_once()  # sweep 3: last record ts > firing event ts

    alert_events = [
        e for e in read_events(os.path.join(fdir, fleet.EVENTS_FILENAME))
        if e.get("kind") == "fleet_alert" and e.get("state") == "firing"
    ]
    fired_rules = sorted({e.get("rule") for e in alert_events})
    check(
        "fleet_ttft_burn" in fired_rules and "fleet_tok_burn" in fired_rules,
        f"both burn-rate pairs fired as fleet_alert events ({fired_rules})",
    )
    check(
        "tpufw_fleet_page_occupancy" in collector.registry.render(),
        "collector registry re-exports the derived series as gauges",
    )

    # ---- the recommendation artifact, verified the operator's way ----
    artifacts = sorted(
        f for f in os.listdir(fdir) if f.startswith("fleet-rec-")
        and f.endswith(".yaml")
    )
    check(
        len(artifacts) == 1,
        f"one sustained-alert sweep -> one decision artifact "
        f"(cooldown held sweep 3 back; got {artifacts})",
    )
    if artifacts:
        art = os.path.join(fdir, artifacts[0])
        counts = fleet.read_manifest_replicas(
            open(art, encoding="utf-8").read()
        )
        check(
            counts.get("prefill") == 2 and counts.get("decode") == 2,
            f"independent pools each stepped +1 (replicas={counts})",
        )
        proc = subprocess.run(
            [sys.executable, "-m", "tpufw.analysis", "--layer", "deploy",
             "--manifest", art, "--no-baseline"],
            capture_output=True, text=True, timeout=300, cwd=repo,
        )
        check(
            proc.returncode == 0,
            "recommendation artifact passes tpulint --layer deploy "
            f"(rc={proc.returncode}: {proc.stdout.strip() or proc.stderr.strip()})",
        )

    # ---- retrospective queries from the series dir alone ----
    def query(*extra: str) -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "tpufw.obs.fleet", "query",
             "--dir", fdir, "--json", *extra],
            capture_output=True, text=True, timeout=120, cwd=repo,
        )
        if proc.returncode != 0:
            return {"_rc": proc.returncode, "_err": proc.stderr}
        return json.loads(proc.stdout)

    pre = query("--at", str(t_quiet))
    check(
        pre.get("alerts_firing") == []
        and sorted(pre.get("replicas", {})) == [
            "decode-0", "prefill-0", "router",
        ],
        "query CLI reconstructs the pre-alert instant: three replicas, "
        f"no alerts (replicas={sorted(pre.get('replicas', {}))}, "
        f"firing={pre.get('alerts_firing')})",
    )
    post = query("--window", "60")
    post_rules = sorted(
        {e.get("rule") for e in post.get("alerts_firing", [])}
    )
    check(
        "fleet_ttft_burn" in post_rules,
        f"query CLI sees the burn alert firing at the latest instant "
        f"({post_rules})",
    )
    check(
        "tpufw_fleet_page_occupancy" in post.get("window", {}),
        "trailing-window aggregation covers the derived series",
    )

    # ---- torn tail: a collector killed mid-write must not take the
    # queries with it ----
    with open(os.path.join(fdir, fleet.SERIES_FILENAME), "a",
              encoding="utf-8") as f:
        f.write('{"ts": 999999999.0, "replica": "torn", "ser')
    torn = query("--at", str(t_quiet))
    check(
        sorted(torn.get("replicas", {})) == [
            "decode-0", "prefill-0", "router",
        ],
        "query survives a torn series tail",
    )

    # ---- the digest ----
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "obs_summary.py"),
         fdir],
        capture_output=True, text=True, timeout=120,
    )
    print(proc.stdout, end="")
    check(
        proc.returncode == 0 and "fleet observatory" in proc.stdout
        and "fleet_ttft_burn" in proc.stdout,
        "obs_summary digests the fleet dir (series + alert history)",
    )

    store.close()
    events.close()
    router.close()
    if failures:
        print(f"fleet-smoke FAILED ({len(failures)} check(s)):")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("fleet-smoke OK: scrape -> series -> burn -> alert -> "
          "lint-clean recommendation -> retrospective query, end to end")
    return 0


if __name__ == "__main__":
    sys.exit(main())
