#!/usr/bin/env bash
# TPU tunnel watcher: probe until the wedged backend clears, then bank a
# full bench run IMMEDIATELY (round-3 lesson, docs/PERF.md: tunnel
# wedges last hours and numbers must be banked early — the driver's
# end-of-round run has repeatedly landed inside a wedge window).
#
# Compile-kill safety: the probe child is init-only (jax.devices()
# starts no server-side compile, so killing a hung probe cannot orphan
# one); the bench run gets NO outer timeout — bench.py self-budgets
# (TPUFW_BENCH_TOTAL), TERMs-then-KILLs its own workers with a grace
# window, and always exits with one JSON line.
#
# Usage: scripts/tpu_watch.sh [interval_s] [deadline_epoch] (default
# 540 / now+9.5h). The deadline stops the probe loop before the
# driver's end-of-round bench needs the backend (one TPU job at a
# time) — insurance for a session that ends without a manual pkill.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-540}"
DEADLINE="${2:-$(( $(date +%s) + 34200 ))}"
LOG=docs/evidence/tpu_watch_r5.log
mkdir -p docs/evidence

probe() {
  timeout 90 python -c '
import jax
d = jax.devices()
print("PROBE_OK", d[0].platform, d[0].device_kind, len(d))
' 2>/dev/null
}

echo "$(date -u +%FT%TZ) watcher start (interval ${INTERVAL}s, deadline $(date -u -d "@${DEADLINE}" +%FT%TZ))" >> "$LOG"
while true; do
  # Stop probing once a bench STARTED now could not finish before the
  # deadline (a probe-then-bench just under the wire would hold the
  # backend into the driver's window — the exact collision the
  # deadline exists to prevent).
  if [ "$(( $(date +%s) + ${TPUFW_BENCH_TOTAL:-3600} + 120 ))" -ge "$DEADLINE" ]; then
    echo "$(date -u +%FT%TZ) deadline margin reached; stopping (no bench banked)" >> "$LOG"
    break
  fi
  out=$(probe)
  if echo "$out" | grep -q "PROBE_OK.*tpu"; then
    echo "$(date -u +%FT%TZ) probe ok: $out" >> "$LOG"
    echo "$(date -u +%FT%TZ) bench starting" >> "$LOG"
    TPUFW_BENCH_TOTAL="${TPUFW_BENCH_TOTAL:-3600}" \
    TPUFW_BENCH_TIMEOUT="${TPUFW_BENCH_TIMEOUT:-2600}" \
    TPUFW_BENCH_SAVE=docs/evidence/BENCH_r5_watch_tpu.jsonl \
      python bench.py \
      > docs/evidence/BENCH_r5_watch.json \
      2> docs/evidence/BENCH_r5_watch.err
    rc=$?
    echo "$(date -u +%FT%TZ) bench done rc=$rc: $(cat docs/evidence/BENCH_r5_watch.json)" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung: ${out:-<none>}" >> "$LOG"
  sleep "$INTERVAL"
done
echo "$(date -u +%FT%TZ) watcher exit" >> "$LOG"
