#!/usr/bin/env bash
# TPU tunnel watcher: probe until the wedged backend clears, then bank a
# full bench run IMMEDIATELY (round-3 lesson, docs/PERF.md: tunnel
# wedges last hours and numbers must be banked early — the driver's
# end-of-round run has repeatedly landed inside a wedge window).
#
# Compile-kill safety: the probe child is init-only (jax.devices()
# starts no server-side compile, so killing a hung probe cannot orphan
# one); the bench run gets NO outer timeout — bench.py self-budgets
# (TPUFW_BENCH_TOTAL), TERMs-then-KILLs its own workers with a grace
# window, and always exits with one JSON line.
#
# Usage: scripts/tpu_watch.sh [interval_s] (default 540)
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-540}"
LOG=docs/evidence/tpu_watch_r5.log
mkdir -p docs/evidence

probe() {
  timeout 90 python -c '
import jax
d = jax.devices()
print("PROBE_OK", d[0].platform, d[0].device_kind, len(d))
' 2>/dev/null
}

echo "$(date -u +%FT%TZ) watcher start (interval ${INTERVAL}s)" >> "$LOG"
while true; do
  out=$(probe)
  if echo "$out" | grep -q "PROBE_OK.*tpu"; then
    echo "$(date -u +%FT%TZ) probe ok: $out" >> "$LOG"
    echo "$(date -u +%FT%TZ) bench starting" >> "$LOG"
    TPUFW_BENCH_TOTAL="${TPUFW_BENCH_TOTAL:-3600}" \
    TPUFW_BENCH_TIMEOUT="${TPUFW_BENCH_TIMEOUT:-2600}" \
    TPUFW_BENCH_SAVE=docs/evidence/BENCH_r5_watch_tpu.jsonl \
      python bench.py \
      > docs/evidence/BENCH_r5_watch.json \
      2> docs/evidence/BENCH_r5_watch.err
    rc=$?
    echo "$(date -u +%FT%TZ) bench done rc=$rc: $(cat docs/evidence/BENCH_r5_watch.json)" >> "$LOG"
    break
  fi
  echo "$(date -u +%FT%TZ) probe failed/hung: ${out:-<none>}" >> "$LOG"
  sleep "$INTERVAL"
done
echo "$(date -u +%FT%TZ) watcher exit" >> "$LOG"
