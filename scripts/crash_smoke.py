#!/usr/bin/env python
"""Crash-bundle smoke test: SIGTERM a live training run mid-step and
assert the flight recorder leaves a complete, parseable crash bundle.

Launches ``tpufw.workloads.train_llama`` as a subprocess with full
telemetry on, waits until the events log proves the loop is actually
stepping, sends SIGTERM, and then checks the telemetry dir for:

- ``crash-bundle-p0/manifest.json`` that parses, lists ``sigterm``
  among its reasons, and names only files that actually exist
  (the manifest is written last via rename, so parseable == complete);
- ``ring.jsonl`` inside the bundle that the torn-tail-tolerant event
  reader can digest;
- a ``goodput.json`` rollup whose categories sum to its wall-clock
  (the graceful-preemption path still closes telemetry cleanly).

Exit 0 on success, 1 with a diagnostic on any miss — CI runs this
after the plain obs-smoke pass and uploads the dir either way.

Usage: python scripts/crash_smoke.py [telemetry_dir]
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpufw.obs.events import read_events
from tpufw.workloads.env import env_str

STEP_WAIT_S = 300.0  # compile on a cold CI box dominates this
EXIT_WAIT_S = 120.0


def fail(msg: str) -> int:
    print(f"crash_smoke: FAIL: {msg}", file=sys.stderr)
    return 1


def wait_for_step(events_path: str, proc) -> bool:
    """Poll until the run emits its first step event (the loop is
    live, so the SIGTERM lands genuinely mid-run)."""
    deadline = time.time() + STEP_WAIT_S
    while time.time() < deadline:
        if proc.poll() is not None:
            return False
        if os.path.exists(events_path):
            try:
                if any(
                    e.get("kind") == "step"
                    for e in read_events(events_path)
                ):
                    return True
            except OSError:
                pass
        time.sleep(0.5)
    return False


def main() -> int:
    tdir = (
        sys.argv[1]
        if len(sys.argv) > 1
        else env_str("telemetry_dir", "/tmp/telemetry-crash")
    )
    env = dict(os.environ)
    env["TPUFW_TELEMETRY_DIR"] = tdir
    # Force a long run (overriding any ambient smoke config): the
    # whole point is interrupting it mid-flight.
    env["TPUFW_TOTAL_STEPS"] = "500"
    env["TPUFW_SYNC_EVERY"] = "1"
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpufw.workloads.train_llama"], env=env
    )
    events_path = os.path.join(tdir, "events.jsonl")
    try:
        if not wait_for_step(events_path, proc):
            return fail(
                f"no step event within {STEP_WAIT_S}s "
                f"(exit={proc.poll()})"
            )
        print(f"crash_smoke: run is stepping (pid {proc.pid}); SIGTERM")
        proc.send_signal(signal.SIGTERM)
        try:
            code = proc.wait(timeout=EXIT_WAIT_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            return fail(f"run did not exit within {EXIT_WAIT_S}s of SIGTERM")
    finally:
        if proc.poll() is None:
            proc.kill()
    print(f"crash_smoke: run exited with code {code}")

    bundle = os.path.join(tdir, "crash-bundle-p0")
    manifest_path = os.path.join(bundle, "manifest.json")
    try:
        with open(manifest_path, encoding="utf-8") as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return fail(f"no parseable manifest at {manifest_path}: {e}")
    if "sigterm" not in manifest.get("reasons", []):
        return fail(f"manifest reasons lack 'sigterm': {manifest}")
    missing = [
        name
        for name in manifest.get("files", [])
        if not os.path.exists(os.path.join(bundle, name))
    ]
    if missing:
        return fail(f"manifest names missing files: {missing}")
    for required in ("ring.jsonl", "stacks.txt", "env.json"):
        if required not in manifest.get("files", []):
            return fail(f"bundle lacks {required}: {manifest['files']}")
    ring = read_events(os.path.join(bundle, "ring.jsonl"))
    if not ring:
        return fail("bundle ring.jsonl parsed to zero events")

    gp_path = os.path.join(tdir, "goodput.json")
    try:
        with open(gp_path, encoding="utf-8") as f:
            gp = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return fail(f"no parseable goodput rollup at {gp_path}: {e}")
    wall = gp.get("wall_s", 0.0)
    total = sum(gp.get("categories", {}).values())
    if wall <= 0 or abs(total - wall) > 0.02 * wall:
        return fail(
            f"goodput categories sum {total:.3f}s vs wall {wall:.3f}s "
            "(beyond 2%)"
        )
    print(
        f"crash_smoke: OK — bundle complete ({len(manifest['files'])} "
        f"files, {len(ring)} ring events), goodput sums to wall "
        f"({total:.2f}s / {wall:.2f}s)"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
