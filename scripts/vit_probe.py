import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
from tpufw.utils.profiling import enable_compile_cache
enable_compile_cache()
from tpufw.mesh import MeshConfig
from tpufw.models import VIT_CONFIGS, ViT
from tpufw.train import VisionTrainer, VisionTrainerConfig, synthetic_images

import dataclasses

vcfg = dataclasses.replace(
    VIT_CONFIGS["vit_b16"], remat=os.environ.get("VIT_REMAT", "1") == "1"
)
B = int(os.environ.get("VIT_BATCH", "128"))
vt = VisionTrainer(
    ViT(vcfg),
    VisionTrainerConfig(batch_size=B, image_size=224, total_steps=4, sync_every=2),
    MeshConfig(),
)
vt.init_state()
h = vt.run(
    synthetic_images(B, 224, 1000, on_device=True),
    flops_per_image=vcfg.flops_per_image(224),
)
print("VIT_OK", [round(m.mfu, 4) for m in h])
