#!/usr/bin/env python
"""One-screen digest of a tpufw telemetry dir (TPUFW_TELEMETRY_DIR).

Reads the artifacts the unified telemetry subsystem writes —
events*.jsonl, trace*.json, metrics.prom, goodput*.json, crash
bundles, hang dumps — and prints the run at a glance: step/loss
trajectory, event-kind counts, straggler incidents, where the
wall-clock went by span and by goodput category, headline counters,
and whatever evidence an abnormal exit left behind. CI runs it over
the smoke run's artifact so a failed run is diagnosable from the job
log alone.

Crashed runs are exactly when this script gets used, so every reader
degrades gracefully: a missing, torn, or half-written file prints a
one-line note instead of a traceback.

Usage:  python scripts/obs_summary.py <telemetry_dir>
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpufw.obs.events import read_events


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    return f"{seconds * 1e3:.1f}ms"


def _load_json(path: str):
    """Parse a JSON file, or None on any miss/tear — a SIGKILLed
    writer leaves half a trace.json and this script must still run."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None


def summarize_events(paths: list[str]) -> None:
    events = []
    for p in paths:
        try:
            events.extend(read_events(p))
        except OSError:
            print(f"  (unreadable: {os.path.basename(p)})")
    if not events:
        print("  (no events)")
        return
    kinds = collections.Counter(e.get("kind", "?") for e in events)
    print("  kinds: " + ", ".join(f"{k}={n}" for k, n in sorted(kinds.items())))
    steps = [e for e in events if e.get("kind") == "step"]
    if steps:
        first, last = steps[0], steps[-1]
        try:
            print(
                f"  steps {first['step']}..{last['step']}: "
                f"loss {first['loss']:.4f} -> {last['loss']:.4f}, "
                f"last step_time {_fmt_s(last['step_time_s'])} "
                f"(data_wait {_fmt_s(last['data_wait_s'])})"
            )
        except (KeyError, TypeError, ValueError):
            print(f"  {len(steps)} step event(s) (malformed fields)")
    for ev in events:
        if ev.get("kind") == "straggler_detected":
            print(
                f"  STRAGGLER step {ev.get('step')}: hosts "
                f"{ev.get('straggler_hosts')} vs median "
                f"{_fmt_s(ev.get('median_s', 0.0))} "
                f"(factor {ev.get('factor')})"
            )
        elif ev.get("kind") in ("preemption_signal", "preemption_stop"):
            print(f"  PREEMPTION: {json.dumps(ev, sort_keys=True)}")
        elif ev.get("kind") == "hang":
            print(
                f"  HANG: armed {_fmt_s(ev.get('armed_for_s', 0.0))} "
                f"past a {_fmt_s(ev.get('timeout_s', 0.0))} timeout "
                f"-> {ev.get('dump')}"
            )
    errors = [e for e in events if e.get("level") == "error"]
    if errors:
        print(f"  {len(errors)} error-level event(s):")
        for ev in errors[:5]:
            print(f"    {json.dumps(ev, sort_keys=True)}")


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def summarize_router(paths: list[str]) -> None:
    """Front-door digest: who asked, where requests landed, why any
    were turned away, and what the page migrations cost on the wire.
    Prints nothing when the run had no router/migration events."""
    events = []
    for p in paths:
        try:
            events.extend(read_events(p))
        except OSError:
            continue
    requests = [e for e in events if e.get("kind") == "router_request"]
    rejects = [e for e in events if e.get("kind") == "router_reject"]
    migrations = [e for e in events if e.get("kind") == "serve_migration"]
    if not requests and not rejects and not migrations:
        return
    print("-- router / migration --")
    if requests:
        tenants = collections.Counter(
            e.get("tenant", "?") for e in requests
        )
        replicas = collections.Counter(
            e.get("replica", "?") for e in requests
        )
        lat = sorted(
            e["latency_s"]
            for e in requests
            if isinstance(e.get("latency_s"), (int, float))
        )
        print(
            f"  {len(requests)} routed: tenants "
            + ", ".join(f"{t}={n}" for t, n in sorted(tenants.items()))
            + " | replicas "
            + ", ".join(f"{r}={n}" for r, n in sorted(replicas.items()))
        )
        if lat:
            print(
                f"  latency p50 {_fmt_s(_percentile(lat, 0.5))}, "
                f"p95 {_fmt_s(_percentile(lat, 0.95))}"
            )
    if rejects:
        reasons = collections.Counter(
            (e.get("tenant", "?"), e.get("reason", "?")) for e in rejects
        )
        print(
            f"  {len(rejects)} rejected: "
            + ", ".join(
                f"{t}/{r}={n}" for (t, r), n in sorted(reasons.items())
            )
        )
    if migrations:
        total_b = sum(e.get("bytes", 0) or 0 for e in migrations)
        total_p = sum(e.get("pages", 0) or 0 for e in migrations)
        walls = sorted(
            e["wall_s"]
            for e in migrations
            if isinstance(e.get("wall_s"), (int, float))
        )
        dirs = collections.Counter(
            e.get("direction", "?") for e in migrations
        )
        print(
            f"  {len(migrations)} page migration(s) "
            f"({', '.join(f'{d}={n}' for d, n in sorted(dirs.items()))}): "
            f"{total_p} pages, {_fmt_count(total_b)}B on the wire, "
            f"p95 wall {_fmt_s(_percentile(walls, 0.95))}"
        )


def summarize_kv_fabric(paths: list[str]) -> None:
    """KV-fabric digest: pages crossing the HBM/host-RAM boundary
    (serve_spill events, both directions), session drains, and
    router re-homes. Prints nothing when the run never spilled."""
    events = []
    for p in paths:
        try:
            events.extend(read_events(p))
        except OSError:
            continue
    spills = [e for e in events if e.get("kind") == "serve_spill"]
    rehomes = [e for e in events if e.get("kind") == "router_rehome"]
    if not spills and not rehomes:
        return
    print("-- kv fabric --")
    for entry in ("trie", "session"):
        moves = [e for e in spills if e.get("entry") == entry]
        if not moves:
            continue
        dirs = collections.Counter(
            e.get("direction", "?") for e in moves
        )
        pages = sum(e.get("pages", 0) or 0 for e in moves)
        total_b = sum(e.get("bytes", 0) or 0 for e in moves)
        sessions = sum(e.get("sessions", 0) or 0 for e in moves)
        dropped = sum(e.get("dropped", 0) or 0 for e in moves)
        walls = sorted(
            e["wall_s"]
            for e in moves
            if isinstance(e.get("wall_s"), (int, float))
        )
        line = (
            f"  {len(moves)} {entry} spill move(s) "
            f"({', '.join(f'{d}={n}' for d, n in sorted(dirs.items()))})"
        )
        if pages:
            line += f": {pages} pages, {_fmt_count(total_b)}B"
        if sessions or dropped:
            line += f": {sessions} session(s) exported, {dropped} dropped"
        if walls:
            line += f", p95 wall {_fmt_s(_percentile(walls, 0.95))}"
        print(line)
    if rehomes:
        where = collections.Counter(
            e.get("replica", "?") for e in rehomes
        )
        print(
            f"  {len(rehomes)} session re-home(s): "
            + ", ".join(f"{r}={n}" for r, n in sorted(where.items()))
        )


def summarize_spec(paths: list[str]) -> None:
    """Speculative-decoding digest from serve_spec events: how many
    verify passes ran, what fraction of drafted tokens the target
    accepted, and every time speculation degraded (penalty pools,
    draft-page starvation, legacy tick fallback). Prints nothing for
    runs that never speculated."""
    events = []
    for p in paths:
        try:
            events.extend(read_events(p))
        except OSError:
            continue
    spec = [e for e in events if e.get("kind") == "serve_spec"]
    if not spec:
        return
    print("-- speculative decoding --")
    passes = [e for e in spec if e.get("mode") == "pass"]
    if passes:
        rates = [
            e["accept_rate"]
            for e in passes
            if isinstance(e.get("accept_rate"), (int, float))
        ]
        ks = collections.Counter(e.get("k", "?") for e in passes)
        mean = sum(rates) / len(rates) if rates else 0.0
        print(
            f"  {len(passes)} spec pass(es) "
            f"(k: {', '.join(f'{k}x{n}' for k, n in sorted(ks.items()))}), "
            f"accept rate mean {mean:.1%}"
            + (f", last {rates[-1]:.1%}" if rates else "")
        )
    degrades = collections.Counter(
        (e.get("mode", "?"), e.get("reason", "-"))
        for e in spec
        if e.get("mode") != "pass"
    )
    if degrades:
        print(
            "  degraded: "
            + ", ".join(
                f"{m}({r})={n}" if r != "-" else f"{m}={n}"
                for (m, r), n in sorted(degrades.items())
            )
        )


def summarize_slo(paths: list[str]) -> None:
    """Per-tenant SLO attainment table plus a slowest-requests digest
    with the per-stage TTFT breakdown (both from router events —
    router_request carries ttft_s/stages, slo_violation carries the
    missed targets). Prints nothing for runs without routed
    requests."""
    events = []
    for p in paths:
        try:
            events.extend(read_events(p))
        except OSError:
            continue
    requests = [e for e in events if e.get("kind") == "router_request"]
    violations = [e for e in events if e.get("kind") == "slo_violation"]
    if not requests and not violations:
        return
    print("-- SLO attainment --")
    viol_by = collections.Counter(
        (e.get("tenant", "?"), e.get("metric", "?")) for e in violations
    )
    tenants = sorted(
        {e.get("tenant", "?") for e in requests}
        | {t for t, _m in viol_by}
    )
    print(
        f"  {'tenant':<12} {'req':>5} {'ttft_p50':>9} {'ttft_p95':>9} "
        f"{'viol ttft':>9} {'viol tok':>8} {'attain':>7}"
    )
    for tenant in tenants:
        rows = [e for e in requests if e.get("tenant", "?") == tenant]
        ttfts = sorted(
            e["ttft_s"]
            for e in rows
            if isinstance(e.get("ttft_s"), (int, float))
        )
        n = len(rows)
        v_ttft = viol_by.get((tenant, "ttft"), 0)
        v_tok = viol_by.get((tenant, "tok"), 0)
        attain = (n - v_ttft) / n if n else 0.0
        print(
            f"  {tenant:<12} {n:>5} "
            f"{_fmt_s(_percentile(ttfts, 0.5)):>9} "
            f"{_fmt_s(_percentile(ttfts, 0.95)):>9} "
            f"{v_ttft:>9} {v_tok:>8} {attain:>6.1%}"
        )
    timed = [
        e for e in requests
        if isinstance(e.get("latency_s"), (int, float))
    ]
    if timed:
        print("-- slowest requests --")
        timed.sort(key=lambda e: -e["latency_s"])
        for e in timed[:3]:
            trace = str(e.get("trace", ""))[:8] or "-"
            ttft = e.get("ttft_s")
            ttft_s = _fmt_s(ttft) if isinstance(ttft, (int, float)) else "-"
            line = (
                f"  trace={trace} tenant={e.get('tenant', '?')} "
                f"total {_fmt_s(e['latency_s'])} ttft {ttft_s}"
            )
            stages = e.get("stages")
            if isinstance(stages, dict) and stages:
                parts = [
                    f"{k} {_fmt_s(float(v))}"
                    for k, v in stages.items()
                    if isinstance(v, (int, float))
                ]
                line += " | " + " · ".join(parts)
            print(line)


def summarize_trace(paths: list[str]) -> None:
    totals: collections.Counter = collections.Counter()
    counts: collections.Counter = collections.Counter()
    for p in paths:
        doc = _load_json(p)
        if doc is None:
            print(f"  (torn/unreadable: {os.path.basename(p)})")
            continue
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "X":
                totals[ev["name"]] += ev.get("dur", 0.0) / 1e6
                counts[ev["name"]] += 1
    if not totals:
        print("  (no spans)")
        return
    wall = sum(totals.values()) or 1.0
    for name, total in totals.most_common():
        print(
            f"  {name:<18} {_fmt_s(total):>9}  "
            f"({total / wall:5.1%} of span time, n={counts[name]})"
        )


def _fmt_count(x: float) -> str:
    """1.23e9-style engineering shorthand for FLOPs/bytes columns."""
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(x) >= div:
            return f"{x / div:.2f}{unit}"
    return f"{x:.0f}"


def summarize_programs(path: str) -> None:
    """Per-program roofline table from programs.json (the perf
    observatory's cost harvest): FLOPs, bytes, arithmetic intensity,
    measured MFU, and which roof the program sits under."""
    doc = _load_json(path)
    if doc is None:
        print(f"  (torn/unreadable: {os.path.basename(path)})")
        return
    programs = doc.get("programs") or {}
    if not programs:
        print("  (no programs harvested)")
        return
    chip = doc.get("chip", "?")
    balance = doc.get("balance_flops_per_byte")
    if isinstance(balance, (int, float)):
        print(f"  chip={chip} balance={balance:.1f} FLOPs/byte")
    print(
        f"  {'program':<24} {'FLOPs':>9} {'bytes':>9} "
        f"{'AI':>7} {'MFU':>6}  bound"
    )
    rows = sorted(
        programs.items(),
        key=lambda kv: -(kv[1].get("flops") or 0.0),
    )
    for name, p in rows:
        if p.get("error"):
            print(f"  {name:<24} (harvest failed: {p['error']})")
            continue
        ai = p.get("ai_flops_per_byte")
        mfu = p.get("mfu")
        ai_s = f"{ai:.1f}" if isinstance(ai, (int, float)) else "-"
        mfu_s = f"{mfu:.1%}" if isinstance(mfu, (int, float)) else "-"
        print(
            f"  {name:<24} "
            f"{_fmt_count(p.get('flops') or 0.0):>9} "
            f"{_fmt_count(p.get('bytes_accessed') or 0.0):>9} "
            f"{ai_s:>7} {mfu_s:>6}  {p.get('bound') or '-'}"
        )


def summarize_metrics(path: str) -> None:
    wanted = (
        "tpufw_train_steps_total",
        "tpufw_train_tokens_total",
        "tpufw_train_mfu",
        "tpufw_program_mfu",
        "tpufw_hbm_headroom_bytes",
        "tpufw_train_tokens_per_sec_per_chip",
        "tpufw_train_stragglers_total",
        "tpufw_serve_requests_total",
        "tpufw_serve_request_errors_total",
        "tpufw_spec_accept_rate",
        "tpufw_spec_fallback_slots",
        "tpufw_spec_wasted_draft_flops_total",
        "tpufw_router_requests_total",
        "tpufw_router_rejects_total",
        "tpufw_router_decode_pages_free",
        "tpufw_router_prefix_affinity_hits_total",
        "tpufw_router_session_rehomes_total",
        "tpufw_kv_spill_pages",
        "tpufw_kv_spill_bytes_total",
        "tpufw_slo_ttft_attainment",
        "tpufw_slo_tok_attainment",
        "tpufw_slo_requests_total",
        "tpufw_slo_violations_total",
        "tpufw_goodput_ratio",
        "tpufw_run_info",
    )
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        print(f"  (unreadable: {os.path.basename(path)})")
        return
    for line in lines:
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        if name in wanted:
            print(f"  {line.rstrip()}")


def summarize_goodput(paths: list[str]) -> None:
    """Per-process goodput/badput breakdown from goodput*.json."""
    any_printed = False
    for p in paths:
        doc = _load_json(p)
        if doc is None:
            print(f"  (torn/unreadable: {os.path.basename(p)})")
            continue
        wall = doc.get("wall_s", 0.0) or 0.0
        cats = doc.get("categories", {})
        print(
            f"  {os.path.basename(p)}: wall {_fmt_s(wall)}, "
            f"goodput {doc.get('goodput_ratio', 0.0):.1%}"
        )
        denom = wall or 1.0
        for cat, secs in sorted(
            cats.items(), key=lambda kv: -kv[1]
        ):
            print(f"    {cat:<12} {_fmt_s(secs):>9}  ({secs / denom:5.1%})")
        if doc.get("replay_until_step"):
            print(
                f"    (restart replayed steps up to "
                f"{doc['replay_until_step']})"
            )
        any_printed = True
    if not any_printed and not paths:
        print("  (no goodput rollup)")


def summarize_crash_bundles(out: str) -> None:
    """Crash-bundle + hang-dump evidence, if any. The manifest is
    written last (atomic rename), so a parseable manifest means a
    complete bundle."""
    bundles = sorted(glob.glob(os.path.join(out, "crash-bundle-p*")))
    hangs = sorted(glob.glob(os.path.join(out, "hang-p*.json")))
    faults = [
        p
        for p in sorted(glob.glob(os.path.join(out, "fault-p*.log")))
        if os.path.getsize(p) > 0
    ]
    if not bundles and not hangs and not faults:
        return
    print("-- run-health evidence --")
    for b in bundles:
        manifest = _load_json(os.path.join(b, "manifest.json"))
        if manifest is None:
            print(
                f"  {os.path.basename(b)}: INCOMPLETE "
                "(no parseable manifest — writer died mid-flush)"
            )
            continue
        print(
            f"  {os.path.basename(b)}: reasons="
            f"{','.join(manifest.get('reasons', []))} "
            f"files={len(manifest.get('files', []))} "
            f"pid={manifest.get('pid')}"
        )
        ring = os.path.join(b, "ring.jsonl")
        if os.path.exists(ring):
            try:
                tail = read_events(ring)[-3:]
            except OSError:
                tail = []
            for ev in tail:
                print(f"    last: {json.dumps(ev, sort_keys=True)[:120]}")
    for h in hangs:
        doc = _load_json(h)
        if doc is None:
            print(f"  {os.path.basename(h)}: (torn)")
            continue
        print(
            f"  {os.path.basename(h)}: armed "
            f"{_fmt_s(doc.get('armed_for_s', 0.0))} past "
            f"{_fmt_s(doc.get('timeout_s', 0.0))} timeout "
            f"({len(doc.get('recent_events', []))} ring events attached)"
        )
    for p in faults:
        print(
            f"  {os.path.basename(p)}: non-empty faulthandler log "
            "(C-level fault — SIGSEGV/SIGBUS evidence)"
        )


def summarize_fleet(out: str, window_s: float = 300.0) -> None:
    """Fleet observatory digest: last-window derived series table,
    fired alerts, and the recommendation log. Prints nothing when the
    dir has no fleet series; torn tails degrade to whatever parses
    (read_series/read_events both drop unparseable lines)."""
    from tpufw.obs import fleet as obs_fleet

    series_path = os.path.join(out, obs_fleet.SERIES_FILENAME)
    if not os.path.exists(series_path):
        return
    records = obs_fleet.read_series(series_path)
    print("-- fleet observatory --")
    if not records:
        print("  (series file present but nothing parseable)")
        return
    last_ts = records[-1]["ts"]
    replicas = sorted(
        {
            (r["replica"], r.get("role", "?"))
            for r in records
            if r["replica"] != "fleet"
        }
    )
    stale_now = {
        r["replica"]
        for r in records
        if r["ts"] == last_ts and r.get("stale")
    }
    print(
        f"  {len(records)} records, {len(replicas)} replica(s), "
        f"last sweep @ {last_ts:.3f}"
        + (f", stale now: {sorted(stale_now)}" if stale_now else "")
    )
    stats = obs_fleet.window_stats(records, last_ts - window_s, last_ts)
    if stats:
        print(f"  last {window_s:.0f}s derived series (min/mean/max):")
        for skey, st in stats.items():
            print(
                f"    {skey:<58} {st['min']:>9.4g} {st['mean']:>9.4g} "
                f"{st['max']:>9.4g}"
            )
    history = obs_fleet.load_alert_history(
        os.path.join(out, obs_fleet.EVENTS_FILENAME)
    )
    alerts = [e for e in history if e.get("kind") == "fleet_alert"]
    if alerts:
        print("  alerts:")
        for ev in alerts[-10:]:
            print(
                f"    {ev.get('ts', 0):.3f} {ev.get('state'):<9} "
                f"{ev.get('rule')} [{ev.get('severity', '?')}] "
                f"{ev.get('series')} = {ev.get('value')}"
            )
    recs = [e for e in history if e.get("kind") == "fleet_recommendation"]
    if recs:
        print("  recommendations:")
        for ev in recs[-5:]:
            print(
                f"    {ev.get('ts', 0):.3f} pools="
                f"{json.dumps(ev.get('pools'), sort_keys=True)} "
                f"reason={','.join(ev.get('reason', []))} -> "
                f"{ev.get('artifact')}"
            )


def summarize_load(out: str) -> None:
    """Load observatory digest: per-rung attainment table (from the
    torn-tolerant load-trace reader), the detected knee from
    BENCH_load.json, and the scale_action timeline with each
    decision's burn rate. Prints nothing when the dir has neither a
    load trace nor a load bench payload."""
    from tpufw.load.genload import read_trace

    trace_path = os.path.join(out, "load-trace.jsonl")
    bench = _load_json(os.path.join(out, "BENCH_load.json"))
    recs = read_trace(trace_path)
    if bench is None and not recs:
        return
    print("-- load observatory --")
    if recs:
        rungs: dict = {}
        for r in recs:
            rungs.setdefault(
                (r["rung"], r["offered_rps"]), []
            ).append(r)
        print(
            f"  {len(recs)} trace record(s), {len(rungs)} rung(s):"
        )
        print(
            "    rung  rps      offered  ok    429   err   "
            "ttft_p50  ttft_p95"
        )
        for (rung, rps), rs in sorted(rungs.items()):
            ok = sum(1 for r in rs if r["status"] == 200)
            rej = sum(1 for r in rs if r["status"] == 429)
            ttfts = sorted(
                float(r["ttft_s"]) for r in rs
                if isinstance(r.get("ttft_s"), (int, float))
            )
            print(
                f"    {rung:<5} {rps:<8g} {len(rs):<8} {ok:<5} "
                f"{rej:<5} {len(rs) - ok - rej:<5} "
                f"{_fmt_s(_percentile(ttfts, 50)):>8}  "
                f"{_fmt_s(_percentile(ttfts, 95)):>8}"
            )
    if bench is not None:
        goal = bench.get("goal")
        for rung in bench.get("rungs", []):
            tens = rung.get("tenants", {})
            att = " ".join(
                f"{t}={st.get('attainment', 0):.3f}"
                for t, st in sorted(tens.items())
            )
            print(
                f"  rung {rung.get('rung')} "
                f"@{rung.get('offered_rps')}rps: "
                f"attainment={rung.get('attainment', 0):.3f} "
                f"goodput={rung.get('goodput_tok_s', 0):.1f}tok/s "
                f"[{att}]"
            )
        knee = bench.get("knee")
        if knee is not None:
            print(
                f"  knee: rung {knee.get('rung')} @ "
                f"{knee.get('offered_rps')} rps "
                f"(attainment {knee.get('attainment')} >= goal {goal})"
            )
        else:
            print(f"  knee: none (no rung met goal {goal})")
    actions = []
    phases = []
    for path in sorted(glob.glob(os.path.join(out, "events*.jsonl"))):
        for e in read_events(path):
            if e.get("kind") == "scale_action":
                actions.append(e)
            elif e.get("kind") == "load_phase":
                phases.append(e)
    if phases:
        print(
            "  phases: "
            + " -> ".join(str(e.get("phase")) for e in phases[-8:])
        )
    if actions:
        print("  scale actions:")
        for e in actions[-10:]:
            burn = e.get("burn")
            print(
                f"    {e.get('ts', 0):.3f} {e.get('action'):<10} "
                f"{e.get('pool')}/{e.get('replica') or '-'}"
                + (f" burn={burn}" if burn is not None else "")
                + (
                    f" decision@{e.get('decision_ts')}"
                    if e.get("decision_ts") is not None else ""
                )
                + (
                    f" recovery={_fmt_s(float(e['recovery_s']))}"
                    if e.get("recovery_s") is not None else ""
                )
            )


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    out = argv[1]
    if not os.path.isdir(out):
        print(f"obs_summary: no such dir {out!r}", file=sys.stderr)
        return 2
    print(f"== telemetry: {out} ==")
    print("-- events --")
    summarize_events(sorted(glob.glob(os.path.join(out, "events*.jsonl"))))
    summarize_router(sorted(glob.glob(os.path.join(out, "events*.jsonl"))))
    summarize_kv_fabric(
        sorted(glob.glob(os.path.join(out, "events*.jsonl")))
    )
    summarize_spec(sorted(glob.glob(os.path.join(out, "events*.jsonl"))))
    summarize_slo(sorted(glob.glob(os.path.join(out, "events*.jsonl"))))
    print("-- spans (total time) --")
    summarize_trace(sorted(glob.glob(os.path.join(out, "trace*.json"))))
    gp = sorted(glob.glob(os.path.join(out, "goodput*.json")))
    if gp:
        print("-- goodput/badput --")
        summarize_goodput(gp)
    progs = os.path.join(out, "programs.json")
    if os.path.exists(progs):
        print("-- compiled programs (roofline) --")
        summarize_programs(progs)
    prom = os.path.join(out, "metrics.prom")
    if os.path.exists(prom):
        print("-- metrics snapshot --")
        summarize_metrics(prom)
    summarize_fleet(out)
    summarize_load(out)
    summarize_crash_bundles(out)
    return 0


if __name__ == "__main__":
    # Default SIGPIPE so `obs_summary.py dir | head` exits quietly.
    import signal

    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main(sys.argv))
