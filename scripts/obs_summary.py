#!/usr/bin/env python
"""One-screen digest of a tpufw telemetry dir (TPUFW_TELEMETRY_DIR).

Reads the three artifacts the unified telemetry subsystem writes —
events*.jsonl, trace*.json, metrics.prom — and prints the run at a
glance: step/loss trajectory, event-kind counts, straggler incidents,
where the wall-clock went by span, and the headline counters. CI runs
it over the smoke run's artifact so a failed run is diagnosable from
the job log alone.

Usage:  python scripts/obs_summary.py <telemetry_dir>
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpufw.obs.events import read_events


def _fmt_s(seconds: float) -> str:
    if seconds >= 1.0:
        return f"{seconds:.2f}s"
    return f"{seconds * 1e3:.1f}ms"


def summarize_events(paths: list[str]) -> None:
    events = []
    for p in paths:
        events.extend(read_events(p))
    if not events:
        print("  (no events)")
        return
    kinds = collections.Counter(e["kind"] for e in events)
    print("  kinds: " + ", ".join(f"{k}={n}" for k, n in sorted(kinds.items())))
    steps = [e for e in events if e["kind"] == "step"]
    if steps:
        first, last = steps[0], steps[-1]
        print(
            f"  steps {first['step']}..{last['step']}: "
            f"loss {first['loss']:.4f} -> {last['loss']:.4f}, "
            f"last step_time {_fmt_s(last['step_time_s'])} "
            f"(data_wait {_fmt_s(last['data_wait_s'])})"
        )
    for ev in events:
        if ev["kind"] == "straggler_detected":
            print(
                f"  STRAGGLER step {ev['step']}: hosts "
                f"{ev['straggler_hosts']} vs median "
                f"{_fmt_s(ev['median_s'])} (factor {ev['factor']})"
            )
        elif ev["kind"] in ("preemption_signal", "preemption_stop"):
            print(f"  PREEMPTION: {json.dumps(ev, sort_keys=True)}")
    errors = [e for e in events if e.get("level") == "error"]
    if errors:
        print(f"  {len(errors)} error-level event(s):")
        for ev in errors[:5]:
            print(f"    {json.dumps(ev, sort_keys=True)}")


def summarize_trace(paths: list[str]) -> None:
    totals: collections.Counter = collections.Counter()
    counts: collections.Counter = collections.Counter()
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "X":
                totals[ev["name"]] += ev["dur"] / 1e6
                counts[ev["name"]] += 1
    if not totals:
        print("  (no spans)")
        return
    wall = sum(totals.values())
    for name, total in totals.most_common():
        print(
            f"  {name:<18} {_fmt_s(total):>9}  "
            f"({total / wall:5.1%} of span time, n={counts[name]})"
        )


def summarize_metrics(path: str) -> None:
    wanted = (
        "tpufw_train_steps_total",
        "tpufw_train_tokens_total",
        "tpufw_train_mfu",
        "tpufw_train_tokens_per_sec_per_chip",
        "tpufw_train_stragglers_total",
        "tpufw_serve_requests_total",
        "tpufw_serve_request_errors_total",
    )
    with open(path) as f:
        for line in f:
            if line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            if name in wanted:
                print(f"  {line.rstrip()}")


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    out = argv[1]
    if not os.path.isdir(out):
        print(f"obs_summary: no such dir {out!r}", file=sys.stderr)
        return 2
    print(f"== telemetry: {out} ==")
    print("-- events --")
    summarize_events(sorted(glob.glob(os.path.join(out, "events*.jsonl"))))
    print("-- spans (total time) --")
    summarize_trace(sorted(glob.glob(os.path.join(out, "trace*.json"))))
    prom = os.path.join(out, "metrics.prom")
    if os.path.exists(prom):
        print("-- metrics snapshot --")
        summarize_metrics(prom)
    return 0


if __name__ == "__main__":
    # Default SIGPIPE so `obs_summary.py dir | head` exits quietly.
    import signal

    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main(sys.argv))
