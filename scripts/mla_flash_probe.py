"""Validate the MLA flash path on real Mosaic at the deepseek_mla_bench
shape (qk_head_dim 192 = 128 nope + 64 rope, v padded 128->192 inside
the dispatch) - the one flipped preset with no banked flash hardware
run (r5 review finding). Trains 3 steps; prints per-window MFU."""
import sys

sys.path.insert(0, "/root/repo")

from tpufw.utils.profiling import enable_compile_cache

enable_compile_cache()

from tpufw.mesh import MeshConfig
from tpufw.models import DEEPSEEK_CONFIGS, Deepseek
from tpufw.train import Trainer, TrainerConfig, synthetic_batches

import dataclasses
cfg = DEEPSEEK_CONFIGS["deepseek_mla_bench"]
import os
if os.environ.get("MLA_PROBE_XLA") == "1":
    cfg = dataclasses.replace(cfg, attention_backend="xla")
if os.environ.get("MLA_PROBE_B8") == "1":
    _B = 8
else:
    _B = 2

trainer = Trainer(
    Deepseek(cfg),
    TrainerConfig(
        batch_size=_B, seq_len=2048, total_steps=3, lr=1e-4,
        warmup_steps=2, loss_chunk_size=512, log_every=1, sync_every=2,
    ),
    MeshConfig(),
)
trainer.init_state()
hist = trainer.run(
    synthetic_batches(_B, 2048, cfg.vocab_size),
    model_flops_per_token=cfg.flops_per_token(2047),
)
print("MLA_PROBE_OK", cfg.attention_backend, _B, [round(m.mfu, 4) for m in hist])
