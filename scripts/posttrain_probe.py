"""Post-training suite on the real chip — DPO, GRPO, and contrastive
embeddings at bench scale (596M model) have only ever run on CPU
meshes. One timed case each, JSON rows to
docs/evidence/POSTTRAIN_r5.jsonl. Timing is value-fetch based
(float(loss)) per the tunnel discipline (block_until_ready lies)."""
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "POSTTRAIN_r5.jsonl",
)
_TAGS: dict = {}


def emit(row):
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main():
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax
    import numpy as np

    from tpufw.configs.presets import bench_model_config
    from tpufw.mesh import MeshConfig
    from tpufw.models import Llama
    from tpufw.train import TrainerConfig

    d = jax.devices()[0]
    _TAGS.update(platform=d.platform)
    emit({"event": "start", "kind": d.device_kind})

    cfg = dataclasses.replace(
        bench_model_config(), remat_policy="attn_out"
    )
    flops_tok = cfg.flops_per_token(2047)
    peak = 197e12

    def timed_steps(step, state, batch, n=3):
        state, m = step(state, batch)  # compile + step 1
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = step(state, batch)
        float(m["loss"])
        return (time.perf_counter() - t0) / n, m

    # 1. DPO: policy fwd+bwd + frozen bf16 reference fwd per step.
    try:
        from tpufw.train.dpo import DPOConfig, DPOTrainer

        rows, seq = 8, 2048
        tr = DPOTrainer(
            Llama(cfg),
            TrainerConfig(
                batch_size=rows, seq_len=seq, total_steps=4, lr=1e-5,
                warmup_steps=1, loss_chunk_size=512,
            ),
            MeshConfig(),
            dpo=DPOConfig(beta=0.1, ref_dtype="bfloat16"),
        )
        tr.init_state()
        rng = np.random.default_rng(0)
        batch = tr.globalize_batch({
            "tokens": rng.integers(
                1, cfg.vocab_size, (rows, seq)
            ).astype(np.int32),
            "loss_mask": np.ones((rows, seq), np.int32),
            "segment_ids": np.ones((rows, seq), np.int32),
        })
        step = tr.compiled_step(batch)
        dt, m = timed_steps(step, tr.state, batch)
        # DPO compute per step ~= policy fwd+bwd (3x fwd) + ref fwd
        # (1x) = 4/3 of an LM train step's FLOPs.
        emit({
            "case": "dpo_step", "rows": rows, "seq": seq,
            "step_ms": round(dt * 1e3, 1),
            "tok_per_s": round(rows * seq / dt, 1),
            "mfu_policy_plus_ref": round(
                (4.0 / 3.0) * flops_tok * rows * seq / dt / peak, 4
            ),
            "loss": round(float(m["loss"]), 4),
        })
        del tr, step, batch
    except Exception as e:  # noqa: BLE001
        emit({"case": "dpo_step",
              "error": f"{type(e).__name__}: {e}"[:300]})
    import gc

    gc.collect()
    jax.clear_caches()

    # 2. GRPO: one full iteration = grouped rollout (decode) + the
    # clipped-ratio policy step.
    try:
        from tpufw.train.grpo import GRPOConfig, GRPOTrainer

        n_prompts, group, new = 2, 8, 128
        seq = 512
        gtr = GRPOTrainer(
            Llama(dataclasses.replace(cfg, max_seq_len=seq)),
            TrainerConfig(
                batch_size=n_prompts * group, seq_len=seq,
                total_steps=4, lr=1e-6, warmup_steps=1,
                loss_chunk_size=512,
            ),
            MeshConfig(),
            grpo=GRPOConfig(
                group_size=group, max_new_tokens=new, temperature=1.0,
            ),
        )
        gtr.init_state()
        prompts = [[7, 8, 9, 10], [11, 12, 13]]

        def reward(ps, completions):
            return np.array(
                [len(c) / float(new) for c in completions]
            )

        def one_iter(key):
            batch, info = gtr.rollout(prompts, reward, key)
            step = gtr.compiled_step(batch)
            gtr.state, m = step(gtr.state, batch)
            float(m["loss"])
            return m

        one_iter(jax.random.key(0))  # compile rollout + step
        t0 = time.perf_counter()
        m = one_iter(jax.random.key(1))
        dt = time.perf_counter() - t0
        emit({
            "case": "grpo_iteration",
            "prompts": n_prompts, "group_size": group,
            "max_new_tokens": new,
            "iter_s": round(dt, 2),
            "completion_tok_per_s": round(
                n_prompts * group * new / dt, 1
            ),
            "loss": round(float(m["loss"]), 4),
        })
        del gtr
    except Exception as e:  # noqa: BLE001
        emit({"case": "grpo_iteration",
              "error": f"{type(e).__name__}: {e}"[:300]})
    gc.collect()
    jax.clear_caches()

    # 3. Contrastive embeddings: bidirectional InfoNCE over in-batch
    # negatives (E5 recipe), bidirectional encoder (causal=False).
    try:
        from tpufw.train.contrastive import (
            ContrastiveConfig,
            EmbeddingTrainer,
        )

        rows, seq = 32, 512
        etr = EmbeddingTrainer(
            Llama(
                dataclasses.replace(
                    cfg, max_seq_len=seq, causal=False
                )
            ),
            TrainerConfig(
                batch_size=rows, seq_len=seq, total_steps=4, lr=1e-5,
                warmup_steps=1,
            ),
            MeshConfig(),
            contrastive=ContrastiveConfig(),
        )
        etr.init_state()
        rng = np.random.default_rng(1)
        batch = etr.globalize_batch({
            "tokens": rng.integers(
                1, cfg.vocab_size, (rows, seq)
            ).astype(np.int32),
            "segment_ids": np.ones((rows, seq), np.int32),
        })
        step = etr.compiled_step(batch)
        dt, m = timed_steps(step, etr.state, batch)
        emit({
            "case": "contrastive_step", "rows": rows, "seq": seq,
            "step_ms": round(dt * 1e3, 1),
            "tok_per_s": round(rows * seq / dt, 1),
            "loss": round(float(m["loss"]), 4),
        })
    except Exception as e:  # noqa: BLE001
        emit({"case": "contrastive_step",
              "error": f"{type(e).__name__}: {e}"[:300]})
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
