"""int8 full-8B decode: batch sweep + unrolled composition on the one
v5e chip — the serving-default posture (int8 weights, unrolled layers)
at the north-star model shape. Extends the bench int8_8b tier (batch 8
scanned: 512 tok/s/chip, 66% of the weight-streaming floor) to the
batch sizes continuous batching actually runs.

One JSON line per case to docs/evidence/INT8_8B_SWEEP_r5.jsonl.
"""
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, "/root/repo")

OUT = "/root/repo/docs/evidence/INT8_8B_SWEEP_r5.jsonl"
_TAGS: dict = {}


def emit(row):
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main():
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import gc

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpufw.infer import SamplingConfig, cast_decode_params, generate
    from tpufw.models import LLAMA_CONFIGS, Llama, unstack_layer_params

    d = jax.devices()[0]
    _TAGS.update(platform=d.platform)
    emit({"event": "start", "kind": d.device_kind})

    prompt, new = 128, 128
    base = dataclasses.replace(
        LLAMA_CONFIGS["llama3_8b"].decode_config(),
        max_seq_len=prompt + new,
        quantized_weights=True,
    )

    def timed(model, params, b):
        prompts = jax.random.randint(
            jax.random.key(0), (b, prompt), 0, base.vocab_size
        )
        pads = jnp.zeros((b,), jnp.int32)

        def gen():
            return generate(
                model, params, prompts, pads, jax.random.key(2),
                max_new_tokens=new, sampling=SamplingConfig(),
            )

        np.asarray(gen())  # compile+warm
        t0 = time.perf_counter()
        np.asarray(gen())
        return time.perf_counter() - t0

    model = Llama(base)
    params = cast_decode_params(
        jax.jit(model.init)(
            jax.random.key(1),
            jnp.zeros((1, prompt), jnp.int32),
        )["params"]
    )
    u_params = None
    try:
        for b in (8, 16, 32, 64):
            try:
                dt = timed(model, params, b)
                emit({
                    "case": f"int8_scanned_b{b}",
                    "batch": b,
                    "tok_per_s": round(b * new / dt, 1),
                })
            except Exception as e:  # noqa: BLE001
                emit({"case": f"int8_scanned_b{b}",
                      "error": f"{type(e).__name__}: {e}"[:300]})
        # Serving-default composition: int8 x unrolled (32 unscanned
        # layers; compile grows with n_layers - measure it too).
        u_model = Llama(dataclasses.replace(base, scan_layers=False))
        u_params = unstack_layer_params(params, donate=True)
        params = None
        for b in (8, 32):
            try:
                c0 = time.perf_counter()
                dt = timed(u_model, u_params, b)
                emit({
                    "case": f"int8_unrolled_b{b}",
                    "batch": b,
                    "tok_per_s": round(b * new / dt, 1),
                    "compile_plus_2runs_s": round(
                        time.perf_counter() - c0, 1
                    ),
                })
            except Exception as e:  # noqa: BLE001
                emit({"case": f"int8_unrolled_b{b}",
                      "error": f"{type(e).__name__}: {e}"[:300]})
    finally:
        del params, u_params
        gc.collect()
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
