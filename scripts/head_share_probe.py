"""Is the 596M headline's 49%-vs-60% gap (vs the 8B block) the LM
head/CE share or the smaller d_model? Run the bench model with the
block8b-style shrunk vocab (2048): if MFU jumps toward 60, the head/CE
is the gap; if it stays ~49, it's matmul width."""
import dataclasses
import sys

sys.path.insert(0, "/root/repo")
from tpufw.utils.profiling import enable_compile_cache

enable_compile_cache()

from tpufw.configs.presets import bench_model_config
from tpufw.mesh import MeshConfig
from tpufw.models import Llama
from tpufw.train import Trainer, TrainerConfig, synthetic_batches

for vocab in (2048,):
    cfg = dataclasses.replace(
        bench_model_config(), vocab_size=vocab,
        remat_policy="attn_out",
    )
    trainer = Trainer(
        Llama(cfg),
        TrainerConfig(
            batch_size=16, seq_len=2048, total_steps=6, lr=1e-4,
            warmup_steps=2, loss_chunk_size=512, log_every=1,
            sync_every=4,
        ),
        MeshConfig(),
    )
    trainer.init_state()
    hist = trainer.run(
        synthetic_batches(16, 2048, cfg.vocab_size),
        model_flops_per_token=cfg.flops_per_token(2047),
    )
    print("VOCAB", vocab, [round(m.mfu, 4) for m in hist])
