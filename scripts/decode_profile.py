#!/usr/bin/env python
"""Decode-throughput component profile (VERDICT r3 item 2 / weak 4).

The round-3 record: 626 tok/s/chip bf16 at batch 8 on the 596M bench
model, vs a ~5,400 tok/s weight-stream roofline (1.19 GB bf16 weights,
819 GB/s v5e HBM) — 12.8 ms/step where weights account for ~1.5 ms.
Nobody has measured WHERE the other 11 ms goes. This script isolates
the components, one JSON line per experiment:

  1. baseline      — the exact bench decode tier (prefill 128 + 128 new)
  2. decode_only   — max_new only, 1-token prompt (prefill cost out)
  3. batch sweep   — B in {1, 8, 32}: flat per-step = bandwidth-bound,
                     linear = compute/overhead-bound
  4. newtok sweep  — 64 vs 256 new tokens: per-step slope vs fixed cost
  5. no_head       — hidden-states only (lm head + sampling cost out)
  6. unscanned     — scan_layers=False (layer-scan slice overhead out)
  7. small_cache   — max_seq_len exactly prompt+new vs 2048 (cache
                     update / attention slot traffic)
  8. int8          — weight-only quant (the serving lever; r3: 1.124x,
                     should be ~1.7x if truly bandwidth-bound)

Timing is value-fetch based (np.asarray), never block_until_ready —
the axon tunnel lies about the latter (docs/PERF.md). Run from
/root/repo with the TPU healthy:  python scripts/decode_profile.py
Results land in docs/evidence/DECODE_PROFILE_r5.jsonl as they complete
(a later wedge can't erase them).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "evidence", "DECODE_PROFILE_r5.jsonl",
)
# Every row carries the platform so a --smoke wiring check appended to
# the same evidence file can never be mistaken for hardware numbers.
_TAGS: dict = {}


def emit(row: dict) -> None:
    row = {"t": round(time.time(), 1), **_TAGS, **row}
    print(json.dumps(row), flush=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")


def main() -> int:
    smoke = "--smoke" in sys.argv
    if smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpufw.configs import bench_model_config
    from tpufw.infer import SamplingConfig, cast_decode_params, generate
    from tpufw.models import Llama

    if smoke:
        jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    _TAGS.update(platform=devices[0].platform, smoke=smoke)
    emit({"event": "start", "kind": devices[0].device_kind})

    base_cfg = bench_model_config()
    if smoke:
        from tpufw.models import LLAMA_CONFIGS

        base_cfg = LLAMA_CONFIGS["llama3_tiny"]
    hbm_bw = 819e9  # v5e

    def weight_bytes(cfg, quant):
        """Per-CASE decode-streamed weight bytes: the embedding table is
        a [B]-row gather (excluded), the lm head streams fully; int8
        stores projections at 1 byte (+~1% scales, ignored)."""
        streamed = cfg.n_params() - cfg.vocab_size * cfg.d_model
        return streamed * (1 if quant else 2)

    def run_case(name, cfg, b, prompt_len, n_new, quant=False,
                 return_hidden=False):
        """Compile+warm one generate, then time a second full call.
        Returns per-step ms and roofline fraction."""
        import gc

        gc.collect()
        # Params always init from the UNquantized twin; int8 cases
        # quantize that tree and run it through the quantized model
        # (bench.py's decode-tier discipline).
        fp_cfg = (
            dataclasses.replace(cfg, quantized_weights=False)
            if quant else cfg
        )
        model = Llama(cfg)
        prompts = jax.random.randint(
            jax.random.key(0), (b, prompt_len), 0, cfg.vocab_size
        )
        pads = jnp.zeros((b,), jnp.int32)
        params = cast_decode_params(
            jax.jit(Llama(fp_cfg).init)(
                jax.random.key(1), prompts
            )["params"]
        )
        if quant:
            from tpufw.ops.quant import quantize_params

            params = quantize_params(params)

        def gen():
            return generate(
                model, params, prompts, pads, jax.random.key(2),
                max_new_tokens=n_new, sampling=SamplingConfig(),
            )

        t0 = time.perf_counter()
        np.asarray(gen())
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(gen())
        dt = time.perf_counter() - t0
        step_ms = dt / n_new * 1e3
        wb = weight_bytes(cfg, quant)
        row = {
            "case": name, "batch": b, "prompt": prompt_len,
            "new": n_new, "total_s": round(dt, 4),
            "step_ms": round(step_ms, 3),
            "tok_per_s": round(b * n_new / dt, 1),
            "roofline_frac": round((wb / hbm_bw) / (dt / n_new), 4),
            "compile_s": round(compile_s, 1),
        }
        emit(row)
        del params
        return row

    dec = lambda **kw: dataclasses.replace(  # noqa: E731
        base_cfg.decode_config(), **kw
    )

    # 1. The exact bench decode tier.
    run_case("baseline", dec(max_seq_len=256), 8, 128, 128)
    # 2. Prefill out of the picture.
    run_case("decode_only", dec(max_seq_len=257), 8, 1, 256)
    # 3. Batch sweep: bandwidth-bound decode is ~flat in step_ms.
    for b in (1, 32):
        run_case(f"batch{b}", dec(max_seq_len=256), b, 128, 128)
    # 4. New-token sweep at MATCHED cache size (256 slots, same as
    # baseline — cache length alone moved step_ms ~10x in the smoke
    # run, so it must not vary here): half the steps amortizing the
    # same 128-token prefill. step_ms(new64) - step_ms(baseline)
    # ~= prefill_cost/64; equal step_ms means per-step cost dominates.
    run_case("new64", dec(max_seq_len=256), 8, 128, 64)
    # 5. Head + sampling out: hidden-only decode loop. (Approximated by
    #    a model with a tiny vocab: head matmul+sample shrink ~256x.)
    run_case(
        "tiny_vocab", dec(max_seq_len=256, vocab_size=128), 8, 128, 128
    )
    # 6. Layer scan out (per-layer weight slicing overhead).
    run_case(
        "unscanned", dec(max_seq_len=256, scan_layers=False),
        8, 128, 128,
    )
    # 7. Oversized cache: slot traffic scaling (2048 slots vs 256).
    run_case("cache2048", dec(max_seq_len=2048), 8, 128, 128)
    # 8. int8 weight-only.
    run_case(
        "int8", dec(max_seq_len=256, quantized_weights=True),
        8, 128, 128, quant=True,
    )
    emit({"event": "done"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
