{{- define "tpu-stack.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{- define "tpu-stack.image" -}}
{{ .Values.image.repository }}:{{ .Values.image.tag }}
{{- end }}
