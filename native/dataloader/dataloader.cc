#include "dataloader.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstring>
#include <numeric>
#include <string>
#include <vector>

namespace {

thread_local std::string g_error;

struct Mmap {
  const uint8_t* data = nullptr;
  size_t size = 0;

  bool Open(const char* path) {
    int fd = ::open(path, O_RDONLY | O_CLOEXEC);
    if (fd < 0) {
      g_error = std::string("open failed: ") + path;
      return false;
    }
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < 0) {
      g_error = std::string("stat failed: ") + path;
      ::close(fd);
      return false;
    }
    size = static_cast<size_t>(st.st_size);
    if (size) {
      void* p = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
      if (p == MAP_FAILED) {
        g_error = std::string("mmap failed: ") + path;
        ::close(fd);
        return false;
      }
      data = static_cast<const uint8_t*>(p);
    }
    ::close(fd);
    return true;
  }

  void Close() {
    if (data) munmap(const_cast<uint8_t*>(data), size);
    data = nullptr;
  }
};

// splitmix64 — tiny deterministic RNG for the epoch shuffle.
uint64_t SplitMix64(uint64_t& state) {
  uint64_t z = (state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Loader {
  Mmap bin, idx;
  const uint32_t* tokens = nullptr;
  const uint64_t* offsets = nullptr;  // n_docs + 1 entries
  uint64_t n_docs = 0;

  // Epoch iteration state.
  std::vector<uint64_t> order;
  uint64_t doc_pos = 0;     // index into order
  uint64_t intra_doc = 0;   // tokens of current doc already consumed
  bool exhausted = true;

  // Current (partially filled) row, carried across batches.
  std::vector<int32_t> row_tokens, row_segs;
  int32_t seg = 1;
};

bool FillRowsFromDocs(Loader* L, int32_t seq) {
  // Consume docs until the current row is full or the epoch runs dry.
  while (static_cast<int32_t>(L->row_tokens.size()) < seq) {
    if (L->doc_pos >= L->order.size()) return false;  // dry (shard end)
    uint64_t doc = L->order[L->doc_pos];
    uint64_t start = L->offsets[doc] + L->intra_doc;
    uint64_t end = L->offsets[doc + 1];
    if (start >= end) {  // empty doc or fully consumed
      ++L->doc_pos;
      L->intra_doc = 0;
      continue;
    }
    uint64_t space = seq - L->row_tokens.size();
    uint64_t take = std::min<uint64_t>(space, end - start);
    for (uint64_t i = 0; i < take; ++i) {
      L->row_tokens.push_back(static_cast<int32_t>(L->tokens[start + i]));
      L->row_segs.push_back(L->seg);
    }
    L->seg += 1;
    L->intra_doc += take;
    if (L->offsets[doc] + L->intra_doc >= end) {
      ++L->doc_pos;
      L->intra_doc = 0;
    }
  }
  return true;
}

void EmitRow(Loader* L, int32_t seq, int32_t* toks, int32_t* segs,
             float* mask) {
  size_t n = L->row_tokens.size();
  for (int32_t i = 0; i < seq; ++i) {
    bool real = static_cast<size_t>(i) < n;
    toks[i] = real ? L->row_tokens[i] : 0;
    segs[i] = real ? L->row_segs[i] : 0;
    mask[i] = real ? 1.0f : 0.0f;
  }
  L->row_tokens.clear();
  L->row_segs.clear();
  L->seg = 1;
}

}  // namespace

extern "C" {

void* tpufwdata_open(const char* bin_path, const char* idx_path) {
  auto* L = new Loader();
  if (!L->bin.Open(bin_path) || !L->idx.Open(idx_path)) {
    tpufwdata_close(L);
    return nullptr;
  }
  if (L->idx.size < sizeof(uint64_t) || L->idx.size % sizeof(uint64_t)) {
    g_error = "idx file must hold >=1 uint64 offsets";
    tpufwdata_close(L);
    return nullptr;
  }
  L->tokens = reinterpret_cast<const uint32_t*>(L->bin.data);
  L->offsets = reinterpret_cast<const uint64_t*>(L->idx.data);
  L->n_docs = L->idx.size / sizeof(uint64_t) - 1;
  uint64_t total = L->offsets[L->n_docs];
  if (total * sizeof(uint32_t) != L->bin.size) {
    g_error = "idx final offset does not match bin token count";
    tpufwdata_close(L);
    return nullptr;
  }
  // Every offset must be monotonic: a corrupt intermediate offset would
  // send FillRowsFromDocs reading past the mmap.
  for (uint64_t i = 0; i < L->n_docs; ++i) {
    if (L->offsets[i] > L->offsets[i + 1]) {
      g_error = "idx offsets are not monotonically non-decreasing";
      tpufwdata_close(L);
      return nullptr;
    }
  }
  return L;
}

void tpufwdata_close(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  if (!L) return;
  L->bin.Close();
  L->idx.Close();
  delete L;
}

const char* tpufwdata_error() { return g_error.c_str(); }

uint64_t tpufwdata_n_docs(void* handle) {
  return static_cast<Loader*>(handle)->n_docs;
}

uint64_t tpufwdata_n_tokens(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  return L->offsets[L->n_docs];
}

void tpufwdata_begin_epoch(void* handle, int shuffle, uint64_t seed,
                           uint64_t epoch, uint32_t shard,
                           uint32_t num_shards) {
  auto* L = static_cast<Loader*>(handle);
  L->order.resize(L->n_docs);
  std::iota(L->order.begin(), L->order.end(), 0);
  if (shuffle && L->n_docs > 1) {
    uint64_t state = seed * 0x2545F4914F6CDD1DULL + epoch + 1;
    for (uint64_t i = L->n_docs - 1; i > 0; --i) {
      uint64_t j = SplitMix64(state) % (i + 1);
      std::swap(L->order[i], L->order[j]);
    }
  }
  if (num_shards > 1) {
    std::vector<uint64_t> mine;
    for (uint64_t i = shard; i < L->order.size(); i += num_shards) {
      mine.push_back(L->order[i]);
    }
    L->order = std::move(mine);
  }
  L->doc_pos = 0;
  L->intra_doc = 0;
  L->row_tokens.clear();
  L->row_segs.clear();
  L->seg = 1;
  L->exhausted = false;
}

int tpufwdata_next_batch(void* handle, int32_t batch, int32_t seq,
                         int32_t* out_tokens, int32_t* out_segments,
                         float* out_loss_mask) {
  auto* L = static_cast<Loader*>(handle);
  if (L->exhausted) return 0;
  int32_t rows = 0;
  bool dry = false;
  for (; rows < batch; ++rows) {
    if (!FillRowsFromDocs(L, seq)) {
      dry = true;
      break;
    }
    EmitRow(L, seq, out_tokens + static_cast<size_t>(rows) * seq,
            out_segments + static_cast<size_t>(rows) * seq,
            out_loss_mask + static_cast<size_t>(rows) * seq);
  }
  if (!dry) return 1;
  // Epoch ran dry mid-batch: flush any partial row, pad out empty rows —
  // mirrors pack_documents' tail handling. An entirely empty batch (dry
  // hit on row 0 with nothing carried) emits nothing.
  bool have_partial = !L->row_tokens.empty();
  if (rows == 0 && !have_partial) {
    L->exhausted = true;
    return 0;
  }
  if (have_partial) {
    EmitRow(L, seq, out_tokens + static_cast<size_t>(rows) * seq,
            out_segments + static_cast<size_t>(rows) * seq,
            out_loss_mask + static_cast<size_t>(rows) * seq);
    ++rows;
  }
  for (; rows < batch; ++rows) {
    int32_t* t = out_tokens + static_cast<size_t>(rows) * seq;
    int32_t* s = out_segments + static_cast<size_t>(rows) * seq;
    float* m = out_loss_mask + static_cast<size_t>(rows) * seq;
    std::memset(t, 0, sizeof(int32_t) * seq);
    std::memset(s, 0, sizeof(int32_t) * seq);
    for (int32_t i = 0; i < seq; ++i) m[i] = 0.0f;
  }
  L->exhausted = true;
  return 1;
}

}  // extern "C"
