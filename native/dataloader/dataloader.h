// tpufw native data loader: mmap'd token corpus -> packed LM batches.
//
// The reference delegates its data path entirely (there is none — its
// workload is `nvidia-smi`, reference README.md:314); a training framework
// needs one, and the packing loop is the reference-stack role (GPU
// dataloader workers) implemented native per the runtime-in-C++ design:
// the packer walks millions of small docs per epoch, which is Python-loop
// territory only a compiled loop keeps off the step path.
//
// Corpus format (the Megatron/nanoGPT-style flat layout):
//   <prefix>.bin  — uint32 tokens, all docs concatenated
//   <prefix>.idx  — uint64 little-endian doc START offsets (n_docs+1
//                   entries; last = total token count)
//
// Packing semantics are EXACTLY tpufw.train.data.pack_documents: greedy
// row fill, docs split across rows/batches, per-row segment ids starting
// at 1, zero-padded tails, trailing partial batch padded with empty rows.
// Parity is pinned by tests/test_native_data.py.
#pragma once

#include <cstdint>

extern "C" {

// Opens a corpus; returns an opaque handle or null (see tpufwdata_error).
void* tpufwdata_open(const char* bin_path, const char* idx_path);
void tpufwdata_close(void* handle);

// Last error message for a failed open (thread-local, static storage).
const char* tpufwdata_error();

uint64_t tpufwdata_n_docs(void* handle);
uint64_t tpufwdata_n_tokens(void* handle);

// Start an epoch: doc order is identity when shuffle=0, else a
// deterministic permutation from (seed, epoch). shard/num_shards split
// the (post-shuffle) doc order round-robin across data-parallel hosts —
// each host packs a disjoint document subset (num_shards=1 = all docs).
void tpufwdata_begin_epoch(void* handle, int shuffle, uint64_t seed,
                           uint64_t epoch, uint32_t shard,
                           uint32_t num_shards);

// Fill one packed batch. out_tokens/out_segments are [batch*seq] int32,
// out_loss_mask is [batch*seq] float32 (1.0 on real tokens). Returns 1
// if a batch was produced, 0 when the epoch is exhausted (call
// begin_epoch again for the next one).
int tpufwdata_next_batch(void* handle, int32_t batch, int32_t seq,
                         int32_t* out_tokens, int32_t* out_segments,
                         float* out_loss_mask);

}  // extern "C"
