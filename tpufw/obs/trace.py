"""Span tracing with Chrome trace-event JSON export.

Context-manager spans around the trainer's phases (data-fetch,
step-dispatch, host-sync, checkpoint, tune-candidate) collected
in-memory and dumped as Chrome trace-event JSON (the ``traceEvents``
``"ph": "X"`` complete-event form) on close — drag the file into
https://ui.perfetto.dev or chrome://tracing and the step loop reads
like a flame chart. This is the microscope for WHERE a window's time
went; XProf (``utils/profiling.py``) stays the microscope for what
the devices did inside the step.

Disabled tracing must be free enough to leave the instrumentation
in the loop unconditionally: ``NullTracer.span`` returns one shared
no-op context manager — no allocation, no clock read (the <1%
per-step overhead budget is asserted in tests/test_obs.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional


class _Span:
    """Reusable-shape span context manager; one allocation per enter
    (cheap relative to the phases traced, which are >=100us)."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tracer._push(self.name)
        return self

    def __exit__(self, *exc):
        self._tracer._pop(self.name)
        self._tracer._record(self.name, self._t0, time.perf_counter(), self.args)
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Collects complete events; ``close()`` writes Perfetto-loadable
    JSON. Timestamps are microseconds on the process-local
    ``perf_counter`` clock (Chrome trace epochs are arbitrary); the
    wall-clock anchor is recorded in ``otherData`` for cross-host
    alignment."""

    def __init__(
        self,
        path: str,
        pid: int = 0,
        process_name: str = "",
        max_events: Optional[int] = None,
    ):
        self.path = path
        self.pid = pid
        self._name = process_name
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._closed = False
        # Long-running processes (the serving scheduler) trace hot
        # per-chunk spans forever: cap the buffer so memory stays
        # bounded — the trace keeps the RUN'S HEAD (startup + first
        # traffic, where compile stalls and admission bugs live) and
        # counts what it dropped.
        self._max = max_events
        self._dropped = 0
        # Observers called (name, dur_s, args) after each complete
        # span — the goodput ledger rides these instead of re-timing
        # the loop. Wiring-time mutation only.
        self.listeners: List = []
        # Open spans per thread, for the hang watchdog's "where was
        # the run wedged" dump. perf_counter start kept so the dump
        # can say how long each frame has been open.
        self._live: dict = {}

    enabled = True

    def _ts(self, t: float) -> float:
        return round((t - self._t0) * 1e6, 3)

    def _push(self, name: str) -> None:
        tid = threading.get_ident()
        with self._lock:
            self._live.setdefault(tid, []).append((name, time.perf_counter()))

    def _pop(self, name: str) -> None:
        tid = threading.get_ident()
        with self._lock:
            stack = self._live.get(tid)
            if stack and stack[-1][0] == name:
                stack.pop()
            if not stack:
                self._live.pop(tid, None)

    def live_spans(self) -> dict:
        """Snapshot of currently-open spans: thread ident ->
        [(name, open_for_s), ...] innermost last. The watchdog dumps
        this so a hang report names the wedged phase, not just the
        wedged line."""
        now = time.perf_counter()
        with self._lock:
            return {
                tid: [(name, round(now - t0, 3)) for name, t0 in stack]
                for tid, stack in self._live.items()
            }

    def _record(
        self, name: str, t0: float, t1: float, args: Optional[dict]
    ) -> None:
        ev = {
            "name": name,
            "ph": "X",
            "ts": self._ts(t0),
            "dur": round((t1 - t0) * 1e6, 3),
            "pid": self.pid,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            if self._closed:
                return
            if self._max is not None and len(self._events) >= self._max:
                self._dropped += 1
            else:
                self._events.append(ev)
        # Listeners fire even past the buffer cap (ledger accounting
        # must not stop when the trace fills) and outside the lock.
        for fn in tuple(self.listeners):
            try:
                fn(name, t1 - t0, args)
            except Exception:
                pass  # observability must never take down the run

    def span(self, name: str, **args) -> _Span:
        return _Span(self, name, args)

    def complete(self, name: str, dur_s: float, **args) -> None:
        """Record a span that just ENDED, ``dur_s`` long — for phases
        whose duration is measured elsewhere (e.g. ``timed_batches``
        already times the data wait; re-timing it would double-count
        the clock reads)."""
        t1 = time.perf_counter()
        self._record(name, t1 - dur_s, t1, args or None)

    def instant(self, name: str, **args) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "p",
            "ts": self._ts(time.perf_counter()),
            "pid": self.pid,
            "tid": threading.get_ident() & 0xFFFF,
        }
        if args:
            ev["args"] = args
        with self._lock:
            if self._closed:
                return
            if self._max is not None and len(self._events) >= self._max:
                self._dropped += 1
                return
            self._events.append(ev)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            events = self._events
        if self._name:
            events = [
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": self.pid,
                    "args": {"name": self._name},
                }
            ] + events
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_epoch_s": self._wall0,
                "dropped_events": self._dropped,
            },
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class NullTracer:
    """Disabled stand-in. ``span`` hands back one shared no-op context
    manager — the hot-loop cost of leaving spans in place is two
    attribute lookups and a call."""

    path = None
    enabled = False

    def span(self, name: str, **args) -> _NullSpan:
        return _NULL_SPAN

    def complete(self, name: str, dur_s: float, **args) -> None:
        pass

    def instant(self, name: str, **args) -> None:
        pass

    def live_spans(self) -> dict:
        return {}

    def close(self) -> None:
        pass


NULL = NullTracer()
