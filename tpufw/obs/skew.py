"""Multi-host skew monitor: per-host window timings + straggler events.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) observes that pod-scale regressions are dominated by
per-host skew and input stalls that fleet-averaged step times hide: in
a synchronous SPMD program one slow host IS the step time, and the
average tells you nothing about which host to go look at. This monitor
piggybacks on the sync window the trainers already pay for — once per
window (not per step) each host contributes its window wall-time and
data-wait to an allgather, every host publishes the per-host gauges,
and a ``straggler_detected`` event fires when some host's window time
exceeds the fleet median by a configurable factor.

The gather is injectable so the detection logic is testable on the
CPU backend (single process, no collectives) with synthetic skewed
timings; the default gathers via ``multihost_utils.process_allgather``
only when there is actually more than one process.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from tpufw.obs import events as events_mod
from tpufw.obs.registry import Registry

# Per-host gauges published on every host (labels, not per-host metric
# names: one dashboard query fans out over the fleet).
HOST_WINDOW_GAUGE = "tpufw_train_host_window_seconds"
HOST_WAIT_GAUGE = "tpufw_train_host_data_wait_seconds"
STRAGGLER_COUNTER = "tpufw_train_stragglers_total"

GatherFn = Callable[[Sequence[float]], List[Sequence[float]]]


def _default_gather(row: Sequence[float]) -> List[Sequence[float]]:
    import jax

    if jax.process_count() == 1:
        return [row]
    from jax.experimental import multihost_utils

    import numpy as np

    gathered = multihost_utils.process_allgather(
        np.asarray(row, dtype=np.float64)
    )
    return [list(map(float, r)) for r in gathered]


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


class SkewMonitor:
    """Record per-host window timings; emit straggler events.

    factor:    a host is a straggler when its window time exceeds
               ``factor * median`` across hosts.
    min_gap_s: AND exceeds the median by this many seconds — tiny
               windows (compile-cache-warm CPU smoke runs) would
               otherwise flag scheduler noise as stragglers.
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        events=None,
        factor: float = 2.0,
        min_gap_s: float = 0.05,
        gather: Optional[GatherFn] = None,
    ):
        if factor <= 1.0:
            raise ValueError(f"straggler factor must be > 1, got {factor}")
        self.registry = registry
        self.events = events if events is not None else events_mod.NULL
        self.factor = factor
        self.min_gap_s = min_gap_s
        self._gather = gather or _default_gather

    def record(
        self, step: int, window_time_s: float, data_wait_s: float
    ) -> List[int]:
        """Contribute this host's window to the fleet view; returns
        the straggler host indices (empty when healthy). Collective:
        in multi-host runs every process must call this at the same
        step, which the sync-window call site guarantees."""
        rows = self._gather((float(window_time_s), float(data_wait_s)))
        times = [r[0] for r in rows]
        waits = [r[1] for r in rows]
        if self.registry is not None:
            wg = self.registry.gauge(
                HOST_WINDOW_GAUGE, "per-host sync-window wall time"
            )
            dg = self.registry.gauge(
                HOST_WAIT_GAUGE, "per-host per-step input-pipeline wait"
            )
            for h, (t, w) in enumerate(zip(times, waits)):
                wg.set(t, host=h)
                dg.set(w, host=h)
        med = _median(times)
        cut = max(med * self.factor, med + self.min_gap_s)
        stragglers = [h for h, t in enumerate(times) if t > cut]
        if stragglers:
            if self.registry is not None:
                self.registry.counter(
                    STRAGGLER_COUNTER,
                    "windows in which at least one host straggled",
                ).inc()
            self.events.emit(
                "straggler_detected",
                level="warn",
                step=step,
                straggler_hosts=stragglers,
                host_window_s=[round(t, 6) for t in times],
                host_data_wait_s=[round(w, 6) for w in waits],
                median_s=round(med, 6),
                factor=self.factor,
            )
        return stragglers
