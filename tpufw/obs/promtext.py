"""Tolerant Prometheus text-exposition parser + bit-exact renderer.

The fleet collector (``tpufw.obs.fleet``) scrapes ``/metrics``
endpoints it does not control mid-write, mid-restart, and mid-version
-skew — so the parser is *tolerant*: any line that does not parse is
dropped, never raised. The renderer is the opposite: it re-emits a
parsed document byte-for-byte, and the round trip against
``Registry.render()``'s own exposition is pinned by tests — which is
what keeps this module and ``registry.py`` from drifting into two
dialects of the same format.

Shape model: a document is an ordered list of ``Family`` (one ``#
HELP``/``# TYPE`` header group), each holding ordered ``Sample`` rows.
Histogram families own their ``_bucket``/``_sum``/``_count`` samples.
Label order inside a sample is preserved as scraped; ``sample_key``
produces the *canonical* (sorted-label) form the series store keys on.

Stdlib only, jax-free — importable from the collector daemon and bare
CI containers alike.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from tpufw.obs.registry import _fmt, escape_help, escape_label_value

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")

#: Sample-name suffixes a typed family may own beyond its bare name.
_FAMILY_SUFFIXES = {
    "histogram": ("_bucket", "_sum", "_count"),
    "summary": ("_sum", "_count"),
}


def _unescape(s: str, quoted: bool = False) -> str:
    """Invert exposition escaping: ``\\\\`` -> ``\\``, ``\\n`` ->
    newline, and (inside quoted label values only) ``\\"`` -> ``"``."""
    if "\\" not in s:
        return s
    out: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if quoted and nxt == '"':
                out.append('"')
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def format_value(v: float) -> str:
    """Exposition value text matching ``registry._fmt``, extended with
    the spec spellings for non-finite floats (the registry never emits
    those, but a scraped document may round-trip them)."""
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return _fmt(v)


@dataclass
class Sample:
    """One exposition row. ``labels`` keep scrape order; ``raw`` is
    the value text exactly as scraped (the renderer re-emits it, so
    float formatting can never drift through a round trip)."""

    name: str
    labels: Tuple[Tuple[str, str], ...]
    value: float
    raw: str = ""
    timestamp: str = ""

    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)

    def key(self) -> str:
        return sample_key(self.name, dict(self.labels))


@dataclass
class Family:
    """A ``# HELP``/``# TYPE`` header group and its samples. ``help``
    is the *unescaped* text; ``None`` means no HELP line was seen
    (distinct from an empty one, for bit-exact re-rendering)."""

    name: str
    kind: str = ""
    help: Optional[str] = None
    samples: List[Sample] = field(default_factory=list)

    def owns(self, sample_name: str) -> bool:
        if sample_name == self.name:
            return True
        for suffix in _FAMILY_SUFFIXES.get(self.kind, ()):
            if sample_name == self.name + suffix:
                return True
        return False


def sample_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical series key: name + sorted, escaped labels — the
    exposition spelling the registry itself would use, so store keys
    and scraped lines agree char-for-char."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"'
        for k, v in sorted(labels.items())
    )
    return f"{name}{{{inner}}}"


def parse_sample_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Invert ``sample_key`` (tolerant: a bare name parses as no
    labels; malformed label blocks yield whatever prefix parsed)."""
    brace = key.find("{")
    if brace < 0:
        return key, {}
    labels, _ = _parse_labels(key[brace:])
    return key[:brace], dict(labels)


def _parse_labels(
    s: str,
) -> Tuple[Tuple[Tuple[str, str], ...], Optional[int]]:
    """Parse ``{k="v",...}`` at the start of ``s``. Returns
    (label pairs, index just past the closing brace) — index ``None``
    when the block is malformed (caller drops the line)."""
    assert s[0] == "{"
    pairs: List[Tuple[str, str]] = []
    i = 1
    while True:
        while i < len(s) and s[i] in " \t":
            i += 1
        if i < len(s) and s[i] == "}":
            return tuple(pairs), i + 1
        m = _NAME_RE.match(s, i)
        if m is None:
            return tuple(pairs), None
        name = m.group(0)
        i = m.end()
        while i < len(s) and s[i] in " \t":
            i += 1
        if i >= len(s) or s[i] != "=":
            return tuple(pairs), None
        i += 1
        while i < len(s) and s[i] in " \t":
            i += 1
        if i >= len(s) or s[i] != '"':
            return tuple(pairs), None
        i += 1
        buf: List[str] = []
        while i < len(s):
            c = s[i]
            if c == "\\" and i + 1 < len(s):
                nxt = s[i + 1]
                if nxt == "\\":
                    buf.append("\\")
                elif nxt == "n":
                    buf.append("\n")
                elif nxt == '"':
                    buf.append('"')
                else:
                    buf.append(c)
                    buf.append(nxt)
                i += 2
                continue
            if c == '"':
                break
            buf.append(c)
            i += 1
        else:
            return tuple(pairs), None  # unterminated value
        i += 1  # past closing quote
        pairs.append((name, "".join(buf)))
        while i < len(s) and s[i] in " \t":
            i += 1
        if i < len(s) and s[i] == ",":
            i += 1
            continue
        if i < len(s) and s[i] == "}":
            return tuple(pairs), i + 1
        return tuple(pairs), None


def _parse_sample_line(line: str) -> Optional[Sample]:
    m = _NAME_RE.match(line)
    if m is None:
        return None
    name = m.group(0)
    rest = line[m.end():]
    labels: Tuple[Tuple[str, str], ...] = ()
    if rest.startswith("{"):
        labels, end = _parse_labels(rest)
        if end is None:
            return None
        rest = rest[end:]
    parts = rest.split()
    if not parts or len(parts) > 2:
        return None
    raw = parts[0]
    try:
        value = float(raw)
    except ValueError:
        return None
    return Sample(
        name=name,
        labels=labels,
        value=value,
        raw=raw,
        timestamp=parts[1] if len(parts) == 2 else "",
    )


def parse(text: str) -> List[Family]:
    """Parse an exposition document into ordered families. Tolerant:
    unparseable lines (torn writes, foreign comment syntax) are
    dropped; samples with no preceding TYPE get an untyped family of
    their own."""
    families: List[Family] = []
    current: Optional[Family] = None
    for line in text.split("\n"):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "HELP":
                name = parts[2]
                help_text = _unescape(parts[3]) if len(parts) > 3 else ""
                if current is None or current.name != name:
                    current = Family(name)
                    families.append(current)
                current.help = help_text
            elif len(parts) >= 4 and parts[1] == "TYPE":
                name = parts[2]
                if current is None or current.name != name:
                    current = Family(name)
                    families.append(current)
                current.kind = parts[3]
            # other comments: dropped (tolerance over fidelity)
            continue
        sample = _parse_sample_line(line)
        if sample is None:
            continue
        if current is None or not current.owns(sample.name):
            current = Family(sample.name)
            families.append(current)
        current.samples.append(sample)
    return families


def render(families: Iterable[Family]) -> str:
    """Re-emit families as exposition text. Raw value text and label
    order are preserved, so ``render(parse(x))`` is byte-identical for
    any ``x`` the registry produced."""
    lines: List[str] = []
    for fam in families:
        if fam.help is not None:
            lines.append(f"# HELP {fam.name} {escape_help(fam.help)}")
        if fam.kind:
            lines.append(f"# TYPE {fam.name} {fam.kind}")
        for s in fam.samples:
            if s.labels:
                inner = ",".join(
                    f'{k}="{escape_label_value(v)}"' for k, v in s.labels
                )
                head = f"{s.name}{{{inner}}}"
            else:
                head = s.name
            raw = s.raw if s.raw else format_value(s.value)
            line = f"{head} {raw}"
            if s.timestamp:
                line += f" {s.timestamp}"
            lines.append(line)
    return "\n".join(lines) + "\n" if lines else ""


def flatten(
    text_or_families, *, drop_buckets: bool = True
) -> Dict[str, float]:
    """Canonical-key -> value map of a document, the shape the series
    store records. Histogram ``_bucket`` rows are dropped by default
    (their cardinality would dominate every record; ``_sum``/``_count``
    carry the rate math the fleet layer actually uses)."""
    families = (
        parse(text_or_families)
        if isinstance(text_or_families, str)
        else text_or_families
    )
    out: Dict[str, float] = {}
    for fam in families:
        for s in fam.samples:
            if drop_buckets and s.name.endswith("_bucket") and any(
                k == "le" for k, _ in s.labels
            ):
                continue
            out[s.key()] = s.value
    return out
