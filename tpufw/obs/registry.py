"""Thread-safe metrics registry with Prometheus text exposition.

One registry per process, shared by every subsystem that wants a
number scraped: the trainer loop (step time / MFU / data-wait), the
serving stack (request/tick counters — ``serve.py`` renders its
``/metrics`` endpoint from here), and the tune runner. Counters,
gauges, and fixed-bucket histograms only — the subset Prometheus'
text format can express without a client library, matching the
device-plugin shim's hand-rolled exposition that the rest of the
repo already mimics.

Design points carried over from ``serve.py``'s retired ``_Metrics``:

- values render via ``repr``, not ``%g`` — ``%g`` rounds to 6
  significant digits, which stalls large counters (``rate()`` then
  reads 0 until a 10-unit jump);
- counters can be pre-registered at 0 so alerts on
  ``increase(...)`` see a real 0-valued series before the first
  increment, not an absent one.

Gauges additionally accept a callback (``set_function``) evaluated
at scrape time, for point-in-time values like queue depth that have
one source of truth elsewhere.

Stdlib only (``threading`` + ``http.server``): must import in every
context the trainer runs in, including bare CI containers.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Sequence, Tuple

# Prometheus text exposition content type (version pinned by spec).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Default buckets for time-in-seconds histograms: step times live in
# the 10ms..minutes range, data waits in the sub-ms..seconds range;
# the union covers both without a per-metric bucket debate.
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _fmt(v: float) -> str:
    return str(int(v)) if v == int(v) else repr(v)


def escape_help(s: str) -> str:
    """HELP-line escaping per the text-format spec: backslash and
    newline only (quotes are legal verbatim in HELP text)."""
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(s: str) -> str:
    """Label-value escaping per the text-format spec: backslash,
    double-quote, newline. Without this a label value containing a
    quote tears the exposition line for every conformant parser."""
    return (
        s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key: LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{escape_label_value(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """Base: one named metric, possibly with labeled children."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[LabelKey, float] = {}

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def _header(self) -> list:
        lines = []
        if self.help:
            lines.append(
                f"# HELP {self.name} {escape_help(self.help)}"
            )
        lines.append(f"# TYPE {self.name} {self.kind}")
        return lines

    def render(self) -> list:
        with self._lock:
            values = dict(self._values)
        lines = self._header()
        for key in sorted(values):
            lines.append(
                f"{self.name}{_label_str(key)} {_fmt(values[key])}"
            )
        return lines


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        # Pre-initialized unlabeled series (absent-series rationale
        # above); labeled children appear on first inc.
        self._values[()] = 0.0

    def inc(self, v: float = 1.0, **labels) -> None:
        if v < 0:
            raise ValueError(f"counter {self.name}: negative inc {v}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def reset(self, **labels) -> None:
        """Zero a series — for code that must be invisible to
        scrapes, e.g. serve warmup ticks that run before the
        listener binds."""
        with self._lock:
            self._values[_label_key(labels)] = 0.0


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Evaluate ``fn`` at scrape time (point-in-time values with
        one source of truth elsewhere, e.g. queue depth)."""
        with self._lock:
            self._fn = fn

    def render(self) -> list:
        with self._lock:
            values = dict(self._values)
            fn = self._fn
        if fn is not None:
            try:
                values[()] = float(fn())
            except Exception:  # noqa: BLE001 — scrape must not 500
                pass
        lines = self._header()
        for key in sorted(values):
            lines.append(
                f"{self.name}{_label_str(key)} {_fmt(values[key])}"
            )
        return lines


class Histogram(_Metric):
    """Fixed-bucket histogram (cumulative ``le`` buckets + ``_sum`` /
    ``_count``), the exposition-format shape Prometheus' histogram_
    quantile expects."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError(f"histogram {self.name}: empty buckets")
        self._bucket_counts: Dict[LabelKey, list] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._counts: Dict[LabelKey, int] = {}

    def observe(self, v: float, n: int = 1, **labels) -> None:
        """Record ``v``; ``n > 1`` records it n times in one locked
        update — the sync-window case, where one host sync carries a
        window of n per-step averages (sum and count then aggregate
        exactly; only the bucket spread is collapsed to the mean)."""
        key = _label_key(labels)
        with self._lock:
            counts = self._bucket_counts.get(key)
            if counts is None:
                counts = [0] * (len(self.buckets) + 1)  # +Inf last
                self._bucket_counts[key] = counts
            # Linear scan: bucket lists are short (~17) and observe
            # sits off the hot path (once per sync window).
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    counts[i] += n
                    break
            else:
                counts[len(self.buckets)] += n
            self._sums[key] = self._sums.get(key, 0.0) + v * n
            self._counts[key] = self._counts.get(key, 0) + n

    def value(self, **labels) -> float:
        """Histogram 'value' is its observation count."""
        with self._lock:
            return float(self._counts.get(_label_key(labels), 0))

    def reset(self, **labels) -> None:
        """Drop a series — the histogram counterpart of
        ``Counter.reset`` (serve warmup must be invisible to
        scrapes; buckets/sum/count all return to zero)."""
        key = _label_key(labels)
        with self._lock:
            self._bucket_counts.pop(key, None)
            self._sums.pop(key, None)
            self._counts.pop(key, None)

    def render(self) -> list:
        with self._lock:
            bucket_counts = {
                k: list(v) for k, v in self._bucket_counts.items()
            }
            sums = dict(self._sums)
            counts = dict(self._counts)
        lines = self._header()
        for key in sorted(counts):
            cum = 0
            for i, ub in enumerate(self.buckets):
                cum += bucket_counts[key][i]
                le = _label_str(key, f'le="{_fmt(ub)}"')
                lines.append(f"{self.name}_bucket{le} {cum}")
            cum += bucket_counts[key][len(self.buckets)]
            le = _label_str(key, 'le="+Inf"')
            lines.append(f"{self.name}_bucket{le} {cum}")
            lines.append(
                f"{self.name}_sum{_label_str(key)} {_fmt(sums[key])}"
            )
            lines.append(f"{self.name}_count{_label_str(key)} {cum}")
        return lines


class Registry:
    """Named metrics, one instance per kind; get-or-create accessors
    so call sites never coordinate creation order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, *args, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Sequence[float] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets)

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for _, m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


class _MetricsHandler(BaseHTTPRequestHandler):
    registry: Registry  # set on the server class by start_http_server

    def do_GET(self):  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path.rstrip("/") == "/debug/profile":
            self._handle_profile(query)
            return
        if path not in ("/metrics", "/metrics/"):
            self.send_error(404)
            return
        body = self.server.registry.render().encode()  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _handle_profile(self, query: str) -> None:
        """``GET /debug/profile?seconds=N`` — kick a time-bounded
        jax.profiler capture via the mounted ProfileTrigger
        (tpufw.obs.perf); 404 when no trigger is mounted (no
        telemetry dir to drop the trace into), 409 while one is
        already running."""
        import json
        from urllib.parse import parse_qs

        trigger = getattr(self.server, "profiler", None)
        if trigger is None:
            self.send_error(404)
            return
        try:
            seconds = float(
                parse_qs(query).get("seconds", ["2.0"])[0]
            )
        except ValueError:
            seconds = 2.0
        result = trigger.trigger(seconds)
        body = json.dumps(result).encode()
        self.send_response(409 if "error" in result else 200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes are not log events
        pass


def start_http_server(
    registry: Registry, port: int, host: str = "0.0.0.0", profiler=None
) -> ThreadingHTTPServer:
    """Serve ``registry`` at ``/metrics`` on ``port`` (0 = ephemeral;
    bound port is ``server.server_address[1]``) from a daemon thread.
    Caller owns shutdown(). ``profiler`` (a tpufw.obs.perf
    ProfileTrigger) additionally mounts ``/debug/profile``."""
    httpd = ThreadingHTTPServer((host, port), _MetricsHandler)
    httpd.registry = registry  # type: ignore[attr-defined]
    httpd.profiler = profiler  # type: ignore[attr-defined]
    threading.Thread(
        target=httpd.serve_forever, daemon=True, name="obs-metrics"
    ).start()
    return httpd
