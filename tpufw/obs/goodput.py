"""Goodput/badput ledger: every second of run wall-clock attributed
to exactly one category.

At pod scale the dominant losses are not slow steps but *non-step
time* — compile, restart replay, checkpoint stalls, data starvation —
so the first question after any run is "what fraction of wall-clock
was productive training?". The ledger answers it by riding the
telemetry the loop already produces: Tracer span completions map to
categories through a per-workload table (``TRAIN_SPAN_CATEGORIES`` /
``SERVE_SPAN_CATEGORIES``), EventLog events drive replay detection,
and explicit ``add()`` covers phases with no span (the serve
scheduler's busy/wasted-slot split). Whatever is not attributed is
``idle`` by construction, so the categories always sum to the run's
wall-clock exactly.

Replay: after a non-graceful restart the trainer re-trains steps it
already paid for (everything past the last checkpoint). The ledger
scans the previous run's events (the JSONL file is opened append-mode,
so a restart into the same telemetry dir sees its predecessor's
``step`` events) for the max step reached; if this run resumes from a
checkpoint *behind* that high-water mark, productive time is booked
as ``replay`` until the run passes it. A graceful preemption
(checkpoint at the stop step) replays nothing.

Exposed three ways: ``tpufw_goodput_ratio`` gauge +
``tpufw_badput_seconds_total{category=...}`` counter on the shared
registry, a ``goodput`` event at close, and a per-run
``goodput.json`` rollup in the telemetry dir.

Stdlib only; all methods are safe to call from span/event listeners,
including listeners invoked inside signal handlers (the lock is
reentrant for that reason — a SIGTERM can land while the victim
thread holds it via a span completion).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, Mapping, Optional

# Trainer-loop spans -> ledger categories. The trainer's spans do not
# nest (each loop phase closes before the next opens), so summing
# their durations never double-counts. ``checkpoint_wait`` /
# ``checkpoint_restore`` come from CheckpointManager itself (the
# async-save drain and the resume restore are not wrapped by the
# loop's own ``checkpoint`` span).
TRAIN_SPAN_CATEGORIES: Dict[str, str] = {
    "tune": "compile",
    "data_fetch": "data_wait",
    "step_dispatch": "productive",
    "host_sync": "productive",
    "eval": "eval",
    "checkpoint": "checkpoint",
    "checkpoint_wait": "checkpoint",
    "checkpoint_restore": "checkpoint",
    "preemption_sync": "preemption",
}

# Serve spans -> categories. ``serve_admit`` is deliberately ABSENT:
# it nests ``serve_prefill`` inside itself, so counting both would
# double-book the prefill seconds. ``serve_decode_chunk`` is also
# absent — the scheduler splits each chunk into busy/wasted_slot
# explicitly via ``add()`` using the live-token fraction, which a
# name->category table cannot express.
SERVE_SPAN_CATEGORIES: Dict[str, str] = {
    "serve_pool_build": "compile",
    "serve_prefill": "busy",
}

# Categories counted as goodput (numerator of tpufw_goodput_ratio).
TRAIN_PRODUCTIVE = ("productive",)
SERVE_PRODUCTIVE = ("busy",)


def rollup_path(telemetry_dir: str, process: int = 0) -> str:
    name = "goodput.json" if process == 0 else f"goodput-p{process}.json"
    return os.path.join(telemetry_dir, name)


def _prior_max_step(events_path: Optional[str]) -> int:
    """High-water ``step`` from a previous run's events in the same
    file (append-mode survivors). 0 when there is no history."""
    if not events_path or not os.path.exists(events_path):
        return 0
    from tpufw.obs.events import read_events

    best = 0
    try:
        for ev in read_events(events_path):
            if ev.get("kind") == "step":
                try:
                    best = max(best, int(ev.get("step", 0)))
                except (TypeError, ValueError):
                    continue
    except OSError:
        return 0
    return best


class GoodputLedger:
    """Attributes run wall-clock to exclusive categories; see module
    docstring. One instance per process, owned by ``Telemetry``."""

    def __init__(
        self,
        registry=None,
        events=None,
        span_categories: Optional[Mapping[str, str]] = None,
        productive: Iterable[str] = TRAIN_PRODUCTIVE,
        out_path: Optional[str] = None,
        prior_events_path: Optional[str] = None,
    ):
        self._registry = registry
        self._events = events
        self._span_cats = dict(
            TRAIN_SPAN_CATEGORIES if span_categories is None
            else span_categories
        )
        self._productive = frozenset(productive)
        self._out_path = out_path
        # RLock: listeners run inside EventLog.emit, and emit can
        # happen from a signal handler that interrupted a thread
        # already inside the ledger (span completion). A plain Lock
        # would deadlock that thread against itself.
        self._lock = threading.RLock()
        self._t0 = time.monotonic()
        self._wall0 = time.time()
        self._seconds: Dict[str, float] = {}
        self._published: Dict[str, float] = {}
        self._closed = False
        # Replay detection state (module docstring): armed by the
        # run_start event only when this run resumes mid-history.
        self._prior_max = _prior_max_step(prior_events_path)
        self._replay_until = 0
        self._last_step = 0

    # -- attribution ---------------------------------------------------

    def add(self, category: str, seconds: float) -> None:
        """Book ``seconds`` of wall-clock to ``category``. The direct
        entry point for phases with no span (serve chunk splits)."""
        if seconds <= 0:
            return
        with self._lock:
            if self._closed:
                return
            self._seconds[category] = (
                self._seconds.get(category, 0.0) + seconds
            )

    def on_span(self, name: str, dur_s: float, args=None) -> None:
        """Tracer listener: span completion -> category."""
        cat = self._span_cats.get(name)
        if cat is None:
            return
        if cat in self._productive and self._last_step < self._replay_until:
            # Still re-training steps a previous incarnation already
            # paid for: productive only in the thermodynamic sense.
            cat = "replay"
        self.add(cat, dur_s)

    def on_event(self, event: dict) -> None:
        """EventLog listener: step progress + replay arming."""
        kind = event.get("kind")
        if kind == "step":
            try:
                step = int(event.get("step", 0))
            except (TypeError, ValueError):
                return
            with self._lock:
                self._last_step = max(self._last_step, step)
        elif kind == "run_start":
            try:
                start = int(event.get("start_step", 0) or 0)
            except (TypeError, ValueError):
                start = 0
            with self._lock:
                # start_step == 0 is a fresh run reusing the dir, not
                # a restart — its steps are first-time work even if
                # an older run got further.
                if start > 0 and self._prior_max > start:
                    self._replay_until = self._prior_max
                self._last_step = max(self._last_step, start)

    # -- reporting -----------------------------------------------------

    def rollup(self) -> dict:
        """Point-in-time rollup; ``idle`` absorbs the unattributed
        remainder so categories sum to ``wall_s`` exactly (unless
        attribution overlapped, in which case idle floors at 0)."""
        with self._lock:
            wall = time.monotonic() - self._t0
            cats = dict(self._seconds)
        attributed = sum(cats.values())
        cats["idle"] = max(0.0, wall - attributed)
        good = sum(v for k, v in cats.items() if k in self._productive)
        return {
            "wall_s": round(wall, 6),
            "start_ts": round(self._wall0, 6),
            "goodput_ratio": round(good / wall, 6) if wall > 0 else 0.0,
            "categories": {k: round(v, 6) for k, v in sorted(cats.items())},
            "replay_until_step": self._replay_until,
            "last_step": self._last_step,
        }

    def publish(self) -> dict:
        """Push the current rollup into the registry. Counters only
        move forward, so each category's *delta* since the last
        publish is inc'd (idle can shrink retroactively when a long
        span closes; that delta clamps at 0 and catches up later).
        Returns the rollup it published."""
        roll = self.rollup()
        if self._registry is not None:
            self._registry.gauge(
                "tpufw_goodput_ratio",
                "fraction of run wall-clock spent in productive work",
            ).set(roll["goodput_ratio"])
            badput = self._registry.counter(
                "tpufw_badput_seconds_total",
                "wall-clock seconds lost to non-productive categories",
            )
            with self._lock:
                for cat, secs in roll["categories"].items():
                    if cat in self._productive:
                        continue
                    delta = secs - self._published.get(cat, 0.0)
                    if delta > 0:
                        badput.inc(delta, category=cat)
                        self._published[cat] = secs
        return roll

    def close(self, extra: Optional[dict] = None) -> dict:
        """Final publish + ``goodput`` event + ``goodput.json``.
        Idempotent; returns the final rollup. ``extra`` (e.g. the perf
        observatory's end-of-run MFU attribution) is merged into both
        the event and the JSON rollup — utilization next to the
        goodput ratio is the one-line answer to "was the run slow
        because of badput or because of the program"."""
        with self._lock:
            if self._closed:
                return self.rollup()
        roll = self.publish()
        if extra:
            roll.update(extra)
        with self._lock:
            self._closed = True
        if self._events is not None:
            try:
                self._events.emit(
                    "goodput",
                    wall_s=roll["wall_s"],
                    goodput_ratio=roll["goodput_ratio"],
                    categories=roll["categories"],
                    **(extra or {}),
                )
            except Exception:
                pass  # closing telemetry must not mask the run's exit
        if self._out_path:
            try:
                os.makedirs(
                    os.path.dirname(self._out_path) or ".", exist_ok=True
                )
                tmp = self._out_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(roll, f, indent=2, sort_keys=True)
                os.replace(tmp, self._out_path)
            except OSError:
                pass
        return roll


class NullGoodputLedger:
    """Disabled stand-in: every method a constant-time no-op so the
    instrumented call sites never branch."""

    def add(self, category: str, seconds: float) -> None:
        pass

    def on_span(self, name: str, dur_s: float, args=None) -> None:
        pass

    def on_event(self, event: dict) -> None:
        pass

    def rollup(self) -> dict:
        return {}

    def publish(self) -> dict:
        return {}

    def close(self, extra=None) -> dict:
        return {}


NULL = NullGoodputLedger()
