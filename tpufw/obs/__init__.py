"""Unified telemetry: metrics registry + event log + span tracing +
skew monitoring behind one handle.

The trainer stack previously measured itself through three
disconnected mechanisms — ``train/metrics.py``'s ``Meter`` (stdout
JSON), ``serve.py``'s private Prometheus class, and the XProf wrapper
— with no shared registry and no way to see WHY a headline number
regressed. ``tpufw/obs`` is the shared layer:

- :mod:`tpufw.obs.registry` — thread-safe counters/gauges/histograms,
  Prometheus text exposition, stdlib HTTP endpoint
  (``TPUFW_METRICS_PORT`` for trainers; ``serve.py``'s ``/metrics``
  renders the same registry).
- :mod:`tpufw.obs.events` — schema'd JSONL event log, per host.
- :mod:`tpufw.obs.trace` — context-manager spans, Chrome trace-event
  JSON (Perfetto-loadable).
- :mod:`tpufw.obs.skew` — per-host window gauges + straggler events,
  piggybacked on the sync window.

``Telemetry.create(...)`` wires all four from TrainerConfig /
``TPUFW_TELEMETRY_DIR`` / ``TPUFW_METRICS_PORT``;
``Telemetry.disabled()`` hands back null components cheap enough to
leave the instrumentation in the hot loop unconditionally (asserted
<1% per-step in tests/test_obs.py).
"""

from __future__ import annotations

import os
from typing import Optional

from tpufw.obs import events as events_mod
from tpufw.obs import goodput as goodput_mod
from tpufw.obs import perf as perf_mod
from tpufw.obs import trace as trace_mod
from tpufw.obs.health import NULL_WATCHDOG, FlightRecorder, HangWatchdog
from tpufw.obs.registry import Registry, start_http_server
from tpufw.obs.skew import SkewMonitor

__all__ = [
    "FlightRecorder",
    "HangWatchdog",
    "Registry",
    "SkewMonitor",
    "Telemetry",
    "start_http_server",
]


def _jax_ids():
    """(process_index, process_count) if jax is importable and
    initialized enough to ask; (0, 1) otherwise. Lazy: obs must not
    drag jax in for stdlib users (serve's HTTP thread, obs_summary)."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 — uninitialized backend etc.
        return 0, 1


class Telemetry:
    """One handle bundling registry/events/tracer/skew. Components
    degrade independently: a metrics port without a telemetry dir
    serves scrapes but writes no files, and vice versa."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        events=None,
        tracer=None,
        skew: Optional[SkewMonitor] = None,
        server=None,
        out_dir: Optional[str] = None,
        goodput=None,
        watchdog=None,
        recorder: Optional[FlightRecorder] = None,
        perf=None,
        profiler=None,
    ):
        self.registry = registry
        self.events = events if events is not None else events_mod.NULL
        self.tracer = tracer if tracer is not None else trace_mod.NULL
        self.skew = skew
        self.server = server
        self.out_dir = out_dir
        self.goodput = goodput if goodput is not None else goodput_mod.NULL
        self.watchdog = watchdog if watchdog is not None else NULL_WATCHDOG
        self.recorder = recorder
        self.perf = perf if perf is not None else perf_mod.NULL
        self.profiler = profiler
        self._closed = False

    @property
    def enabled(self) -> bool:
        return self.registry is not None

    @property
    def bound_port(self) -> Optional[int]:
        """Actual metrics port (resolves port 0 to the ephemeral bind)."""
        return None if self.server is None else self.server.server_address[1]

    @staticmethod
    def disabled() -> "Telemetry":
        return _NULL

    @staticmethod
    def create(
        telemetry_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        straggler_factor: float = 2.0,
        role: str = "train",
        gather=None,
        registry: Optional[Registry] = None,
        trace_name: Optional[str] = None,
        trace_max_events: Optional[int] = None,
    ) -> "Telemetry":
        """Build telemetry from config knobs. All-None knobs return
        the shared disabled singleton. ``metrics_port=0`` binds an
        ephemeral port (tests); None means no server. ``role``
        prefixes the trace/process naming so multi-role hosts
        (train + eval) stay distinguishable in Perfetto, selects the
        span->goodput-category table, and decides whether the flight
        recorder's SIGTERM hook terminates (serve: yes — nothing
        above it handles the signal; train: no — GracefulShutdown
        owns the grace-window exit). Pass ``registry`` to mount the
        telemetry on an existing registry (serve's ``/metrics``
        renders its own); ``trace_name``/``trace_max_events``
        override the per-process defaults.

        The run-health layer rides along when a telemetry dir is
        given: a goodput ledger (always), a flight recorder
        (``TPUFW_CRASH_BUNDLE``, default on), and a hang watchdog
        (``TPUFW_HANG_TIMEOUT_S`` > 0)."""
        if telemetry_dir is None and metrics_port is None:
            return _NULL
        from tpufw.workloads.env import (
            env_bool,
            env_float,
            env_int,
        )

        proc, nprocs = _jax_ids()
        if registry is None:
            registry = Registry()
        events = events_mod.NULL
        tracer = trace_mod.NULL
        ledger = None
        watchdog = None
        recorder = None
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            events_path = events_mod.log_path(telemetry_dir, proc)
            # Ledger first, so it can read the PREVIOUS run's step
            # high-water mark out of the append-mode events file
            # (replay detection) — order relative to EventLog does
            # not actually matter (append never truncates), but
            # scanning before this run writes anything is clearest.
            serve = role == "serve"
            ledger = goodput_mod.GoodputLedger(
                registry=registry,
                span_categories=(
                    goodput_mod.SERVE_SPAN_CATEGORIES
                    if serve
                    else goodput_mod.TRAIN_SPAN_CATEGORIES
                ),
                productive=(
                    goodput_mod.SERVE_PRODUCTIVE
                    if serve
                    else goodput_mod.TRAIN_PRODUCTIVE
                ),
                out_path=goodput_mod.rollup_path(telemetry_dir, proc),
                prior_events_path=events_path,
            )
            events = events_mod.EventLog(
                events_path, host=proc, process=proc
            )
            ledger._events = events
            if trace_name is None:
                trace_name = (
                    "trace.json" if proc == 0 else f"trace-p{proc}.json"
                )
            tracer = trace_mod.Tracer(
                os.path.join(telemetry_dir, trace_name),
                pid=proc,
                process_name=f"{role}:p{proc}/{nprocs}",
                max_events=trace_max_events,
            )
            tracer.listeners.append(ledger.on_span)
            events.listeners.append(ledger.on_event)
            if env_bool("crash_bundle", True):
                recorder = FlightRecorder(
                    telemetry_dir,
                    proc=proc,
                    ring_size=max(1, env_int("flight_ring", 256)),
                    registry=registry,
                    tracer=tracer,
                    terminate_on_sigterm=serve,
                )
                events.listeners.append(recorder.on_event)
                recorder.install()
            hang_timeout = env_float("hang_timeout_s", 0.0)
            if hang_timeout > 0:
                watchdog = HangWatchdog(
                    hang_timeout,
                    telemetry_dir,
                    proc=proc,
                    tracer=tracer,
                    events=events,
                    recorder=recorder,
                    abort=env_bool("hang_abort", False),
                )
        skew = SkewMonitor(
            registry=registry,
            events=events,
            factor=straggler_factor,
            gather=gather,
        )
        # Perf observatory (TPUFW_PERF_OBS, default on): compiled-
        # program cost harvest + roofline gauges. Gated on a telemetry
        # dir — without one there is nowhere for programs.json or the
        # profiler traces to land, and dir-less runs (most unit tests)
        # should not pay the AOT lower/compile harvest.
        perf = None
        profiler = None
        if telemetry_dir and env_bool("perf_obs", True):
            perf = perf_mod.PerfObservatory(
                registry=registry, out_dir=telemetry_dir
            )
            profiler = perf_mod.ProfileTrigger(
                os.path.join(telemetry_dir, "xprof")
            )
        server = None
        if metrics_port is not None:
            server = start_http_server(
                registry, metrics_port, profiler=profiler
            )
        tel = Telemetry(
            registry=registry,
            events=events,
            tracer=tracer,
            skew=skew,
            server=server,
            out_dir=telemetry_dir,
            goodput=ledger,
            watchdog=watchdog,
            recorder=recorder,
            perf=perf,
            profiler=profiler,
        )
        _emit_compile_cache_event(events)
        return tel

    def set_run_info(self, **labels) -> None:
        """Publish the ``tpufw_run_info`` identity gauge (value always
        1; the information is in the labels) so every scrape is
        joinable to a build: tpufw/jax versions are added here,
        callers pass backend/mesh/model. Also lands in the crash
        bundle's config.json."""
        if self.registry is None:
            return
        info = {}
        try:
            import tpufw

            info["tpufw_version"] = str(tpufw.__version__)
        except Exception:  # noqa: BLE001
            pass
        try:
            import jax

            info["jax_version"] = str(jax.__version__)
        except Exception:  # noqa: BLE001
            pass
        info.update({k: str(v) for k, v in labels.items()})
        self.registry.gauge(
            "tpufw_run_info",
            "run identity (value is always 1; labels carry the info)",
        ).set(1, **info)
        if self.recorder is not None:
            self.recorder.record_config({"run_info": info})

    def record_config(self, config: dict) -> None:
        """Stash run configuration into the flight recorder so a
        crash bundle is self-describing. No-op when disabled."""
        if self.recorder is not None:
            self.recorder.record_config(config)

    def snapshot_metrics(self) -> Optional[str]:
        """Dump the registry's current exposition text to
        ``<out_dir>/metrics.prom`` (final flush for runs nothing ever
        scraped — obs_summary reads counter totals from it)."""
        if self.registry is None or not self.out_dir:
            return None
        path = os.path.join(self.out_dir, "metrics.prom")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.registry.render())
        os.replace(tmp, path)
        return path

    def _goodput_extra(self) -> dict:
        """End-of-run utilization merged into the goodput closing
        event/JSON: the perf observatory's headline-program MFU and
        roofline attribution when harvested, else the Meter's last
        published ``tpufw_train_mfu`` gauge."""
        extra: dict = {}
        try:
            a = self.perf.attrib()
            if "measured_mfu" in a:
                extra["mfu"] = a["measured_mfu"]
                extra["mfu_program"] = a["program"]
            if "roofline_bound" in a:
                extra["roofline_bound"] = a["roofline_bound"]
            if "hbm_headroom_bytes" in a:
                extra["hbm_headroom_bytes"] = a["hbm_headroom_bytes"]
            # Peek, don't get-or-create: the fallback must not mint an
            # empty train gauge on a serve registry.
            meter_mfu = (
                self.registry._metrics.get("tpufw_train_mfu")
                if self.registry is not None
                else None
            )
            if "mfu" not in extra and meter_mfu is not None:
                mfu = meter_mfu.value()
                if mfu > 0:
                    extra["mfu"] = round(mfu, 4)
        except Exception:  # noqa: BLE001 — close must stay best-effort
            pass
        return extra

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # Order: watchdog first (a clean shutdown must not fire it),
        # then the goodput rollup (it emits an event + publishes
        # metrics, so it must precede the metrics snapshot and the
        # event-log close), then the files, then the hooks (the
        # recorder stays armed until the very end — an exception
        # inside close itself still gets a bundle).
        self.watchdog.stop()
        try:
            self.goodput.close(extra=self._goodput_extra())
        finally:
            try:
                self.perf.close()
                self.snapshot_metrics()
            finally:
                self.tracer.close()
                self.events.close()
                if self.server is not None:
                    self.server.shutdown()
                    self.server.server_close()
                if self.recorder is not None:
                    self.recorder.uninstall()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _emit_compile_cache_event(events) -> None:
    """Record whether this run starts against a warm persistent XLA
    compile cache — the cold-start-to-first-step headline is mostly
    this bit."""
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001
        return
    if not cache_dir:
        return
    try:
        warm = bool(os.listdir(cache_dir))
    except OSError:
        warm = False
    events.emit("compile_cache", dir=cache_dir, warm=warm)


# Shared disabled singleton: null events/tracer, no registry. close()
# is a no-op because _closed starts True — a workload closing the
# shared instance must not poison later users.
_NULL = Telemetry()
_NULL._closed = True
