"""Unified telemetry: metrics registry + event log + span tracing +
skew monitoring behind one handle.

The trainer stack previously measured itself through three
disconnected mechanisms — ``train/metrics.py``'s ``Meter`` (stdout
JSON), ``serve.py``'s private Prometheus class, and the XProf wrapper
— with no shared registry and no way to see WHY a headline number
regressed. ``tpufw/obs`` is the shared layer:

- :mod:`tpufw.obs.registry` — thread-safe counters/gauges/histograms,
  Prometheus text exposition, stdlib HTTP endpoint
  (``TPUFW_METRICS_PORT`` for trainers; ``serve.py``'s ``/metrics``
  renders the same registry).
- :mod:`tpufw.obs.events` — schema'd JSONL event log, per host.
- :mod:`tpufw.obs.trace` — context-manager spans, Chrome trace-event
  JSON (Perfetto-loadable).
- :mod:`tpufw.obs.skew` — per-host window gauges + straggler events,
  piggybacked on the sync window.

``Telemetry.create(...)`` wires all four from TrainerConfig /
``TPUFW_TELEMETRY_DIR`` / ``TPUFW_METRICS_PORT``;
``Telemetry.disabled()`` hands back null components cheap enough to
leave the instrumentation in the hot loop unconditionally (asserted
<1% per-step in tests/test_obs.py).
"""

from __future__ import annotations

import os
from typing import Optional

from tpufw.obs import events as events_mod
from tpufw.obs import trace as trace_mod
from tpufw.obs.registry import Registry, start_http_server
from tpufw.obs.skew import SkewMonitor

__all__ = [
    "Registry",
    "SkewMonitor",
    "Telemetry",
    "start_http_server",
]


def _jax_ids():
    """(process_index, process_count) if jax is importable and
    initialized enough to ask; (0, 1) otherwise. Lazy: obs must not
    drag jax in for stdlib users (serve's HTTP thread, obs_summary)."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 — uninitialized backend etc.
        return 0, 1


class Telemetry:
    """One handle bundling registry/events/tracer/skew. Components
    degrade independently: a metrics port without a telemetry dir
    serves scrapes but writes no files, and vice versa."""

    def __init__(
        self,
        registry: Optional[Registry] = None,
        events=None,
        tracer=None,
        skew: Optional[SkewMonitor] = None,
        server=None,
        out_dir: Optional[str] = None,
    ):
        self.registry = registry
        self.events = events if events is not None else events_mod.NULL
        self.tracer = tracer if tracer is not None else trace_mod.NULL
        self.skew = skew
        self.server = server
        self.out_dir = out_dir
        self._closed = False

    @property
    def enabled(self) -> bool:
        return self.registry is not None

    @property
    def bound_port(self) -> Optional[int]:
        """Actual metrics port (resolves port 0 to the ephemeral bind)."""
        return None if self.server is None else self.server.server_address[1]

    @staticmethod
    def disabled() -> "Telemetry":
        return _NULL

    @staticmethod
    def create(
        telemetry_dir: Optional[str] = None,
        metrics_port: Optional[int] = None,
        straggler_factor: float = 2.0,
        role: str = "train",
        gather=None,
    ) -> "Telemetry":
        """Build telemetry from config knobs. All-None knobs return
        the shared disabled singleton. ``metrics_port=0`` binds an
        ephemeral port (tests); None means no server. ``role``
        prefixes the trace/process naming so multi-role hosts
        (train + eval) stay distinguishable in Perfetto."""
        if telemetry_dir is None and metrics_port is None:
            return _NULL
        proc, nprocs = _jax_ids()
        registry = Registry()
        events = events_mod.NULL
        tracer = trace_mod.NULL
        if telemetry_dir:
            os.makedirs(telemetry_dir, exist_ok=True)
            events = events_mod.EventLog(
                events_mod.log_path(telemetry_dir, proc),
                host=proc,
                process=proc,
            )
            trace_name = (
                "trace.json" if proc == 0 else f"trace-p{proc}.json"
            )
            tracer = trace_mod.Tracer(
                os.path.join(telemetry_dir, trace_name),
                pid=proc,
                process_name=f"{role}:p{proc}/{nprocs}",
            )
        skew = SkewMonitor(
            registry=registry,
            events=events,
            factor=straggler_factor,
            gather=gather,
        )
        server = None
        if metrics_port is not None:
            server = start_http_server(registry, metrics_port)
        tel = Telemetry(
            registry=registry,
            events=events,
            tracer=tracer,
            skew=skew,
            server=server,
            out_dir=telemetry_dir,
        )
        _emit_compile_cache_event(events)
        return tel

    def snapshot_metrics(self) -> Optional[str]:
        """Dump the registry's current exposition text to
        ``<out_dir>/metrics.prom`` (final flush for runs nothing ever
        scraped — obs_summary reads counter totals from it)."""
        if self.registry is None or not self.out_dir:
            return None
        path = os.path.join(self.out_dir, "metrics.prom")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.registry.render())
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.snapshot_metrics()
        finally:
            self.tracer.close()
            self.events.close()
            if self.server is not None:
                self.server.shutdown()
                self.server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _emit_compile_cache_event(events) -> None:
    """Record whether this run starts against a warm persistent XLA
    compile cache — the cold-start-to-first-step headline is mostly
    this bit."""
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001
        return
    if not cache_dir:
        return
    try:
        warm = bool(os.listdir(cache_dir))
    except OSError:
        warm = False
    events.emit("compile_cache", dir=cache_dir, warm=warm)


# Shared disabled singleton: null events/tracer, no registry. close()
# is a no-op because _closed starts True — a workload closing the
# shared instance must not poison later users.
_NULL = Telemetry()
_NULL._closed = True
