"""Structured JSONL event log — the machine-readable replacement for
the trainer stack's ad-hoc ``print`` telemetry.

One file per host (``events.jsonl`` on process 0, ``events-p<N>.jsonl``
elsewhere — hosts share nothing, so per-host files need no cross-host
locking), one JSON object per line, every line carrying ``ts`` (unix
seconds), ``kind``, ``level``, ``host``, and ``process``. Kinds are
schema'd: ``emit`` raises on an unknown kind or a missing required
field, so producer drift is caught by the tests instead of by a
grep-shaped dashboard breaking three weeks later. Extra fields beyond
the required set are allowed — schemas here are a floor, not a ceiling.

Stdlib only; importable from signal handlers (``preemption.py`` emits
from its SIGTERM latch) and from bare CI containers.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Dict, FrozenSet, List, Optional

LEVELS = ("debug", "info", "warn", "error")

# kind -> required fields (beyond the envelope ts/kind/level/host/
# process added by emit). Floor, not ceiling.
SCHEMA: Dict[str, FrozenSet[str]] = {
    "run_start": frozenset({"workload"}),
    "run_end": frozenset({"steps"}),
    "step": frozenset({"step", "loss", "step_time_s", "data_wait_s"}),
    "eval": frozenset({"step"}),
    "checkpoint_save": frozenset({"step"}),
    "checkpoint_restore": frozenset({"step"}),
    "preemption_signal": frozenset({"signum"}),
    "preemption_stop": frozenset({"step"}),
    "tune_trial": frozenset({"trial", "status"}),
    "tune_result": frozenset({"mode", "cache_hit"}),
    "compile_cache": frozenset({"dir", "warm"}),
    "straggler_detected": frozenset(
        {"step", "straggler_hosts", "median_s", "factor"}
    ),
    "serve_request": frozenset({"rows", "new_tokens", "latency_s"}),
    "serve_pool_switch": frozenset({"cache_len", "slots"}),
    "serve_prefix": frozenset({"hit", "shared_pages", "prompt_tokens"}),
    "serve_migration": frozenset({"pages", "bytes", "wall_s"}),
    "serve_spec": frozenset({"k", "mode"}),
    "serve_prefill_chunk": frozenset(
        {"prompt_tokens", "cursor", "final"}
    ),
    # KV fabric (tpufw.infer.spill + tpufw.serve.bundle.attach_spill):
    # one record per movement across the HBM/host-RAM boundary.
    # ``entry`` is "trie" (one prefix page) or "session" (a drained
    # slot's bundle); ``direction`` is "out" (spill) or "in" (restore).
    # Page/byte/wall fields ride along where the mover knows them.
    "serve_spill": frozenset({"entry", "direction"}),
    "router_request": frozenset({"tenant", "replica", "latency_s"}),
    "router_reject": frozenset({"tenant", "reason"}),
    # A drained replica's sticky session resumed on a survivor from
    # the shared spill store (zero-divergence re-home).
    "router_rehome": frozenset({"session", "replica"}),
    "slo_violation": frozenset(
        {"tenant", "metric", "value_ms", "target_ms"}
    ),
    "goodput": frozenset({"wall_s", "goodput_ratio"}),
    "hang": frozenset({"timeout_s", "armed_for_s"}),
    # Fleet observatory (tpufw.obs.fleet): alert-rule transitions and
    # the scaling decisions sustained alerts turn into.
    "fleet_alert": frozenset({"rule", "state", "series", "value"}),
    "fleet_recommendation": frozenset({"pools", "reason", "artifact"}),
    # Load observatory (tpufw.load): executor action applying a
    # scaling decision (add/remove/skipped/recovered/error), and a
    # sweep/smoke phase boundary (rung-N, burst, idle, done).
    "scale_action": frozenset({"pool", "action", "replica"}),
    "load_phase": frozenset({"phase"}),
}


def validate(event: dict) -> None:
    """Raise ValueError unless ``event`` is a well-formed logged line
    (envelope + per-kind required fields). Used by emit on the way
    out and by tests/readers on the way in."""
    for field in ("ts", "kind", "level", "host", "process"):
        if field not in event:
            raise ValueError(f"event missing envelope field {field!r}")
    kind = event["kind"]
    if kind not in SCHEMA:
        raise ValueError(f"unknown event kind {kind!r}")
    if event["level"] not in LEVELS:
        raise ValueError(f"unknown event level {event['level']!r}")
    missing = SCHEMA[kind] - event.keys()
    if missing:
        raise ValueError(
            f"event kind {kind!r} missing fields {sorted(missing)}"
        )


def log_path(telemetry_dir: str, process: int = 0) -> str:
    name = "events.jsonl" if process == 0 else f"events-p{process}.jsonl"
    return os.path.join(telemetry_dir, name)


class EventLog:
    """Append-only JSONL writer. Thread-safe; lines are flushed per
    emit so a preempted host's last events survive the SIGKILL that
    follows the grace window."""

    def __init__(
        self,
        path: str,
        host: int = 0,
        process: int = 0,
        min_level: str = "info",
    ):
        if min_level not in LEVELS:
            raise ValueError(f"unknown level {min_level!r}")
        self.path = path
        self.host = host
        self.process = process
        self._min = LEVELS.index(min_level)
        # Observers called with the full event dict after each write
        # (goodput ledger, flight-recorder ring). List mutation is
        # wiring-time only; iteration takes a snapshot so a listener
        # can never see a half-registered peer.
        self.listeners: List = []
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f: Optional[io.TextIOWrapper] = open(  # noqa: SIM115
            path, "a", encoding="utf-8"
        )

    def emit(self, kind: str, level: str = "info", **fields) -> None:
        if LEVELS.index(level) < self._min:
            return
        event = {
            "ts": round(time.time(), 6),
            "kind": kind,
            "level": level,
            "host": self.host,
            "process": self.process,
            **fields,
        }
        validate(event)
        line = json.dumps(event, sort_keys=True, default=str)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()
        # Outside the write lock: listeners may be invoked from signal
        # handlers (preemption_signal) and must not be able to deadlock
        # the log; they take their own (reentrant) locks.
        for fn in tuple(self.listeners):
            try:
                fn(event)
            except Exception:
                pass  # observability must never take down the run

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class NullEventLog:
    """Disabled-telemetry stand-in: emit is a constant-time no-op so
    call sites never branch."""

    path = None

    def emit(self, kind: str, level: str = "info", **fields) -> None:
        pass

    def close(self) -> None:
        pass


NULL = NullEventLog()


def read_events(path: str) -> List[dict]:
    """Parse an events JSONL file back into dicts (blank lines
    skipped). Does not validate — readers digesting partial logs
    (e.g. scripts/obs_summary.py mid-run) shouldn't crash on a
    truncated final line; they get whatever parses."""
    out = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn final line on an unclean shutdown
    return out
