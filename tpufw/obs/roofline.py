"""Roofline peaks and classification — the static half of the perf
observatory (tpufw.obs.perf).

A compiled program's arithmetic intensity AI = FLOPs / bytes-accessed
puts it on one side of the machine balance point
``peak FLOP/s / peak HBM bytes/s``: below it the program cannot reach
peak FLOPs no matter how good the schedule (memory-bound), above it
the HBM is not the wall (compute-bound). The peaks come from the
per-generation chip table (tpufw.utils.hardware) with env overrides
— ``TPUFW_PEAK_FLOPS`` / ``TPUFW_PEAK_HBM_BW`` — for hardware the
table does not know or for what-if analysis against a different
roofline (docs/PERF.md).

Kept jax-free: the one jax call (device-kind detection) is behind
``detect_peaks(device=...)``'s default and callers (tests,
scripts/obs_summary.py) can pass an explicit spec instead.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tpufw.utils.hardware import ChipSpec, detect_chip
from tpufw.workloads.env import env_float


@dataclasses.dataclass(frozen=True)
class PeakSpec:
    """The two roofline ceilings plus the HBM capacity headroom math
    needs, resolved for one chip generation (or overridden)."""

    chip: str
    flops_per_s: float
    hbm_bw_bytes_per_s: float
    hbm_bytes: int

    @property
    def balance_flops_per_byte(self) -> float:
        """Machine balance point: the AI at which compute and memory
        time are equal. 0 when bandwidth is unknown."""
        if self.hbm_bw_bytes_per_s <= 0:
            return 0.0
        return self.flops_per_s / self.hbm_bw_bytes_per_s


def peaks_from_spec(spec: ChipSpec) -> PeakSpec:
    """ChipSpec -> PeakSpec with the TPUFW_PEAK_* env overrides
    applied (0/unset keeps the table value)."""
    flops = env_float("peak_flops", 0.0) or spec.peak_bf16_flops
    bw = env_float("peak_hbm_bw", 0.0) or spec.hbm_bw_bytes_per_s
    return PeakSpec(
        chip=spec.name,
        flops_per_s=float(flops),
        hbm_bw_bytes_per_s=float(bw),
        hbm_bytes=spec.hbm_bytes,
    )


def detect_peaks(device=None) -> PeakSpec:
    """Peaks for the running backend's chip (default device). Falls
    back to the CPU table row when no backend is reachable, so the
    observatory never crashes a run over a roofline lookup."""
    try:
        spec = detect_chip(device)
    except Exception:  # noqa: BLE001 — uninitialized backend etc.
        from tpufw.utils.hardware import CHIP_SPECS

        spec = CHIP_SPECS["cpu"]
    return peaks_from_spec(spec)


def classify(
    ai_flops_per_byte: Optional[float], peaks: PeakSpec
) -> Optional[str]:
    """"compute" / "memory" against the machine balance point; None
    when either side of the comparison is unknown (no bytes-accessed
    figure from XLA, or no bandwidth for this chip)."""
    if ai_flops_per_byte is None or ai_flops_per_byte <= 0:
        return None
    balance = peaks.balance_flops_per_byte
    if balance <= 0:
        return None
    return "compute" if ai_flops_per_byte >= balance else "memory"


def attainable_flops_per_s(
    ai_flops_per_byte: float, peaks: PeakSpec
) -> float:
    """The roofline itself: min(peak FLOPs, AI * peak bandwidth) —
    the ceiling a program with this AI can reach on this chip."""
    if peaks.hbm_bw_bytes_per_s <= 0:
        return peaks.flops_per_s
    return min(
        peaks.flops_per_s,
        max(0.0, ai_flops_per_byte) * peaks.hbm_bw_bytes_per_s,
    )
