"""Run-health primitives: hang watchdog + crash flight recorder.

Two failure modes leave today's telemetry blind: a *wedge* (a
collective waiting on a dead peer, a stuck host callback — the run
stops emitting anything, forever) and an *abnormal exit* (unhandled
exception, SIGTERM past the grace window, segfault) that takes the
evidence down with the process. Both are exactly when the telemetry
dir matters most, so both get dedicated machinery:

``HangWatchdog`` — a daemon thread armed around each step
dispatch/host sync. If no ``arm()``/``beat()``/``disarm()`` arrives
within the timeout, it dumps every Python thread's stack, the
tracer's live span stack, and the flight-recorder ring to
``hang-p{proc}-{n}.json``, emits a ``hang`` event, and (opt-in)
SIGABRTs so a supervisor restarts the pod instead of burning the
reservation. It fires at most once per stall: re-arming re-enables
it, so a healthy-but-slow run that keeps making progress is never
killed.

``FlightRecorder`` — a bounded ring of recent events plus
``sys.excepthook`` / ``faulthandler`` / SIGTERM hooks that flush a
self-contained ``crash-bundle-p{proc}/`` (ring dump, thread stacks,
run config, env-knob snapshot, last metrics render) on abnormal
exit. The SIGTERM hook *flushes and chains*; whether it then
terminates is a policy knob — under a trainer, ``GracefulShutdown``
owns the exit (flush must not pre-empt the grace-window checkpoint),
while a standalone server restores the default disposition and
re-raises so SIGTERM still kills it.

Stdlib only; every hook chains to whatever it replaced and never
raises into the host program.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional

# Non-TPUFW env vars worth keeping in a crash bundle: the JAX/XLA
# switches that change compiled-program behavior.
_ENV_EXTRA = (
    "JAX_PLATFORMS",
    "JAX_TRACEBACK_FILTERING",
    "XLA_FLAGS",
    "LIBTPU_INIT_ARGS",
    "TPU_WORKER_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def format_thread_stacks(tracer=None) -> str:
    """Every Python thread's stack (idents resolved to thread names),
    plus the tracer's open spans — the combined "where is everyone"
    view both the watchdog and the recorder dump."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines: List[str] = []
    frames = sys._current_frames()
    for tid, frame in sorted(frames.items()):
        lines.append(f"--- thread {names.get(tid, '?')} (ident {tid}) ---")
        lines.extend(
            ln.rstrip("\n") for ln in traceback.format_stack(frame)
        )
        lines.append("")
    if tracer is not None:
        live = tracer.live_spans()
        if live:
            lines.append("--- open trace spans (innermost last) ---")
            for tid, stack in sorted(live.items()):
                span_s = ", ".join(
                    f"{name} ({open_s}s)" for name, open_s in stack
                )
                lines.append(
                    f"thread {names.get(tid, '?')} (ident {tid}): {span_s}"
                )
            lines.append("")
    return "\n".join(lines)


def env_snapshot() -> Dict[str, str]:
    """The knobs that shaped this run: every TPUFW_* plus the JAX/XLA
    switches in ``_ENV_EXTRA``."""
    out = {}
    for k, v in os.environ.items():
        if k.startswith("TPUFW_") or k in _ENV_EXTRA:
            out[k] = v
    return out


class HangWatchdog:
    """Arms around each step dispatch/host sync; see module docstring.

    The loop contract: ``arm()`` right before dispatching work that
    must finish within ``timeout_s``; ``beat()`` (== re-arm) on any
    sign of progress inside a long phase; ``disarm()`` when entering
    phases with no progress guarantee (eval, checkpoint drain, the
    forced preemption save). A fire disarms until the next ``arm()``,
    so one stall produces one dump, and recovery re-protects the run.
    """

    enabled = True

    def __init__(
        self,
        timeout_s: float,
        out_dir: str,
        proc: int = 0,
        tracer=None,
        events=None,
        recorder=None,
        abort: bool = False,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.out_dir = out_dir
        self.proc = proc
        self._tracer = tracer
        self._events = events
        self._recorder = recorder
        self._abort = abort
        self._cv = threading.Condition()
        self._deadline: Optional[float] = None  # monotonic; None=disarmed
        self._armed_at: Optional[float] = None
        self._stopped = False
        self.fired = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="tpufw-watchdog"
        )
        self._thread.start()

    def arm(self) -> None:
        now = time.monotonic()
        with self._cv:
            if self._deadline is None:
                self._armed_at = now
            self._deadline = now + self.timeout_s
            self._cv.notify()

    def beat(self) -> None:
        """Progress heartbeat: pushes the deadline out without
        resetting ``armed_at`` — a slow-but-progressing phase stays
        protected and never trips the alarm."""
        with self._cv:
            if self._deadline is not None:
                self._deadline = time.monotonic() + self.timeout_s
                self._cv.notify()

    def disarm(self) -> None:
        with self._cv:
            self._deadline = None
            self._armed_at = None
            # Wake the watchdog out of its stale timed wait so it
            # parks on the untimed disarmed wait immediately instead
            # of burning one spurious wakeup at the old deadline.
            self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._deadline = None
            self._cv.notify()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while True:
            with self._cv:
                if self._stopped:
                    return
                if self._deadline is None:
                    self._cv.wait()
                    continue
                now = time.monotonic()
                if now < self._deadline:
                    self._cv.wait(self._deadline - now)
                    continue
                armed_for = now - (self._armed_at or now)
                # One dump per stall: stay disarmed until the loop
                # proves liveness by arming again.
                # tpulint: disable=TPU020 — this thread is the only
                # waiter on _cv; a self-disarm by the sole consumer
                # has nobody to notify.
                self._deadline = None
                self._armed_at = None
                self.fired += 1
                n = self.fired
            self._dump(armed_for, n)

    def _dump(self, armed_for: float, n: int) -> None:
        path = os.path.join(
            self.out_dir, f"hang-p{self.proc}-{n}.json"
        )
        doc = {
            "ts": time.time(),
            "timeout_s": self.timeout_s,
            "armed_for_s": round(armed_for, 3),
            "stacks": format_thread_stacks(self._tracer),
            "live_spans": {
                str(tid): stack
                for tid, stack in (
                    self._tracer.live_spans() if self._tracer else {}
                ).items()
            },
            "recent_events": (
                self._recorder.ring_tail()
                if self._recorder is not None
                else []
            ),
        }
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, default=str)
            os.replace(tmp, path)
        except OSError:
            path = None
        if self._events is not None:
            try:
                self._events.emit(
                    "hang",
                    level="error",
                    timeout_s=self.timeout_s,
                    armed_for_s=round(armed_for, 3),
                    dump=path,
                )
            except Exception:
                pass  # a broken log must not stop the abort below
        if self._abort:
            # SIGABRT, not sys.exit: the wedged main thread is stuck
            # in a collective and will never see an exception; the
            # supervisor's restart is the only way out.
            os.kill(os.getpid(), signal.SIGABRT)


class NullHangWatchdog:
    """Disabled stand-in so loop call sites never branch; the arm/
    disarm pair costs two attribute lookups and a no-op call."""

    enabled = False
    fired = 0

    def arm(self) -> None:
        pass

    def beat(self) -> None:
        pass

    def disarm(self) -> None:
        pass

    def stop(self) -> None:
        pass


NULL_WATCHDOG = NullHangWatchdog()


class FlightRecorder:
    """Bounded ring of recent events + abnormal-exit hooks; flushes a
    self-contained ``crash-bundle-p{proc}/``. See module docstring."""

    def __init__(
        self,
        out_dir: str,
        proc: int = 0,
        ring_size: int = 256,
        registry=None,
        tracer=None,
        terminate_on_sigterm: bool = False,
    ):
        self.out_dir = out_dir
        self.proc = proc
        # deque.append is atomic under the GIL — the ring takes no
        # lock, so feeding it from the event listener (including from
        # inside signal handlers) can't deadlock.
        self.ring: collections.deque = collections.deque(maxlen=ring_size)
        self.context: Dict[str, object] = {}
        self._registry = registry
        self._tracer = tracer
        self._terminate = terminate_on_sigterm
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._exc_handler = None
        self._sigterm_handler = None
        self._sigterm_installed = False
        self._fault_file = None  # we enabled faulthandler iff not None
        self._installed = False
        self.reasons: List[str] = []
        self._exc_text: Optional[str] = None

    # -- feeds ---------------------------------------------------------

    def on_event(self, event: dict) -> None:
        self.ring.append(event)

    def ring_tail(self, n: Optional[int] = None) -> List[dict]:
        tail = list(self.ring)
        return tail if n is None else tail[-n:]

    def record_config(self, config: Dict[str, object]) -> None:
        """Merge run configuration into the bundle's ``config.json``
        (trainer config, run_info labels, mesh shape...)."""
        self.context.update(config)

    # -- hooks ---------------------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        self._installed = True
        # Capture the bound methods ONCE: each attribute access builds
        # a fresh bound-method object, so uninstall's are-we-still-
        # installed identity checks need these exact objects.
        self._exc_handler = self._on_exception
        self._sigterm_handler = self._on_sigterm
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._exc_handler
        # faulthandler gives the C-level last word (SIGSEGV/SIGBUS
        # kill the interpreter before any Python hook runs). Only
        # take it over when nobody else did (pytest enables its own).
        if not faulthandler.is_enabled():
            try:
                os.makedirs(self.out_dir, exist_ok=True)
                self._fault_file = open(  # noqa: SIM115 — held open
                    os.path.join(self.out_dir, f"fault-p{self.proc}.log"),
                    "w",
                    encoding="utf-8",
                )
                faulthandler.enable(file=self._fault_file)
            except OSError:
                self._fault_file = None
        try:
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._sigterm_handler
            )
            self._sigterm_installed = True
        except ValueError:
            # Not the main thread; excepthook/faulthandler still work.
            self._sigterm_installed = False

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if sys.excepthook is self._exc_handler:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        if self._sigterm_installed:
            try:
                if signal.getsignal(signal.SIGTERM) is self._sigterm_handler:
                    signal.signal(
                        signal.SIGTERM,
                        self._prev_sigterm
                        if self._prev_sigterm is not None
                        else signal.SIG_DFL,
                    )
            except (ValueError, TypeError):
                pass
            self._sigterm_installed = False
        if self._fault_file is not None:
            fault_path = self._fault_file.name
            try:
                faulthandler.disable()
                self._fault_file.close()
                # A clean exit leaves an empty fault log; drop it.
                if os.path.getsize(fault_path) == 0:
                    os.remove(fault_path)
            except OSError:
                pass
            self._fault_file = None

    def _on_exception(self, etype, value, tb) -> None:
        try:
            self._exc_text = "".join(
                traceback.format_exception(etype, value, tb)
            )
            self.flush("exception")
        except Exception:
            pass  # the original traceback must still print below
        prev = self._prev_excepthook or sys.__excepthook__
        prev(etype, value, tb)

    def _on_sigterm(self, signum, frame) -> None:
        try:
            self.flush("sigterm")
        except Exception:
            pass  # termination semantics below matter more
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif self._terminate:
            # Standalone process (no GracefulShutdown above us):
            # restore the default disposition and re-raise so SIGTERM
            # still terminates — the recorder observes, never saves.
            try:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
            except (ValueError, TypeError):
                pass
            os.kill(os.getpid(), signal.SIGTERM)

    # -- the bundle ----------------------------------------------------

    def bundle_dir(self) -> str:
        return os.path.join(self.out_dir, f"crash-bundle-p{self.proc}")

    def flush(self, reason: str) -> Optional[str]:
        """Write (or rewrite, on a second trigger) the crash bundle.
        The manifest goes last via rename, so a bundle with a
        parseable manifest is complete. Returns the bundle dir, or
        None if even mkdir failed (disk gone — nothing to do)."""
        bundle = self.bundle_dir()
        try:
            os.makedirs(bundle, exist_ok=True)
        except OSError:
            return None
        self.reasons.append(reason)
        files = []

        def _write(name: str, text: str) -> None:
            try:
                with open(
                    os.path.join(bundle, name), "w", encoding="utf-8"
                ) as f:
                    f.write(text)
                files.append(name)
            except OSError:
                pass

        _write(
            "ring.jsonl",
            "\n".join(
                json.dumps(ev, sort_keys=True, default=str)
                for ev in self.ring_tail()
            )
            + "\n",
        )
        _write("stacks.txt", format_thread_stacks(self._tracer))
        _write(
            "config.json",
            json.dumps(self.context, indent=2, sort_keys=True, default=str),
        )
        _write(
            "env.json",
            json.dumps(env_snapshot(), indent=2, sort_keys=True),
        )
        if self._registry is not None:
            try:
                _write("metrics.prom", self._registry.render())
            except Exception:
                pass
        if self._exc_text:
            _write("exception.txt", self._exc_text)
        manifest = {
            "ts": time.time(),
            "pid": os.getpid(),
            "process": self.proc,
            "reasons": list(self.reasons),
            "files": files,
        }
        try:
            tmp = os.path.join(bundle, "manifest.json.tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f, indent=2, sort_keys=True)
            os.replace(tmp, os.path.join(bundle, "manifest.json"))
        except OSError:
            return None
        return bundle
