"""Compiled-program cost observatory: FLOPs/bytes/HBM per executable,
live MFU + roofline attribution, and the on-demand profiler hooks.

The goodput ledger attributes *seconds* to categories and the tracer
attributes them to spans; this module attributes them to *hardware* —
for every program the run compiles (train step, pipeline step, tune
trials, serve decode chunks, paged inserts) it harvests XLA's own
``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
(argument/output/temp HBM) through the jit AOT path, writes the table
to ``<telemetry_dir>/programs.json``, and combines the static costs
with the measured wall-clock the trainers/scheduler already collect to
publish ``tpufw_program_mfu`` / ``tpufw_program_ai`` /
``tpufw_program_compute_bound`` / ``tpufw_hbm_headroom_bytes``.

Harvest is observe-only: ``observe_jit`` lowers and AOT-compiles the
SAME ``jax.jit`` object the caller is about to execute. Lowering is
abstract (no donated buffer is consumed) and each program is harvested
once per name, so the steady-state cost is one dict lookup; the one
extra executable build per unique program is absorbed by the
persistent XLA compile cache when enabled. ``TPUFW_PERF_OBS=0`` turns
the whole observatory off (the null object keeps every probe site
branch-free, same discipline as the rest of tpufw.obs).

Cost figures are PER DEVICE: the compiled module XLA reports on is
the SPMD-partitioned per-device program, so MFU divides by one chip's
peak and HBM headroom compares against one chip's capacity.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from tpufw.obs import roofline as roofline_mod

PROGRAMS_FILENAME = "programs.json"


def _cost_dict(compiled) -> dict:
    """``Compiled.cost_analysis()`` normalized to one flat dict.
    Older jax returns a one-element list of dicts, newer a dict;
    both may be empty on backends without an HLO cost model."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — unimplemented on some backends
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if isinstance(ca, dict) else {}


def _memory_dict(compiled) -> dict:
    """``Compiled.memory_analysis()`` attributes as a plain dict of
    byte counts (empty when the backend does not implement it)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return {}
    if ma is None:
        return {}
    out = {}
    for field, key in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    return out


def parse_profile_steps(raw: str) -> Optional[Tuple[int, int]]:
    """``TPUFW_PROFILE_STEPS=a:b`` -> (a, b), or None when unset or
    malformed (a bad value must never kill a training run)."""
    raw = (raw or "").strip()
    if not raw:
        return None
    parts = raw.split(":")
    if len(parts) != 2:
        return None
    try:
        start, stop = int(parts[0]), int(parts[1])
    except ValueError:
        return None
    if start < 0 or stop <= start:
        return None
    return start, stop


def resolve_profile_window(
    profile_dir: Optional[str],
    profile_start: int,
    profile_stop: int,
    telemetry_dir: Optional[str] = None,
) -> Tuple[Optional[str], int, int]:
    """The StepProfiler knobs after the ``TPUFW_PROFILE_STEPS`` env
    override: the env window wins over the config window, and when no
    profile dir is configured the capture lands under the telemetry
    dir (``<telemetry_dir>/xprof``) so the trace is linkable from the
    run's own artifact directory."""
    from tpufw.workloads.env import env_str

    window = parse_profile_steps(env_str("profile_steps", ""))
    if window is None:
        return profile_dir, profile_start, profile_stop
    out_dir = profile_dir or (
        os.path.join(telemetry_dir, "xprof") if telemetry_dir else None
    )
    return out_dir, window[0], window[1]


class ProfileTrigger:
    """On-demand ``jax.profiler`` capture behind ``/debug/profile``:
    one time-bounded trace at a time, taken on a daemon thread so the
    HTTP handler returns immediately with the trace path."""

    def __init__(self, out_dir: str, max_seconds: float = 60.0):
        self.out_dir = out_dir
        self.max_seconds = max_seconds
        self._lock = threading.Lock()
        self._active = False

    def trigger(self, seconds: float = 2.0) -> dict:
        seconds = min(max(float(seconds), 0.1), self.max_seconds)
        with self._lock:
            if self._active:
                return {"error": "capture already in progress"}
            self._active = True
        trace_dir = os.path.join(
            self.out_dir, f"ondemand-{int(time.time())}"
        )

        def capture():
            try:
                import jax

                os.makedirs(trace_dir, exist_ok=True)
                jax.profiler.start_trace(trace_dir)
                time.sleep(seconds)
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — never kill the server
                pass
            finally:
                with self._lock:
                    self._active = False

        threading.Thread(
            target=capture, daemon=True, name="obs-profile-capture"
        ).start()
        return {"started": True, "dir": trace_dir, "seconds": seconds}


class PerfObservatory:
    """Per-run registry of compiled-program costs + live roofline
    gauges. ``registry``/``out_dir`` may each be None (gauges only, or
    file only); ``peaks`` defaults to the detected chip's row with the
    ``TPUFW_PEAK_*`` overrides applied."""

    enabled = True

    def __init__(
        self,
        registry=None,
        out_dir: Optional[str] = None,
        peaks: Optional[roofline_mod.PeakSpec] = None,
        key: Optional[str] = None,
    ):
        self._registry = registry
        self._out_dir = out_dir
        self._peaks = peaks
        self._key = key
        self._lock = threading.Lock()
        self._programs: Dict[str, Dict[str, Any]] = {}
        self._closed = False

    # -- static costs -------------------------------------------------

    @property
    def peaks(self) -> roofline_mod.PeakSpec:
        if self._peaks is None:
            self._peaks = roofline_mod.detect_peaks()
        return self._peaks

    def set_key(self, key: str) -> None:
        """Attach the tune-winner-cache-style run key (the trainers
        know it only after the mesh/model resolve)."""
        self._key = key
        self._write()

    def observe_jit(self, name: str, jit_fn, args=(), kwargs=None):
        """Harvest ``jit_fn``'s compiled costs under ``name`` — once;
        repeat calls with a seen name are a dict lookup. Never raises:
        a failed harvest records the error and stops retrying."""
        if name in self._programs:
            return
        try:
            compiled = jit_fn.lower(*args, **(kwargs or {})).compile()
            cost = _cost_dict(compiled)
            mem = _memory_dict(compiled)
        except Exception as e:  # noqa: BLE001 — observe-only, never abort
            with self._lock:
                self._programs.setdefault(
                    name, {"error": f"{type(e).__name__}: {e}"[:300]}
                )
            return
        self.record_costs(
            name,
            flops=float(cost.get("flops", 0.0) or 0.0),
            bytes_accessed=float(cost.get("bytes accessed", 0.0) or 0.0),
            memory=mem,
        )

    def record_costs(
        self,
        name: str,
        flops: float = 0.0,
        bytes_accessed: float = 0.0,
        memory: Optional[dict] = None,
    ) -> None:
        """Ingest one program's static costs (the seam observe_jit
        feeds and tests drive directly) and publish the static gauges."""
        memory = memory or {}
        ai = flops / bytes_accessed if bytes_accessed > 0 else None
        peak_hbm = None
        if memory:
            # Live-at-peak upper bound: arguments + outputs + XLA's
            # own temp high-water mark, minus donated aliases.
            peak_hbm = (
                memory.get("argument_bytes", 0)
                + memory.get("output_bytes", 0)
                + memory.get("temp_bytes", 0)
                - memory.get("alias_bytes", 0)
            )
        entry: Dict[str, Any] = {
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "ai_flops_per_byte": ai,
            "bound": roofline_mod.classify(ai, self.peaks),
            "peak_hbm_bytes": peak_hbm,
            **memory,
        }
        with self._lock:
            self._programs[name] = entry
        if self._registry is not None:
            if ai is not None:
                self._registry.gauge(
                    "tpufw_program_ai",
                    "arithmetic intensity (FLOPs/byte) of the compiled "
                    "program, from XLA cost_analysis",
                ).set(ai, program=name)
            if entry["bound"] is not None:
                self._registry.gauge(
                    "tpufw_program_compute_bound",
                    "roofline classification: 1 = compute-bound, "
                    "0 = memory-bound (vs the chip balance point)",
                ).set(
                    1 if entry["bound"] == "compute" else 0, program=name
                )
            self._publish_headroom()
        self._write()

    def _publish_headroom(self) -> None:
        """``tpufw_hbm_headroom_bytes`` = chip HBM minus the largest
        per-program peak footprint seen so far (can go negative: that
        IS the OOM warning)."""
        with self._lock:
            peaks_seen = [
                p["peak_hbm_bytes"]
                for p in self._programs.values()
                if p.get("peak_hbm_bytes")
            ]
        if not peaks_seen or self._registry is None:
            return
        self._registry.gauge(
            "tpufw_hbm_headroom_bytes",
            "per-chip HBM capacity minus the largest compiled-program "
            "peak footprint (negative = expected OOM)",
        ).set(self.peaks.hbm_bytes - max(peaks_seen))

    # -- measured wall ------------------------------------------------

    def record_wall(self, name: str, wall_s: float) -> Optional[float]:
        """Combine a measured per-call wall with the harvested FLOPs
        into MFU for ``name``; returns the MFU (None when the program
        is unknown, has no FLOPs figure, or the wall is degenerate)."""
        if wall_s <= 0:
            return None
        with self._lock:
            entry = self._programs.get(name)
            if entry is None or not entry.get("flops"):
                return None
            mfu = entry["flops"] / (wall_s * self.peaks.flops_per_s)
            entry["wall_s"] = wall_s
            entry["mfu"] = mfu
            entry["calls"] = entry.get("calls", 0) + 1
        if self._registry is not None:
            self._registry.gauge(
                "tpufw_program_mfu",
                "measured FLOP utilization of the compiled program: "
                "cost_analysis FLOPs / (wall x per-chip peak FLOPs)",
            ).set(mfu, program=name)
        return mfu

    # -- reads --------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._programs.items()}

    def attrib(self, prefix: str = "") -> dict:
        """The bench/goodput summary for programs whose name starts
        with ``prefix``: the highest-FLOP program's last MFU and
        roofline bound, plus the global HBM headroom. Empty dict when
        nothing matched."""
        progs = [
            (n, p)
            for n, p in self.snapshot().items()
            if n.startswith(prefix) and p.get("flops")
        ]
        if not progs:
            return {}
        name, p = max(progs, key=lambda np: np[1]["flops"])
        out: dict = {"program": name}
        if p.get("mfu") is not None:
            out["measured_mfu"] = round(p["mfu"], 4)
        if p.get("bound") is not None:
            out["roofline_bound"] = p["bound"]
        hbm_peaks = [
            q["peak_hbm_bytes"]
            for q in self.snapshot().values()
            if q.get("peak_hbm_bytes")
        ]
        if hbm_peaks:
            out["hbm_headroom_bytes"] = int(
                self.peaks.hbm_bytes - max(hbm_peaks)
            )
        return out

    # -- persistence --------------------------------------------------

    def _document(self) -> dict:
        peaks = self.peaks
        with self._lock:
            programs = {k: dict(v) for k, v in self._programs.items()}
        return {
            "version": 1,
            "key": self._key,
            "chip": peaks.chip,
            "peak_flops_per_chip": peaks.flops_per_s,
            "peak_hbm_bw_bytes_per_s": peaks.hbm_bw_bytes_per_s,
            "hbm_bytes_per_chip": peaks.hbm_bytes,
            "balance_flops_per_byte": peaks.balance_flops_per_byte,
            "programs": programs,
        }

    def _write(self) -> None:
        if not self._out_dir:
            return
        path = os.path.join(self._out_dir, PROGRAMS_FILENAME)
        tmp = path + ".tmp"
        try:
            os.makedirs(self._out_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._document(), f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # telemetry write failure must never abort the run

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._write()


class NullPerfObservatory:
    """Disabled-path twin: every probe is a constant-time no-op (the
    <1% per-step budget asserted in tests/test_perf_obs.py)."""

    enabled = False

    def observe_jit(self, name, jit_fn, args=(), kwargs=None):
        pass

    def record_costs(self, name, flops=0.0, bytes_accessed=0.0,
                     memory=None):
        pass

    def record_wall(self, name, wall_s):
        return None

    def set_key(self, key):
        pass

    def snapshot(self):
        return {}

    def attrib(self, prefix=""):
        return {}

    def close(self):
        pass


NULL = NullPerfObservatory()


def load_programs(telemetry_dir: str) -> Optional[dict]:
    """Read ``<dir>/programs.json``; None when absent or torn (the
    same graceful degradation as the other obs artifacts)."""
    path = os.path.join(telemetry_dir, PROGRAMS_FILENAME)
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
