"""Request-scoped trace context for disaggregated serving.

One request through the front door touches three processes — router,
prefill replica, decode replica — each with its own Tracer writing its
own Chrome-trace file. A :class:`TraceContext` (128-bit ``trace_id`` +
per-hop ``span_id``) is minted at the router, propagated over the
``X-TPUFW-Trace`` HTTP header / the ``trace`` field of JSON control
frames / the page bundle's header meta, and stamped into every
per-stage span's ``args`` — so ``scripts/trace_merge.py`` can join the
three files by ``trace_id`` into one per-request flame row on the
wall-clock-aligned timeline.

The per-stage span vocabulary (each role emits the subset it owns):

======================  ====================================================
``req_queue_wait``      router: WFQ admission wait; prefill: engine lock wait
``req_admit``           router: replica pick; prefill: page acquire + trie
``req_prefill_compute`` prefill: prefill_shared / prefill_row device work
``req_page_export``     prefill: export_slot + bundle encode
``req_prefill_rpc``     router: whole prefill round trip (compute ⊂ rpc)
``req_wire``            router: rpc wall minus the engine-reported wall
``req_splice``          decode: bundle parse + page alloc + splice
``req_decode_chunk``    decode: one shared chunk advancing this request
``req_first_token``     decode: splice end → first decode-chunk flush
``req_decode_rpc``      router: whole decode round trip
======================  ====================================================

Disabled tracing must stay effectively free: :func:`stage` is a no-op
when the tracer is disabled and no context rides the request (the <1%%
request-path overhead budget is asserted in tests/test_reqtrace.py).

Stdlib only — the router imports this and never loads jax.
"""

from __future__ import annotations

import os
import re
from typing import Optional

#: HTTP request/response header carrying the wire form of a context.
HEADER = "X-TPUFW-Trace"

_WIRE_RE = re.compile(
    r"^([0-9a-f]{16,32})-([0-9a-f]{8,16})(?:-([A-Za-z0-9_.:-]{0,64}))?$"
)


def _hex(n_bytes: int) -> str:
    return os.urandom(n_bytes).hex()


class TraceContext:
    """Immutable (trace_id, span_id, tenant) triple plus the parent
    span id this hop descended from. ``trace_id`` is the join key
    across processes; ``span_id`` names this hop's spans."""

    __slots__ = ("trace_id", "span_id", "tenant", "parent")

    def __init__(
        self,
        trace_id: str,
        span_id: str,
        tenant: str = "",
        parent: str = "",
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.tenant = tenant
        self.parent = parent

    def child(self) -> "TraceContext":
        """New span id under the same trace — each role/hop re-spans
        so its stages are attributable to the hop, not the minting
        router."""
        return TraceContext(
            self.trace_id, _hex(4), self.tenant, parent=self.span_id
        )

    def wire(self) -> str:
        """``trace_id-span_id[-tenant]`` — the header / control-frame
        form. The parent link is process-local and does not travel."""
        base = f"{self.trace_id}-{self.span_id}"
        return f"{base}-{self.tenant}" if self.tenant else base

    def meta(self) -> dict:
        """Bundle-header form (rides the page bundle's JSON header
        next to the page geometry)."""
        # wire: produces trace-meta via out
        out = {"id": self.trace_id, "span": self.span_id}
        if self.tenant:
            out["tenant"] = self.tenant
        return out

    def args(self, **extra) -> dict:
        """Span ``args`` carrying the correlation keys trace_merge
        joins on."""
        out = {"trace": self.trace_id, "span": self.span_id}
        if self.tenant:
            out["tenant"] = self.tenant
        if self.parent:
            out["parent"] = self.parent
        out.update(extra)
        return out

    def __repr__(self) -> str:  # debugging/log readability only
        return f"TraceContext({self.wire()!r})"


def mint(tenant: str = "") -> TraceContext:
    """Fresh context — the router calls this for requests arriving
    without an ``X-TPUFW-Trace`` header."""
    return TraceContext(_hex(8), _hex(4), tenant)


def parse(value) -> Optional[TraceContext]:
    """Wire/meta form back into a context; tolerant — a malformed or
    absent value returns None (a bad header must never 500 the front
    door, and an old peer that sends nothing is fine)."""
    # wire: consumes trace-meta via value
    if isinstance(value, TraceContext):
        return value
    if isinstance(value, dict):  # bundle-header meta form
        tid, span = value.get("id"), value.get("span")
        if isinstance(tid, str) and isinstance(span, str) and tid and span:
            return TraceContext(tid, span, str(value.get("tenant") or ""))
        return None
    if not isinstance(value, str):
        return None
    m = _WIRE_RE.match(value.strip())
    if m is None:
        return None
    return TraceContext(m.group(1), m.group(2), m.group(3) or "")


def stage(
    tracer, ctx: Optional[TraceContext], name: str, dur_s: float, **extra
) -> None:
    """Emit one per-stage span (a complete event ending now, ``dur_s``
    long) carrying the trace correlation args. No-op-cheap on the
    disabled path: one attribute read when the tracer is the shared
    NullTracer."""
    if not getattr(tracer, "enabled", False):
        return
    if ctx is not None:
        tracer.complete(name, dur_s, **ctx.args(**extra))
    else:
        tracer.complete(name, dur_s, **extra)
