"""Per-tenant serving SLO tracking: TTFT and per-token latency
against configurable targets, with sliding-window attainment and
multi-window burn rates.

The two SLIs are the ones production LLM serving is judged on:

- **TTFT** — router-observed time to first usable token
  (queue_wait + admit + prefill round trip + splice; the decomposition
  is tpufw.obs.reqtrace's job, this module only judges the total);
- **per-token latency** — (total − ttft) / (n_tokens − 1), the steady
  decode rate a streaming client experiences.

A request is "good" when the SLI is within target. Attainment over a
sliding window is good/total; the **burn rate** for error budget
``1 − goal`` over window W is ``(1 − attainment(W)) / (1 − goal)`` —
1.0 means the budget burns exactly at the sustainable rate, 14.4 on
the short window is the classic page-now threshold. Multi-window
evaluation (default 60s/300s/3600s) lets alerting distinguish a blip
from a sustained regression, and ROADMAP item 4's autoscaler will
read the same gauges.

Targets come from ``TPUFW_SLO_TTFT_MS`` / ``TPUFW_SLO_TOK_MS`` with
per-tenant overrides in ``TPUFW_SLO_TENANTS``
(``tenant:ttft_ms:tok_ms,...`` — same spirit as the router's tenant
weight spec). Everything lands in the shared Registry as
``tpufw_slo_*`` series labeled by tenant, plus a schema'd
``slo_violation`` event per missed target (documented in
docs/OBSERVABILITY.md).

Stdlib only — lives in the router process, which never loads jax.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence, Tuple

from tpufw.workloads.env import env_float, env_str

from .events import NULL as NULL_EVENTS
from .registry import Registry

#: Default sliding windows (seconds): blip / sustained / budget-scale.
DEFAULT_WINDOWS = (60.0, 300.0, 3600.0)

#: Buckets sized for TTFT (tens of ms .. tens of s) and per-token
#: latency (ms .. s) on the same scale.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def parse_tenant_targets(spec: str) -> Dict[str, Tuple[float, float]]:
    """``"vip:500:50, batch:10000:1000"`` -> {tenant: (ttft_ms,
    tok_ms)}. Malformed entries are skipped, like the router's weight
    parser — a bad knob must not take down the front door."""
    out: Dict[str, Tuple[float, float]] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) != 3:
            continue
        try:
            out[parts[0].strip()] = (float(parts[1]), float(parts[2]))
        except ValueError:
            continue
    return out


class SloTracker:
    """Sliding-window SLO accounting for one router process.

    ``observe()`` is called once per completed request off the device
    path; all state lives behind one lock (deques are per-tenant and
    pruned to the longest window on every observe, so memory is
    bounded by request rate × max(windows))."""

    def __init__(
        self,
        registry: Registry,
        events=None,
        *,
        ttft_ms: float = 2000.0,
        tok_ms: float = 200.0,
        tenants: Optional[Dict[str, Tuple[float, float]]] = None,
        goal: float = 0.99,
        windows: Sequence[float] = DEFAULT_WINDOWS,
        clock=time.monotonic,
    ):
        if not 0.0 < goal < 1.0:
            raise ValueError(f"SLO goal must be in (0, 1), got {goal}")
        self.registry = registry
        self.events = events if events is not None else NULL_EVENTS
        self.ttft_ms = float(ttft_ms)
        self.tok_ms = float(tok_ms)
        self.tenants = dict(tenants or {})
        self.goal = float(goal)
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows or self.windows[0] <= 0:
            raise ValueError(f"bad SLO windows {windows!r}")
        self._clock = clock
        self._lock = threading.Lock()
        # Load-observatory phase label (e.g. "rung-2", "burst") —
        # stamped onto slo_violation events so a sweep's violations
        # attribute to their rung. Events-only on purpose: a metric
        # label would churn the fleet deriver's key space (TPU018).
        self._phase = ""
        # tenant -> deque of (t, ttft_ok, tok_ok); tok_ok is None for
        # single-token requests (no steady-state decode to judge).
        self._obs: Dict[str, deque] = {}
        r = registry
        self._h_ttft = r.histogram(
            "tpufw_slo_ttft_seconds",
            "router-observed time to first token",
            buckets=LATENCY_BUCKETS,
        )
        self._h_tok = r.histogram(
            "tpufw_slo_tok_seconds",
            "per-token decode latency after the first token",
            buckets=LATENCY_BUCKETS,
        )
        self._c_requests = r.counter(
            "tpufw_slo_requests_total", "requests judged against the SLO"
        )
        self._c_violations = r.counter(
            "tpufw_slo_violations_total",
            "requests that missed a target, by metric",
        )
        self._g_ttft_att = r.gauge(
            "tpufw_slo_ttft_attainment",
            "fraction of requests meeting the TTFT target "
            "(longest window)",
        )
        self._g_tok_att = r.gauge(
            "tpufw_slo_tok_attainment",
            "fraction of requests meeting the per-token target "
            "(longest window)",
        )
        self._g_burn = r.gauge(
            "tpufw_slo_burn_rate",
            "error-budget burn rate by metric and window "
            "(1.0 = sustainable)",
        )

    # ------------------------------------------------------ targets

    def targets_for(self, tenant: str) -> Tuple[float, float]:
        """(ttft_ms, tok_ms) for a tenant — override or defaults."""
        return self.tenants.get(tenant, (self.ttft_ms, self.tok_ms))

    def set_phase(self, phase: str) -> None:
        """Stamp subsequent slo_violation events with a load phase
        ("" clears). The sweep runner calls this at rung boundaries."""
        with self._lock:
            self._phase = str(phase)

    # ------------------------------------------------------ observe

    def observe(
        self,
        tenant: str,
        ttft_s: float,
        tok_s: Optional[float] = None,
        trace: str = "",
    ) -> None:
        """Judge one completed request and refresh that tenant's
        gauges. ``tok_s`` is None for requests that produced <= 1
        token."""
        tenant = tenant or "default"
        ttft_tgt, tok_tgt = self.targets_for(tenant)
        ttft_ok = ttft_s * 1e3 <= ttft_tgt
        tok_ok = None if tok_s is None else (tok_s * 1e3 <= tok_tgt)
        now = self._clock()
        with self._lock:
            phase = self._phase
        extra = {"phase": phase} if phase else {}
        self._h_ttft.observe(ttft_s, tenant=tenant)
        if tok_s is not None:
            self._h_tok.observe(tok_s, tenant=tenant)
        self._c_requests.inc(tenant=tenant)
        if not ttft_ok:
            self._c_violations.inc(tenant=tenant, metric="ttft")
            self.events.emit(
                "slo_violation", level="warn", tenant=tenant,
                metric="ttft", value_ms=round(ttft_s * 1e3, 3),
                target_ms=ttft_tgt, trace=trace, **extra,
            )
        if tok_ok is False:
            self._c_violations.inc(tenant=tenant, metric="tok")
            self.events.emit(
                "slo_violation", level="warn", tenant=tenant,
                metric="tok", value_ms=round((tok_s or 0.0) * 1e3, 3),
                target_ms=tok_tgt, trace=trace, **extra,
            )
        with self._lock:
            q = self._obs.get(tenant)
            if q is None:
                q = self._obs[tenant] = deque()
            q.append((now, ttft_ok, tok_ok))
            horizon = now - self.windows[-1]
            while q and q[0][0] < horizon:
                q.popleft()
            self._refresh_locked(tenant, now)

    # ---------------------------------------------------- computing

    def _window_stats_locked(self, tenant: str, window: float, now: float):
        """(ttft_attainment, tok_attainment, n) over the window;
        attainment is 1.0 with no traffic (an empty window has burned
        no budget)."""
        q = self._obs.get(tenant) or ()
        cutoff = now - window
        n = ttft_good = tok_n = tok_good = 0
        for t, ttft_ok, tok_ok in q:
            if t < cutoff:
                continue
            n += 1
            ttft_good += ttft_ok
            if tok_ok is not None:
                tok_n += 1
                tok_good += tok_ok
        ttft_att = ttft_good / n if n else 1.0
        tok_att = tok_good / tok_n if tok_n else 1.0
        return ttft_att, tok_att, n

    def _refresh_locked(self, tenant: str, now: float) -> None:
        budget = 1.0 - self.goal
        for w in self.windows:
            ttft_att, tok_att, _n = self._window_stats_locked(
                tenant, w, now
            )
            wl = f"{int(w)}s"
            self._g_burn.set(
                (1.0 - ttft_att) / budget,
                tenant=tenant, metric="ttft", window=wl,
            )
            self._g_burn.set(
                (1.0 - tok_att) / budget,
                tenant=tenant, metric="tok", window=wl,
            )
        # Headline attainment gauges read the LONGEST window — the
        # most stable number, and the one the smoke scrape asserts.
        ttft_att, tok_att, _n = self._window_stats_locked(
            tenant, self.windows[-1], now
        )
        self._g_ttft_att.set(ttft_att, tenant=tenant)
        self._g_tok_att.set(tok_att, tenant=tenant)

    def attainment(
        self, tenant: str, metric: str = "ttft",
        window: Optional[float] = None,
    ) -> float:
        tenant = tenant or "default"
        w = float(window) if window is not None else self.windows[-1]
        with self._lock:
            ttft_att, tok_att, _n = self._window_stats_locked(
                tenant, w, self._clock()
            )
        return ttft_att if metric == "ttft" else tok_att

    def burn_rate(
        self, tenant: str, metric: str = "ttft",
        window: Optional[float] = None,
    ) -> float:
        return (1.0 - self.attainment(tenant, metric, window)) / (
            1.0 - self.goal
        )

    def max_burn(self, window: Optional[str] = None) -> float:
        """Worst burn rate across every (tenant, metric) pair over
        one window — the executor's recovery signal. ``window`` is
        the gauge's label string ("60s"); None means the fastest
        window. Tenant list is snapshotted under the lock, burn math
        runs outside it (burn_rate re-acquires)."""
        if window is None:
            w = self.windows[0]
        else:
            w = float(str(window).rstrip("s"))
        with self._lock:
            tenants = list(self._obs)
        worst = 0.0
        for tenant in tenants:
            for metric in ("ttft", "tok"):
                worst = max(worst, self.burn_rate(tenant, metric, w))
        return worst

    # --------------------------------------------------------- env

    @classmethod
    def from_env(cls, registry: Registry, events=None) -> "SloTracker":
        """Build from TPUFW_SLO_* knobs (documented in docs/ENV.md)."""
        windows = tuple(
            float(w)
            for w in env_str("slo_windows_s", "60,300,3600").split(",")
            if w.strip()
        )
        return cls(
            registry,
            events,
            ttft_ms=env_float("slo_ttft_ms", 2000.0),
            tok_ms=env_float("slo_tok_ms", 200.0),
            tenants=parse_tenant_targets(env_str("slo_tenants", "")),
            goal=env_float("slo_goal", 0.99),
            windows=windows or DEFAULT_WINDOWS,
        )
