"""Fleet observatory: cross-replica scrape -> bounded time-series ->
derived fleet signals -> burn-rate alerts -> checkable scaling
recommendations.

Every other observability layer here is instantaneous and per-process
— gauges exist at scrape time, in one replica, and vanish with it.
This module is the fleet's flight recorder and its brain stem:

- **FleetCollector** scrapes every replica on an interval — router
  ``/metrics`` exposition through the tolerant parser
  (``tpufw.obs.promtext``), prefill/decode replicas through their
  framed-TCP ``signals()`` probe, plus the router's ``/healthz``
  per-replica detail — and appends one record per target per sweep
  into a **SeriesStore** (``fleet-series.jsonl``): size-bounded,
  ring-compacted by decimation (older samples thin out, every kept
  record stays a *genuine* snapshot so counter rate math survives),
  torn-tail-tolerant on read like the event log.
- **Derived fleet series** (``tpufw_fleet_*``) re-aggregate the
  per-replica truth: tokens/s, queue depth, page occupancy across
  arenas, piggyback fraction, spec accept rate, and per-tenant SLO
  attainment + multi-window burn rates across routers.
- A declarative **alert-rule engine** (threshold+for-duration rules
  and fast/slow burn-rate pairs) emits schema'd ``fleet_alert``
  events on firing/resolution.
- A **ScalingRecommender** maps sustained alerts to independent
  prefill-vs-decode replica-count deltas and writes each decision as
  a JobSet-manifest-shaped artifact (the base manifest with the
  ``replicas:`` counts patched) that ``tpulint --layer deploy
  --manifest <artifact>`` verifies *before* anything acts on it.
- A **retrospective query CLI** (``python -m tpufw.obs.fleet query
  --at/--window``) reconstructs fleet state at any past instant from
  the store + the ``events-fleet.jsonl`` alert history.

jax-free and stdlib-only (plus tpufw's own jax-free obs modules): the
collector must run in the router container, a CI runner, or a
laptop reading a copied series dir. Knobs: ``TPUFW_FLEET_SCRAPE_S``
(unset/0 = everything off), ``TPUFW_FLEET_DIR``,
``TPUFW_FLEET_MAX_RECORDS``, ``TPUFW_FLEET_MANIFEST``,
``TPUFW_FLEET_COOLDOWN_S``, ``TPUFW_FLEET_MAX_REPLICAS`` — see
docs/ENV.md.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from tpufw.obs import events as obs_events
from tpufw.obs import promtext
from tpufw.obs.registry import Registry
from tpufw.workloads.env import env_float, env_int, env_str

SERIES_FILENAME = "fleet-series.jsonl"
EVENTS_FILENAME = "events-fleet.jsonl"

# ------------------------------------------------------- series store


def read_series(path: str) -> List[dict]:
    """Parse a fleet-series JSONL file (blank lines skipped, torn or
    garbage lines dropped — the reader half of the EventLog contract:
    a collector killed mid-write must not take the queries with it)."""
    out: List[dict] = []
    try:
        f = open(path, encoding="utf-8")
    except OSError:
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail on an unclean shutdown
            if isinstance(rec, dict) and "ts" in rec and "replica" in rec:
                out.append(rec)
    return out


def _decimate(records: List[dict]) -> List[dict]:
    """Per-replica decimation, anchored at the newest sample: keep the
    later of each adjacent same-replica pair (walking back from the
    end, keep one / drop one). Kept records are untouched genuine
    snapshots — never averaged — so counter deltas between survivors
    still mean what they meant, just over a coarser grid."""
    by_replica: Dict[str, List[int]] = {}
    for i, rec in enumerate(records):
        by_replica.setdefault(str(rec.get("replica")), []).append(i)
    keep = set()
    for positions in by_replica.values():
        n = len(positions)
        for pos, idx in enumerate(positions):
            if (n - 1 - pos) % 2 == 0:
                keep.add(idx)
    return [rec for i, rec in enumerate(records) if i in keep]


class SeriesStore:
    """Append-only, size-bounded fleet time-series (JSONL, one record
    per target per sweep). Past ``max_records`` the file is ring-
    compacted: the newest half is kept verbatim, the older half is
    decimated per replica, and the result replaces the file via
    tmp + atomic rename (a reader or a crash mid-compaction sees
    either the old file or the new one, never a hybrid)."""

    def __init__(
        self,
        path: str,
        *,
        max_records: int = 4096,
        clock: Callable[[], float] = time.time,
    ):
        # resource: acquires file-handle
        self.path = path
        self.max_records = max(16, int(max_records))
        self._clock = clock
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._count = len(read_series(path)) if os.path.exists(path) else 0
        self._f = open(path, "a", encoding="utf-8")  # noqa: SIM115  # resource: acquires file-handle
        try:
            # A predecessor killed mid-write leaves an unterminated
            # tail; appending straight after it would glue the first
            # new record onto the torn line and lose BOTH. Terminate
            # it first.
            torn = False
            try:
                with open(path, "rb") as fh:
                    fh.seek(0, os.SEEK_END)
                    if fh.tell() > 0:
                        fh.seek(-1, os.SEEK_END)
                        torn = fh.read(1) != b"\n"
            except OSError:
                pass
            if torn:
                self._f.write("\n")
                self._f.flush()
        except BaseException:
            # A half-built store must not strand the append handle
            # (TPU019): if the torn-tail repair raises, the caller
            # never gets an object to close().
            self._f.close()
            raise

    def append(
        self,
        replica: str,
        role: str,
        series: Mapping[str, float],
        *,
        ts: Optional[float] = None,
        stale: bool = False,
    ) -> dict:
        rec: Dict[str, Any] = {
            "ts": round(
                float(ts if ts is not None else self._clock()), 6
            ),
            "replica": str(replica),
            "role": str(role),
            "series": {k: float(v) for k, v in series.items()},
        }
        if stale:
            rec["stale"] = True
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            if self._f is None:
                return rec
            self._f.write(line + "\n")
            self._f.flush()
            self._count += 1
            if self._count > self.max_records:
                self._compact_locked()
        return rec

    def _compact_locked(self) -> None:
        records = read_series(self.path)
        keep_tail = max(1, self.max_records // 2)
        head, tail = records[:-keep_tail], records[-keep_tail:]
        kept = _decimate(head) + tail
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in kept:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._f.close()
        # Park in the closed state append() tolerates: if the rename
        # or reopen below raises, _f must not point at a closed
        # handle every later append() would crash on.
        self._f = None
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")  # noqa: SIM115
        self._count = len(kept)

    def read(
        self,
        since: Optional[float] = None,
        until: Optional[float] = None,
    ) -> List[dict]:
        with self._lock:
            records = read_series(self.path)
        if since is not None:
            records = [r for r in records if r["ts"] >= since]
        if until is not None:
            records = [r for r in records if r["ts"] <= until]
        return records

    def close(self) -> None:
        # resource: releases file-handle
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# ---------------------------------------------------- scrape targets


class Target:
    """One scrapeable endpoint. ``scrape()`` returns Prometheus
    exposition text (a ``/metrics`` endpoint or an in-process
    ``Registry.render``) or a signals dict (a framed-TCP replica's
    ``{"signals": true}`` probe) — the collector handles both."""

    def __init__(
        self, name: str, role: str, scrape: Callable[[], Any]
    ):
        self.name = name
        self.role = role
        self.scrape = scrape


def http_target(
    name: str, url: str, role: str = "router", timeout_s: float = 2.0
) -> Target:
    def scrape() -> str:
        import urllib.request

        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8")

    return Target(name, role, scrape)


def signals_target(
    name: str, host: str, port: int, role: str, timeout_s: float = 2.0
) -> Target:
    """Framed-TCP signals probe — how prefill/decode replicas (which
    expose no HTTP) are scraped, the same control frame the router's
    health probes use."""

    def scrape() -> Dict[str, Any]:
        from tpufw.serve import transport

        reply, _rtt = transport.rpc(
            host, int(port), json.dumps({"signals": True}).encode()
        )
        return json.loads(reply.decode("utf-8"))

    return Target(name, role, scrape)


def http_health_fn(
    base_url: str, timeout_s: float = 2.0
) -> Callable[[], dict]:
    """``/healthz`` poller for a remote router — the per-replica
    detail backfills occupancy for replicas the collector cannot
    reach directly."""

    def fetch() -> dict:
        import urllib.request

        with urllib.request.urlopen(
            base_url.rstrip("/") + "/healthz", timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8"))

    return fetch


#: Numeric replica-signal fields -> the per-replica series they record
#: as. One row per field in the docs/OBSERVABILITY.md series catalog.
SIGNAL_SERIES: Tuple[Tuple[str, str], ...] = (
    ("pages_total", "tpufw_fleet_replica_pages_total"),
    ("pages_in_use", "tpufw_fleet_replica_pages_in_use"),
    ("slots_total", "tpufw_fleet_replica_slots_total"),
    ("slots_active", "tpufw_fleet_replica_slots_active"),
    ("migrations", "tpufw_fleet_replica_migrations"),
    ("spec_k", "tpufw_fleet_replica_spec_k"),
    ("spec_passes", "tpufw_fleet_replica_spec_passes"),
    ("prefill_chunk_pages", "tpufw_fleet_replica_prefill_chunk_pages"),
    ("prefill_inflight", "tpufw_fleet_replica_prefill_inflight"),
    ("prefill_chunks", "tpufw_fleet_replica_prefill_chunks"),
    ("piggyback_waterline", "tpufw_fleet_replica_piggyback_waterline"),
    # KV fabric: drain state, prefix-cache hit counters, and spill-
    # tier occupancy/lifetime totals. prefix_digests (the one list-
    # valued signal) is intentionally absent — series are numeric.
    ("draining", "tpufw_fleet_replica_draining"),
    ("sessions_drained", "tpufw_fleet_replica_sessions_drained"),
    ("sessions_resumed", "tpufw_fleet_replica_sessions_resumed"),
    ("prefix_hits", "tpufw_fleet_replica_prefix_hits"),
    ("prefix_misses", "tpufw_fleet_replica_prefix_misses"),
    ("spill_ram_pages", "tpufw_fleet_replica_spill_ram_pages"),
    ("spill_dir_pages", "tpufw_fleet_replica_spill_dir_pages"),
    ("spill_pages_total", "tpufw_fleet_replica_spill_pages_total"),
    (
        "spill_restored_total",
        "tpufw_fleet_replica_spill_restored_total",
    ),
)


def series_from_signals(sig: Mapping[str, Any]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for field, series in SIGNAL_SERIES:
        v = sig.get(field)
        if isinstance(v, (int, float)):
            out[series] = float(v)
    return out


# ---------------------------------------------------- derived series


def _key(name: str, **labels: str) -> str:
    return promtext.sample_key(name, labels)


class _Deriver:
    """Turns one sweep's per-replica records into the
    ``tpufw_fleet_*`` derived series, holding the previous sweep's
    snapshot per replica for counter rate math."""

    #: Counter series summed into the fleet token rate.
    TOKEN_COUNTERS = (
        "tpufw_router_tokens_total",
        "tpufw_serve_tokens_generated_total",
    )
    REQUEST_COUNTER = "tpufw_router_requests_total"
    PIGGYBACK_COUNTER = "tpufw_router_piggyback_total"

    def __init__(self):
        self._prev: Dict[str, Tuple[float, Dict[str, float]]] = {}

    def _rate(
        self, rec: dict, names: Sequence[str]
    ) -> Tuple[float, float]:
        """(delta, dt) of the summed counters vs this replica's
        previous record; (0, 0) without a usable previous sample.
        Negative deltas (replica restart) clamp to zero."""
        prev = self._prev.get(rec["replica"])
        if prev is None:
            return 0.0, 0.0
        prev_ts, prev_series = prev
        dt = rec["ts"] - prev_ts
        if dt <= 0:
            return 0.0, 0.0
        cur = sum(rec["series"].get(n, 0.0) for n in names)
        was = sum(prev_series.get(n, 0.0) for n in names)
        return max(0.0, cur - was), dt

    def derive(self, records: List[dict]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        live = [r for r in records if not r.get("stale")]
        roles: Dict[str, int] = {}
        for rec in live:
            roles[rec["role"]] = roles.get(rec["role"], 0) + 1
        for role, n in sorted(roles.items()):
            out[_key("tpufw_fleet_replicas", role=role)] = float(n)
        out["tpufw_fleet_replicas_unhealthy"] = float(
            sum(1 for r in records if r.get("stale"))
        )

        def total(series_name: str) -> float:
            return sum(
                r["series"].get(series_name, 0.0) for r in live
            )

        out["tpufw_fleet_queue_depth"] = total(
            "tpufw_router_queue_depth"
        )
        pages_in_use = total("tpufw_fleet_replica_pages_in_use")
        pages_total = total("tpufw_fleet_replica_pages_total")
        out["tpufw_fleet_pages_in_use"] = pages_in_use
        out["tpufw_fleet_pages_total"] = pages_total
        if pages_total > 0:
            out["tpufw_fleet_page_occupancy"] = (
                pages_in_use / pages_total
            )
        # KV fabric: pages parked outside HBM (hot host RAM + the
        # directory tier) fleet-wide, replicas mid-drain, and the
        # cross-replica prefix hit ratio — THE number the affinity
        # router is supposed to hold invariant as the pool scales.
        out["tpufw_fleet_spill_pages"] = total(
            "tpufw_fleet_replica_spill_ram_pages"
        ) + total("tpufw_fleet_replica_spill_dir_pages")
        out["tpufw_fleet_draining_replicas"] = total(
            "tpufw_fleet_replica_draining"
        )
        ph = total("tpufw_fleet_replica_prefix_hits")
        pm = total("tpufw_fleet_replica_prefix_misses")
        if ph + pm > 0:
            out["tpufw_fleet_prefix_hit_ratio"] = ph / (ph + pm)

        tok_delta = tok_dt = req_delta = req_dt = pig_delta = 0.0
        for rec in live:
            d, dt = self._rate(rec, self.TOKEN_COUNTERS)
            tok_delta += d
            tok_dt = max(tok_dt, dt)
            d, dt = self._rate(rec, (self.REQUEST_COUNTER,))
            req_delta += d
            req_dt = max(req_dt, dt)
            d, _ = self._rate(rec, (self.PIGGYBACK_COUNTER,))
            pig_delta += d
        if tok_dt > 0:
            out["tpufw_fleet_tokens_per_s"] = tok_delta / tok_dt
        if req_dt > 0:
            out["tpufw_fleet_requests_per_s"] = req_delta / req_dt
        if req_delta > 0:
            out["tpufw_fleet_piggyback_fraction"] = (
                pig_delta / req_delta
            )
        else:
            # No traffic this window: fall back to the cumulative
            # ratio so the series stays defined once requests exist.
            reqs = total(self.REQUEST_COUNTER)
            if reqs > 0:
                out["tpufw_fleet_piggyback_fraction"] = (
                    total(self.PIGGYBACK_COUNTER) / reqs
                )

        accept = [
            r["series"]["tpufw_spec_accept_rate"]
            for r in live
            if "tpufw_spec_accept_rate" in r["series"]
        ]
        if accept:
            out["tpufw_fleet_spec_accept_rate"] = sum(accept) / len(
                accept
            )

        # Per-tenant SLO re-aggregation across routers: attainment and
        # burn rate are already windowed ratios, so the fleet view is
        # their mean across the routers reporting that tenant (one
        # router in every current deployment — the mean is identity).
        slo: Dict[str, List[float]] = {}
        for rec in live:
            for skey, v in rec["series"].items():
                name, labels = promtext.parse_sample_key(skey)
                if name == "tpufw_slo_ttft_attainment" and labels:
                    k = _key(
                        "tpufw_fleet_slo_attainment",
                        metric="ttft",
                        tenant=labels.get("tenant", ""),
                    )
                elif name == "tpufw_slo_tok_attainment" and labels:
                    k = _key(
                        "tpufw_fleet_slo_attainment",
                        metric="tok",
                        tenant=labels.get("tenant", ""),
                    )
                elif name == "tpufw_slo_burn_rate" and labels:
                    k = _key(
                        "tpufw_fleet_slo_burn_rate",
                        metric=labels.get("metric", ""),
                        tenant=labels.get("tenant", ""),
                        window=labels.get("window", ""),
                    )
                else:
                    continue
                slo.setdefault(k, []).append(v)
        for k, vals in slo.items():
            out[k] = sum(vals) / len(vals)

        for rec in live:
            self._prev[rec["replica"]] = (rec["ts"], rec["series"])
        return out


# ------------------------------------------------------- alert rules


@dataclass(frozen=True)
class AlertRule:
    """Threshold + for-duration rule over one derived series (matched
    by series *name*; labeled series alert per label set). ``scale``
    optionally names the scaling hint a sustained firing feeds the
    recommender: ``"prefill:+1"``, ``"decode:-1"``, ..."""

    name: str
    series: str
    op: str = ">"
    threshold: float = 0.0
    for_s: float = 60.0
    severity: str = "warn"
    scale: str = ""


@dataclass(frozen=True)
class BurnRateRule:
    """Classic fast/slow multi-window burn-rate pair over the
    re-aggregated ``tpufw_fleet_slo_burn_rate`` series: fire when the
    fast window says "burning NOW" and the slow window confirms it is
    not a blip. One alert instance per tenant."""

    name: str
    metric: str  # "ttft" | "tok"
    fast_window: str = "60s"
    slow_window: str = "300s"
    fast_threshold: float = 14.4
    slow_threshold: float = 6.0
    for_s: float = 0.0
    severity: str = "page"
    scale: str = ""


#: The registered rule catalog (documented in docs/OBSERVABILITY.md —
#: every series name referenced here is in the series catalog there).
DEFAULT_ALERT_RULES: Tuple[Any, ...] = (
    BurnRateRule(
        name="fleet_ttft_burn",
        metric="ttft",
        severity="page",
        scale="prefill:+1",
    ),
    BurnRateRule(
        name="fleet_tok_burn",
        metric="tok",
        severity="page",
        scale="decode:+1",
    ),
    AlertRule(
        name="fleet_queue_backlog",
        series="tpufw_fleet_queue_depth",
        op=">",
        threshold=8.0,
        for_s=30.0,
        severity="warn",
        scale="prefill:+1",
    ),
    AlertRule(
        name="fleet_pages_pressure",
        series="tpufw_fleet_page_occupancy",
        op=">",
        threshold=0.85,
        for_s=60.0,
        severity="warn",
        scale="decode:+1",
    ),
    AlertRule(
        name="fleet_idle_capacity",
        series="tpufw_fleet_page_occupancy",
        op="<",
        threshold=0.10,
        for_s=600.0,
        severity="info",
        scale="decode:-1",
    ),
    AlertRule(
        name="fleet_replica_down",
        series="tpufw_fleet_replicas_unhealthy",
        op=">",
        threshold=0.0,
        for_s=10.0,
        severity="page",
    ),
)


class AlertEngine:
    """Evaluates the rule catalog against each sweep's derived series.
    Pure state machine over an injectable clock (tests drive it with a
    fake): condition holds -> pending; held ``for_s`` -> firing (one
    ``fleet_alert`` event); condition clears -> resolved (one more)."""

    def __init__(
        self,
        rules: Sequence[Any] = DEFAULT_ALERT_RULES,
        *,
        events=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rules = tuple(rules)
        self._events = events if events is not None else obs_events.NULL
        self._clock = clock
        # instance id -> {"since": pending-start, "firing": bool}
        self._state: Dict[str, Dict[str, Any]] = {}

    def _instances(
        self, rule: Any, derived: Mapping[str, float]
    ) -> List[Tuple[str, str, float, float]]:
        """(instance_id, series_key, value, threshold) rows whose
        condition currently holds, plus held-but-absent handling via
        the caller's state sweep."""
        rows: List[Tuple[str, str, float, float]] = []
        if isinstance(rule, BurnRateRule):
            fast: Dict[str, Tuple[str, float]] = {}
            slow: Dict[str, float] = {}
            for skey, v in derived.items():
                name, labels = promtext.parse_sample_key(skey)
                if (
                    name != "tpufw_fleet_slo_burn_rate"
                    or labels.get("metric") != rule.metric
                ):
                    continue
                tenant = labels.get("tenant", "")
                if labels.get("window") == rule.fast_window:
                    fast[tenant] = (skey, v)
                elif labels.get("window") == rule.slow_window:
                    slow[tenant] = v
            for tenant, (skey, v) in fast.items():
                if (
                    v > rule.fast_threshold
                    and slow.get(tenant, 0.0) > rule.slow_threshold
                ):
                    rows.append(
                        (
                            f"{rule.name}:{tenant}",
                            skey,
                            v,
                            rule.fast_threshold,
                        )
                    )
            return rows
        for skey, v in derived.items():
            name, _labels = promtext.parse_sample_key(skey)
            if name != rule.series:
                continue
            hit = v > rule.threshold if rule.op == ">" else (
                v < rule.threshold
            )
            if hit:
                rows.append(
                    (f"{rule.name}:{skey}", skey, v, rule.threshold)
                )
        return rows

    def evaluate(
        self,
        derived: Mapping[str, float],
        now: Optional[float] = None,
    ) -> List[dict]:
        """Advance every rule's state machine; returns the list of
        currently-firing alert dicts (rule catalog entry + instance
        detail), having emitted events for each transition."""
        now = self._clock() if now is None else float(now)
        firing: List[dict] = []
        seen: set = set()
        for rule in self.rules:
            for inst, skey, value, threshold in self._instances(
                rule, derived
            ):
                seen.add(inst)
                st = self._state.setdefault(
                    inst, {"since": now, "firing": False}
                )
                if not st["firing"] and now - st["since"] >= rule.for_s:
                    st["firing"] = True
                    st["fired_at"] = now
                    self._events.emit(
                        "fleet_alert",
                        level="warn",
                        rule=rule.name,
                        state="firing",
                        series=skey,
                        value=round(value, 6),
                        threshold=threshold,
                        severity=rule.severity,
                    )
                if st["firing"]:
                    firing.append(
                        {
                            "rule": rule,
                            "name": rule.name,
                            "instance": inst,
                            "series": skey,
                            "value": value,
                            "threshold": threshold,
                            "severity": rule.severity,
                            "scale": rule.scale,
                            "firing_for_s": now
                            - st.get("fired_at", now),
                        }
                    )
            # resolve instances whose condition no longer holds
            for inst in [
                i
                for i in self._state
                if i.startswith(rule.name + ":") and i not in seen
            ]:
                st = self._state.pop(inst)
                if st["firing"]:
                    self._events.emit(
                        "fleet_alert",
                        level="info",
                        rule=rule.name,
                        state="resolved",
                        series=inst.partition(":")[2],
                        value=0.0,
                        severity=rule.severity,
                    )
        return firing


# ------------------------------------------------ scaling recommender


_REPLICAS_RE = re.compile(r"replicas:\s*(\d+)\s*$")
_JOB_NAME_RE = re.compile(r"- name:\s*([A-Za-z0-9_-]+)\s*$")


def read_manifest_replicas(text: str) -> Dict[str, int]:
    """Replica counts of the replicatedJobs in a JobSet manifest,
    read with the same line discipline ``patch_manifest_replicas``
    writes with."""
    counts: Dict[str, int] = {}
    pending: Optional[str] = None
    in_jobs = False
    for line in text.split("\n"):
        stripped = line.strip()
        if stripped.startswith("#"):
            continue
        if stripped == "replicatedJobs:":
            in_jobs = True
            pending = None
            continue
        if stripped.startswith("---"):
            in_jobs = False
            pending = None
            continue
        if not in_jobs:
            continue
        if pending is not None:
            m = _REPLICAS_RE.match(stripped)
            if m:
                counts[pending] = int(m.group(1))
            pending = None  # one-shot: replicas must be the next line
            continue
        m = _JOB_NAME_RE.match(stripped)
        if m:
            pending = m.group(1)
    return counts


def patch_manifest_replicas(
    text: str, replicas: Mapping[str, int]
) -> str:
    """Return ``text`` with each named replicatedJob's ``replicas:``
    count rewritten. Pure line surgery (no yaml dependency in the
    collector container): a job's ``replicas:`` line must directly
    follow its ``- name:`` line, which is the convention every
    deploy/ JobSet here uses — container ``- name:`` lines never
    qualify because their next line is ``image:``."""
    lines = text.split("\n")
    pending: Optional[str] = None
    in_jobs = False
    for i, line in enumerate(lines):
        stripped = line.strip()
        if stripped.startswith("#"):
            continue
        if stripped == "replicatedJobs:":
            in_jobs = True
            pending = None
            continue
        if stripped.startswith("---"):
            in_jobs = False
            pending = None
            continue
        if not in_jobs:
            continue
        if pending is not None:
            if _REPLICAS_RE.match(stripped):
                indent = line[: len(line) - len(line.lstrip())]
                lines[i] = f"{indent}replicas: {replicas[pending]}"
            pending = None
            continue
        m = _JOB_NAME_RE.match(stripped)
        if m and m.group(1) in replicas:
            pending = m.group(1)
    return "\n".join(lines)


def _parse_scale(spec: str) -> Optional[Tuple[str, int]]:
    pool, sep, delta = spec.partition(":")
    if not sep:
        return None
    try:
        return pool.strip(), int(delta)
    except ValueError:
        return None


class ScalingRecommender:
    """Maps sustained firing alerts to independent per-pool replica
    deltas and writes each decision as (a) a JobSet-manifest-shaped
    YAML artifact — the base manifest with ``replicas:`` patched and a
    decision header comment — that the deploy lint layer verifies via
    ``tpulint --layer deploy --manifest <artifact>``, and (b) a JSON
    sidecar decision record. Per-pool cooldown keeps one incident
    from ratcheting the fleet."""

    def __init__(
        self,
        out_dir: str,
        base_manifest: str,
        *,
        cooldown_s: float = 300.0,
        min_replicas: int = 1,
        max_replicas: int = 8,
        events=None,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.out_dir = out_dir
        self.base_manifest = base_manifest
        self.cooldown_s = float(cooldown_s)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self._events = events if events is not None else obs_events.NULL
        self._clock = clock
        self._wall = wall_clock
        os.makedirs(out_dir, exist_ok=True)
        with open(base_manifest, encoding="utf-8") as f:
            self._base_text = f.read()
        self.current = read_manifest_replicas(self._base_text)
        self._last_change: Dict[str, float] = {}
        self._seq = len(
            _glob.glob(os.path.join(out_dir, "fleet-rec-*.json"))
        )
        #: Decision subscribers (e.g. tpufw.load.GangExecutor) — each
        #: called with the decision record after it is written and
        #: emitted. Same contract as EventLog.listeners: snapshot
        #: iteration, a raising subscriber is swallowed so it can
        #: never block the decision from landing on disk.
        self.listeners: List[Callable[[dict], None]] = []

    def consider(
        self, firing: Sequence[dict], now: Optional[float] = None
    ) -> Optional[dict]:
        """One sustained-alert sweep -> at most one decision. Returns
        the decision record (also written to disk + event log) or
        None when nothing changes."""
        now = self._clock() if now is None else float(now)
        deltas: Dict[str, int] = {}
        reasons: Dict[str, List[str]] = {}
        seen_rules: set = set()
        for alert in firing:
            if alert["name"] in seen_rules:
                continue  # one vote per rule, however many instances
            seen_rules.add(alert["name"])
            hint = _parse_scale(alert.get("scale", ""))
            if hint is None:
                continue
            pool, delta = hint
            deltas[pool] = deltas.get(pool, 0) + delta
            reasons.setdefault(pool, []).append(alert["name"])
        changes: Dict[str, Dict[str, int]] = {}
        for pool, delta in deltas.items():
            if pool not in self.current:
                continue
            if now - self._last_change.get(pool, -1e18) < self.cooldown_s:
                continue
            delta = max(-1, min(1, delta))  # one step per decision
            target = max(
                self.min_replicas,
                min(self.max_replicas, self.current[pool] + delta),
            )
            if target != self.current[pool]:
                changes[pool] = {
                    "from": self.current[pool],
                    "to": target,
                }
        if not changes:
            return None
        self._seq += 1
        stem = f"fleet-rec-{self._seq:04d}"
        new_counts = dict(self.current)
        for pool, ch in changes.items():
            new_counts[pool] = ch["to"]
        decision = {
            "ts": round(self._wall(), 6),
            "pools": changes,
            "replicas": new_counts,
            "reason": sorted(
                {r for pool in changes for r in reasons.get(pool, [])}
            ),
            "base_manifest": self.base_manifest,
            "artifact": stem + ".yaml",
        }
        patched = patch_manifest_replicas(self._base_text, new_counts)
        header = (
            f"# fleet-recommendation: {json.dumps(decision, sort_keys=True)}\n"
            "# Emitted by tpufw.obs.fleet.ScalingRecommender — verify with\n"
            "#   python -m tpufw.analysis --layer deploy "
            "--manifest <this file>\n"
        )
        yaml_path = os.path.join(self.out_dir, stem + ".yaml")
        json_path = os.path.join(self.out_dir, stem + ".json")
        with open(yaml_path, "w", encoding="utf-8") as f:
            f.write(header + patched)
        with open(json_path, "w", encoding="utf-8") as f:
            json.dump(decision, f, indent=2, sort_keys=True)
            f.write("\n")
        for pool, ch in changes.items():
            self.current[pool] = ch["to"]
            self._last_change[pool] = now
        self._events.emit(
            "fleet_recommendation",
            pools=changes,
            reason=decision["reason"],
            artifact=yaml_path,
            replicas=new_counts,
        )
        for fn in tuple(self.listeners):
            try:
                fn(decision)
            except Exception:
                pass
        return decision


# --------------------------------------------------------- collector


class FleetCollector:
    """Scrape every target once per sweep, append per-target records
    + one derived ``fleet`` record, evaluate alerts, feed sustained
    ones to the recommender. A target that dies mid-scrape is stale-
    marked (its record says so; the fleet keeps flying)."""

    def __init__(
        self,
        targets: Sequence[Target],
        store: SeriesStore,
        *,
        events=None,
        registry: Optional[Registry] = None,
        rules: Sequence[Any] = DEFAULT_ALERT_RULES,
        recommender: Optional[ScalingRecommender] = None,
        health_fn: Optional[Callable[[], dict]] = None,
        clock: Callable[[], float] = time.time,
        mono: Callable[[], float] = time.monotonic,
    ):
        # resource: transfers file-handle — the collector owns the
        # store from here on; FleetCollector.stop() closes it.
        self.targets = list(targets)
        self.store = store
        self.events = events if events is not None else obs_events.NULL
        #: The collector's own registry: derived series re-exported as
        #: gauges so the observatory is itself scrapeable.
        self.registry = registry if registry is not None else Registry()
        self.recommender = recommender
        self._health_fn = health_fn
        self._clock = clock
        self._mono = mono
        self._deriver = _Deriver()
        self.alerts = AlertEngine(rules, events=self.events, clock=mono)
        self.busy_s = 0.0
        #: CPU seconds the collector thread itself burned — the honest
        #: overhead-on-serving number. ``busy_s`` (wall) also counts
        #: time blocked on an engine's lock, which steals nothing from
        #: the request path; this doesn't.
        self.busy_cpu_s = 0.0
        self.scrapes = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._c_sweeps = self.registry.counter(
            "tpufw_fleet_scrapes_total", "collector sweeps completed"
        )
        self._c_busy = self.registry.counter(
            "tpufw_fleet_scrape_seconds_total",
            "wall seconds the collector spent scraping + deriving",
        )
        self._c_busy_cpu = self.registry.counter(
            "tpufw_fleet_scrape_cpu_seconds_total",
            "CPU seconds the collector thread spent scraping + "
            "deriving (excludes time blocked on replica locks)",
        )

    def scrape_once(self) -> Dict[str, float]:
        """One sweep. Returns the derived series dict (also appended
        to the store under the ``fleet`` pseudo-replica)."""
        t0 = self._mono()
        t0_cpu = time.thread_time()
        now = self._clock()
        records: List[dict] = []
        direct = set()
        for target in self.targets:
            try:
                raw = target.scrape()
            except Exception:  # noqa: BLE001 — replica died mid-scrape
                records.append(
                    self.store.append(
                        target.name, target.role, {}, ts=now, stale=True
                    )
                )
                direct.add(target.name)
                continue
            if isinstance(raw, str):
                series = promtext.flatten(raw)
            elif isinstance(raw, dict):
                series = series_from_signals(raw)
            else:
                series = {}
            records.append(
                self.store.append(target.name, target.role, series, ts=now)
            )
            direct.add(target.name)
        if self._health_fn is not None:
            try:
                health = self._health_fn()
            except Exception:  # noqa: BLE001 — router gone ≠ collector gone
                health = {}
            for name, detail in (health.get("replicas") or {}).items():
                if name in direct or not isinstance(detail, dict):
                    continue
                records.append(
                    self.store.append(
                        name,
                        str(detail.get("role", "replica")),
                        series_from_signals(detail),
                        ts=now,
                        stale=not detail.get("healthy", False),
                    )
                )
        derived = self._deriver.derive(records)
        self.store.append("fleet", "fleet", derived, ts=now)
        for skey, v in derived.items():
            name, labels = promtext.parse_sample_key(skey)
            self.registry.gauge(name).set(v, **labels)
        firing = self.alerts.evaluate(derived)
        if self.recommender is not None:
            self.recommender.consider(firing)
        self.scrapes += 1
        self._c_sweeps.inc()
        spent = self._mono() - t0
        self.busy_s += spent
        self._c_busy.inc(spent)
        spent_cpu = time.thread_time() - t0_cpu
        self.busy_cpu_s += spent_cpu
        self._c_busy_cpu.inc(spent_cpu)
        return derived

    def run(
        self,
        interval_s: float,
        *,
        stop: Optional[threading.Event] = None,
        max_scrapes: Optional[int] = None,
    ) -> int:
        stop = stop if stop is not None else self._stop
        while not stop.is_set():
            self.scrape_once()
            if max_scrapes is not None and self.scrapes >= max_scrapes:
                break
            stop.wait(interval_s)
        return self.scrapes

    def start(self, interval_s: float) -> "FleetCollector":
        """Run the sweep loop from a daemon thread; ``stop()`` ends
        it. Returns self for one-line attach."""
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.run,
            args=(float(interval_s),),
            kwargs={"stop": self._stop},
            daemon=True,
            name="fleet-collector",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.store.close()


def collector_from_env(
    targets: Sequence[Target],
    *,
    health_fn: Optional[Callable[[], dict]] = None,
    default_dir: str = "",
) -> Optional[FleetCollector]:
    """Build + start a collector from the TPUFW_FLEET_* knobs, or
    return None when TPUFW_FLEET_SCRAPE_S is unset/0 — the disabled
    path creates no files, no threads, and no collector object."""
    scrape_s = env_float("fleet_scrape_s", 0.0)
    if scrape_s <= 0:
        return None
    fleet_dir = env_str("fleet_dir", default_dir or ".")
    os.makedirs(fleet_dir, exist_ok=True)
    store = SeriesStore(
        os.path.join(fleet_dir, SERIES_FILENAME),
        max_records=env_int("fleet_max_records", 4096),
    )
    try:
        events = obs_events.EventLog(
            os.path.join(fleet_dir, EVENTS_FILENAME)
        )
        recommender = None
        manifest = env_str("fleet_manifest", "")
        if manifest and os.path.exists(manifest):
            recommender = ScalingRecommender(
                fleet_dir,
                manifest,
                cooldown_s=env_float("fleet_cooldown_s", 300.0),
                max_replicas=env_int("fleet_max_replicas", 8),
                events=events,
            )
        collector = FleetCollector(
            targets,
            store,
            events=events,
            recommender=recommender,
            health_fn=health_fn,
        )
    except BaseException:
        # Anything between the open and the ownership handoff to the
        # collector raising would strand the series handle (TPU019).
        store.close()
        raise
    return collector.start(scrape_s)


# ----------------------------------------------- retrospective query


def load_alert_history(path: str) -> List[dict]:
    try:
        return [
            e
            for e in obs_events.read_events(path)
            if e.get("kind") in ("fleet_alert", "fleet_recommendation")
        ]
    except OSError:
        return []


def alerts_firing_at(history: Sequence[dict], at: float) -> List[dict]:
    """Replay fleet_alert transitions up to ``at``; return the events
    of instances still firing then."""
    state: Dict[Tuple[str, str], dict] = {}
    for ev in history:
        if ev.get("kind") != "fleet_alert" or ev.get("ts", 0) > at:
            continue
        ikey = (str(ev.get("rule")), str(ev.get("series")))
        if ev.get("state") == "firing":
            state[ikey] = ev
        elif ev.get("state") == "resolved":
            state.pop(ikey, None)
    return list(state.values())


def state_at(
    records: Sequence[dict],
    history: Sequence[dict],
    at: float,
    *,
    horizon_s: float = 600.0,
) -> dict:
    """Reconstruct fleet state at instant ``at``: the latest record
    per replica at or before ``at`` (within ``horizon_s`` — older
    means the replica was already gone), the derived series then, and
    the alerts firing then."""
    latest: Dict[str, dict] = {}
    for rec in records:
        if rec["ts"] <= at and at - rec["ts"] <= horizon_s:
            prev = latest.get(rec["replica"])
            if prev is None or rec["ts"] >= prev["ts"]:
                latest[rec["replica"]] = rec
    derived = latest.pop("fleet", None)
    return {
        "at": at,
        "replicas": {
            name: {
                "ts": rec["ts"],
                "role": rec.get("role", ""),
                "stale": bool(rec.get("stale")),
                "series": rec.get("series", {}),
            }
            for name, rec in sorted(latest.items())
        },
        "derived": (derived or {}).get("series", {}),
        "derived_ts": (derived or {}).get("ts"),
        "alerts_firing": alerts_firing_at(history, at),
    }


def window_stats(
    records: Sequence[dict], start: float, end: float
) -> Dict[str, Dict[str, float]]:
    """min/mean/max/n per derived series over [start, end] — the
    last-window table the digest and the query CLI print."""
    acc: Dict[str, List[float]] = {}
    for rec in records:
        if rec.get("replica") != "fleet":
            continue
        if not (start <= rec["ts"] <= end):
            continue
        for skey, v in rec.get("series", {}).items():
            acc.setdefault(skey, []).append(float(v))
    return {
        skey: {
            "min": min(vals),
            "mean": sum(vals) / len(vals),
            "max": max(vals),
            "n": float(len(vals)),
        }
        for skey, vals in sorted(acc.items())
    }


# --------------------------------------------------------------- CLI


def _cmd_query(args: argparse.Namespace) -> int:
    series_path = os.path.join(args.dir, SERIES_FILENAME)
    records = read_series(series_path)
    if not records:
        print(f"no fleet series at {series_path}")
        return 1
    history = load_alert_history(
        os.path.join(args.dir, EVENTS_FILENAME)
    )
    at = args.at if args.at is not None else records[-1]["ts"]
    out = state_at(records, history, at)
    if args.window:
        out["window_s"] = args.window
        out["window"] = window_stats(records, at - args.window, at)
    if args.json:
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    print(f"== fleet state @ {at:.3f} ==")
    for name, rec in out["replicas"].items():
        mark = " STALE" if rec["stale"] else ""
        print(f"  {name} ({rec['role']}) ts={rec['ts']:.3f}{mark}")
    print("derived:")
    for skey, v in sorted(out["derived"].items()):
        print(f"  {skey} = {promtext.format_value(v)}")
    if out["alerts_firing"]:
        print("alerts firing:")
        for ev in out["alerts_firing"]:
            print(
                f"  {ev.get('rule')} [{ev.get('severity', '?')}] "
                f"{ev.get('series')} = {ev.get('value')}"
            )
    else:
        print("alerts firing: none")
    if args.window:
        print(f"window ({args.window:.0f}s): min / mean / max")
        for skey, st in out["window"].items():
            print(
                f"  {skey}: {st['min']:.4g} / {st['mean']:.4g} / "
                f"{st['max']:.4g}  (n={int(st['n'])})"
            )
    return 0


def _cmd_collect(args: argparse.Namespace) -> int:
    targets: List[Target] = []
    health_fn = None
    if args.router:
        base = args.router.rstrip("/")
        targets.append(
            http_target("router", base + "/metrics", role="router")
        )
        health_fn = http_health_fn(base)
    for spec in args.target or []:
        # role=name=host:port (signals probe) or role=name=http://...
        try:
            role, name, addr = spec.split("=", 2)
        except ValueError:
            print(f"bad --target {spec!r} (role=name=addr)")
            return 2
        if addr.startswith("http://") or addr.startswith("https://"):
            targets.append(http_target(name, addr, role=role))
        else:
            host, _, port = addr.rpartition(":")
            targets.append(
                signals_target(name, host, int(port), role)
            )
    if not targets:
        print("no targets: pass --router and/or --target")
        return 2
    os.makedirs(args.dir, exist_ok=True)
    store = SeriesStore(
        os.path.join(args.dir, SERIES_FILENAME),
        max_records=args.max_records,
    )
    events = None
    # Everything from here to the scrape loop runs under the close
    # guarantee: a raise while wiring the collector must not strand
    # the series handle the store just opened (TPU019).
    try:
        events = obs_events.EventLog(
            os.path.join(args.dir, EVENTS_FILENAME)
        )
        recommender = None
        if args.manifest:
            recommender = ScalingRecommender(
                args.dir,
                args.manifest,
                cooldown_s=args.cooldown_s,
                events=events,
            )
        collector = FleetCollector(
            targets,
            store,
            events=events,
            recommender=recommender,
            health_fn=health_fn,
        )
        stop = threading.Event()
        deadline = (
            time.monotonic() + args.duration if args.duration else None
        )
        try:
            while not stop.is_set():
                collector.scrape_once()
                if (
                    deadline is not None
                    and time.monotonic() >= deadline
                ):
                    break
                stop.wait(args.interval)
        except KeyboardInterrupt:
            pass
    finally:
        store.close()
        if events is not None:
            events.close()
    print(
        json.dumps(
            {
                "scrapes": collector.scrapes,
                "busy_s": round(collector.busy_s, 6),
                "series": store.path,
            }
        )
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpufw.obs.fleet",
        description="fleet observatory: collect / query",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    q = sub.add_parser(
        "query", help="reconstruct fleet state from a series dir"
    )
    q.add_argument("--dir", required=True, help="fleet series dir")
    q.add_argument(
        "--at", type=float, default=None,
        help="unix timestamp to reconstruct (default: latest record)",
    )
    q.add_argument(
        "--window", type=float, default=0.0,
        help="also aggregate derived series over the trailing window",
    )
    q.add_argument("--json", action="store_true")
    c = sub.add_parser("collect", help="run the collector loop")
    c.add_argument("--dir", required=True)
    c.add_argument("--interval", type=float, default=5.0)
    c.add_argument(
        "--router", default="",
        help="router base URL (scrapes /metrics + /healthz)",
    )
    c.add_argument(
        "--target", action="append",
        help="extra target, role=name=host:port (framed-TCP signals) "
        "or role=name=http://... (/metrics)",
    )
    c.add_argument("--duration", type=float, default=0.0)
    c.add_argument("--max-records", type=int, default=4096)
    c.add_argument(
        "--manifest", default="",
        help="base JobSet manifest enabling the scaling recommender",
    )
    c.add_argument("--cooldown-s", type=float, default=300.0)
    args = parser.parse_args(argv)
    if args.cmd == "query":
        return _cmd_query(args)
    return _cmd_collect(args)


if __name__ == "__main__":
    raise SystemExit(main())
