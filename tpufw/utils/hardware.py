"""Chip specs and detection — the numbers MFU accounting depends on.

The reference's only hardware contract is an environmental claim ("tested on
4GB+ GPUs", reference ``README.md:7``) and a health gate (``nvidia-smi``,
``README.md:81-84``). The TPU-native equivalent needs real per-chip peak
numbers because MFU — the BASELINE north-star metric (>=35% on v5e-16) — is
tokens/sec * model FLOPs per token / peak FLOPs, and "peak FLOPs" is a
per-generation constant, not something discoverable at runtime.

Public sources for the table: Google Cloud TPU system architecture docs.
"""

from __future__ import annotations

import dataclasses
import re


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Static description of one accelerator chip generation."""

    name: str
    # Peak dense matmul throughput in FLOP/s at the listed dtype.
    peak_bf16_flops: float
    hbm_bytes: int
    # ICI links per chip — used by the mesh layer to sanity-check topologies.
    ici_links: int = 4
    # Peak HBM bandwidth in bytes/s — the roofline's second axis
    # (tpufw.obs.roofline). 0 = unknown; consumers must degrade.
    hbm_bw_bytes_per_s: float = 0.0
    # Largest host (VM) chip count offered for the generation — the
    # upper bound on a pod's google.com/tpu limit, cross-checked by
    # tpulint TPU010 against the deploy manifests. v5e/v6e offer 1/4/8
    # chip hosts; v4/v5p hosts are fixed at 4.
    chips_per_host: int = 4

    @property
    def hbm_gib(self) -> float:
        return self.hbm_bytes / 2**30


# Peak bf16 FLOP/s per chip. v5e: 197 TFLOP/s bf16, 16 GiB HBM at
# 819 GB/s. v5p: 459 TFLOP/s bf16, 95 GiB at 2765 GB/s. v4: 275
# TFLOP/s, 32 GiB at 1228 GB/s. v6e (Trillium): 918 TFLOP/s bf16,
# 32 GiB at 1640 GB/s.
CHIP_SPECS: dict[str, ChipSpec] = {
    "v4": ChipSpec("v4", 275e12, 32 * 2**30, hbm_bw_bytes_per_s=1.228e12),
    "v5e": ChipSpec(
        "v5e", 197e12, 16 * 2**30,
        hbm_bw_bytes_per_s=8.19e11, chips_per_host=8,
    ),
    "v5p": ChipSpec("v5p", 459e12, 95 * 2**30, hbm_bw_bytes_per_s=2.765e12),
    "v6e": ChipSpec(
        "v6e", 918e12, 32 * 2**30,
        hbm_bw_bytes_per_s=1.64e12, chips_per_host=8,
    ),
    # CPU fallback so MFU accounting degrades gracefully in tests / dryruns.
    # ~100 GFLOP/s and ~50 GB/s are nominal single-socket figures; tests
    # never assert on them.
    "cpu": ChipSpec(
        "cpu", 100e9, 16 * 2**30,
        ici_links=0, hbm_bw_bytes_per_s=5e10, chips_per_host=1,
    ),
}

_KIND_PATTERNS: list[tuple[str, str]] = [
    (r"v6e|v6 ?lite|trillium", "v6e"),
    (r"v5p", "v5p"),
    (r"v5 ?lite|v5e|v5litepod", "v5e"),
    (r"v4", "v4"),
    (r"cpu", "cpu"),
]


def detect_chip(device=None) -> ChipSpec:
    """Map a jax device (default: ``jax.devices()[0]``) to its ChipSpec.

    Works off ``device.device_kind`` strings like "TPU v5 lite" / "TPU v5e".
    Unknown accelerators fall back to v5e (the BASELINE target hardware)
    rather than raising — benchmarks should run, and report, not crash.
    """
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu").lower()
    for pattern, name in _KIND_PATTERNS:
        if re.search(pattern, kind):
            return CHIP_SPECS[name]
    return CHIP_SPECS["v5e"]


def peak_flops_per_chip(device=None) -> float:
    return detect_chip(device).peak_bf16_flops
