"""Profiling + persistent compile cache — SURVEY.md §5's tracing subsystem
and the §7.4 cold-start lever.

The reference's only observability channel is ``kubectl logs`` and a
``watch`` loop (reference ``README.md:282-286, 331-335``); there is no
profiler to port. The TPU-native build gets two real mechanisms:

- **XProf traces**: ``StepProfiler`` wraps ``jax.profiler`` so the trainer
  captures a window of steps (skipping compile-dominated step 0) into a
  TensorBoard-loadable directory. Per-step named scopes come for free via
  ``jax.profiler.StepTraceAnnotation``.
- **Persistent XLA compile cache**: first-compile dominates TPU pod
  cold-start -> first-step (the BASELINE metric); pointing the cache at a
  PV/GCS path makes recompiles across pod restarts near-free. This is the
  TPU analog of the reference's image-pull/reboot wall-clock sink
  (``README.md:70-74, 202``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

# Env var consumed by workload entry points (set in deploy/ manifests).
COMPILE_CACHE_ENV = "TPUFW_COMPILE_CACHE_DIR"


def machine_fingerprint() -> str:
    """Short stable id of this host's CPU architecture + feature flags.

    XLA CPU executables are compiled for the build host's exact feature
    set; reusing a cache dir across heterogeneous machines can SIGILL
    (observed as a warning spray in BENCH_r02). Keying cache dirs by
    this fingerprint gives each machine class its own namespace while
    identical pods still share.
    """
    import hashlib
    import platform

    bits = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 "flags", arm64 "Features": first hit describes
                # every core uniformly on the machines we care about.
                if line.startswith(("flags", "Features")):
                    bits.append(" ".join(sorted(line.split(":", 1)[1].split())))
                    break
    except OSError:
        pass
    return hashlib.sha256(" ".join(bits).encode()).hexdigest()[:10]


def enable_compile_cache(
    path: Optional[str] = None, per_machine: bool = True
) -> Optional[str]:
    """Turn on XLA's persistent compilation cache at ``path``.

    ``path`` defaults to ``$TPUFW_COMPILE_CACHE_DIR``; no-op (returning
    None) when neither is set, so workloads can call this unconditionally.
    With ``per_machine`` (default) the cache lives in a
    ``machine_fingerprint()`` subdir, so a dir shared across machine
    types (PV, checked-in cache) cannot serve an executable compiled
    for another host's CPU features.
    """
    path = path or os.environ.get(COMPILE_CACHE_ENV)
    if not path:
        return None
    if per_machine:
        path = os.path.join(path, machine_fingerprint())
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: tiny compiles are still worth skipping on restart.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax binds the persistent cache to the FIRST dir it initializes
    # with; re-pointing the config alone would silently keep writing to
    # the old dir. Reset unconditionally — re-init is lazy and cheap,
    # and conditional resets invite stale-binding bugs.
    from jax.experimental.compilation_cache import (
        compilation_cache as _cc,
    )

    _cc.reset_cache()
    return path


class StepProfiler:
    """Captures steps [start, stop) of a train loop into an XProf trace.

    Usage from a step loop::

        prof = StepProfiler(dir, start_step=3, stop_step=6)
        for i, batch in enumerate(data):
            prof.maybe_start(i)
            with prof.step(i):
                run_step(batch)
            prof.maybe_stop(i)

    Inactive (``dir=None``) it is free: every method returns immediately.
    Start defaults past step 0 so the capture window holds steady-state
    steps, not the XLA compile.
    """

    def __init__(
        self,
        trace_dir: Optional[str],
        start_step: int = 3,
        stop_step: int = 6,
    ):
        self.trace_dir = trace_dir
        self.start_step = start_step
        self.stop_step = stop_step
        self._active = False

    def maybe_start(self, step: int) -> None:
        if self.trace_dir and not self._active and step == self.start_step:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
            self._active = True

    def step(self, step: int):
        if self._active:
            return jax.profiler.StepTraceAnnotation("train", step_num=step)
        import contextlib

        return contextlib.nullcontext()

    def maybe_stop(self, step: int) -> None:
        if self._active and step + 1 >= self.stop_step:
            # Block so the trace includes completed device work.
            jax.effects_barrier()
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.effects_barrier()
            jax.profiler.stop_trace()
            self._active = False
