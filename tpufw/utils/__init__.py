from tpufw.utils.hardware import (  # noqa: F401
    ChipSpec,
    CHIP_SPECS,
    detect_chip,
    peak_flops_per_chip,
)
