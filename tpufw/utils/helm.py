"""Minimal helm-template renderer (chart-rot tests + tpulint TPU014).

`helm` isn't installed in the CI/dev image (the round-1 suite skipped its
one chart test, leaving the templates unexercised — VERDICT r1 weak #7).
Lives in the library (moved from tests/helm_mini.py) because the deploy
analyzer renders the chart the same way the tests do: one renderer, one
template subset, one failure mode for drift.
This implements exactly the template subset deploy/charts/tpu-stack uses:

  {{ .Values.a.b }} / {{ .Release.X }} / {{ .Chart.X }} / {{ . }}
  {{ include "name" . }}    (defines parsed from templates/_helpers.tpl)
  {{- if EXPR }} ... {{- end }}
  {{- with EXPR }} ... {{- end }}          (rebinds .)
  filters: quote, nindent N, toYaml, ternary A B

It is NOT a general helm implementation; unknown constructs raise, so a
template drifting outside the supported subset fails the test loudly
instead of rendering garbage. When a real `helm` binary exists, the test
additionally compares this renderer's output against `helm template`.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import yaml

_TAG = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}")
_DEFINE = re.compile(
    r'\{\{-\s*define\s+"([^"]+)"\s*-\}\}(.*?)\{\{-\s*end\s*\}\}', re.S
)


class Context:
    def __init__(self, chart_dir: str, release_name: str, namespace: str,
                 values_overrides: dict | None = None):
        with open(os.path.join(chart_dir, "Chart.yaml")) as f:
            chart = yaml.safe_load(f)
        with open(os.path.join(chart_dir, "values.yaml")) as f:
            values = yaml.safe_load(f)
        if values_overrides:
            values = _deep_merge(values, values_overrides)
        self.root = {
            "Values": values,
            "Chart": {
                "Name": chart["name"],
                "AppVersion": str(chart.get("appVersion", "")),
                "Version": str(chart.get("version", "")),
            },
            "Release": {
                "Name": release_name,
                "Namespace": namespace,
                "Service": "Helm",
            },
        }
        self.defines: dict[str, str] = {}
        helpers = os.path.join(chart_dir, "templates", "_helpers.tpl")
        if os.path.exists(helpers):
            with open(helpers) as f:
                for name, body in _DEFINE.findall(f.read()):
                    self.defines[name] = body.strip("\n")


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _lookup(path: str, ctx: Context, dot: Any) -> Any:
    if path == ".":
        return dot
    cur: Any = ctx.root
    for part in path.lstrip(".").split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _eval_term(term: str, ctx: Context, dot: Any) -> Any:
    term = term.strip()
    if term.startswith('"') and term.endswith('"'):
        return term[1:-1]
    if re.fullmatch(r"-?\d+", term):
        return int(term)
    m = re.fullmatch(r'include\s+"([^"]+)"\s+(\.[\w.]*|\.)', term)
    if m:
        name, dot_expr = m.groups()
        if name not in ctx.defines:
            raise ValueError(f"helm_mini: unknown define {name!r}")
        return render_str(
            ctx.defines[name], ctx, _lookup(dot_expr, ctx, dot)
        )
    if term.startswith("."):
        return _lookup(term, ctx, dot)
    raise ValueError(f"helm_mini: unsupported term {term!r}")


def _eval_expr(expr: str, ctx: Context, dot: Any) -> Any:
    """Evaluate `term | filter | filter ...`."""
    parts = [p.strip() for p in expr.split("|")]
    head = parts[0]
    m = re.fullmatch(r"toYaml\s+(.+)", head)
    if m:
        val: Any = _to_yaml(_eval_term(m.group(1), ctx, dot))
    else:
        val = _eval_term(head, ctx, dot)
    for filt in parts[1:]:
        toks = filt.split(None, 2)
        name = toks[0]
        if name == "quote":
            val = json.dumps("" if val is None else str(val))
        elif name == "nindent":
            n = int(toks[1])
            pad = " " * n
            text = val if isinstance(val, str) else _to_yaml(val)
            val = "\n".join(pad + ln if ln else ln
                            for ln in text.splitlines())
        elif name == "toYaml":
            val = _to_yaml(val)
        elif name == "ternary":
            a = _eval_term(toks[1], ctx, dot)
            b = _eval_term(toks[2], ctx, dot)
            val = a if val else b
        else:
            raise ValueError(f"helm_mini: unsupported filter {name!r}")
    return val


def _to_yaml(val: Any) -> str:
    return yaml.safe_dump(val, default_flow_style=False).strip("\n")


_CTRL = re.compile(r"^(\s*)\{\{-?\s*(if|with|end)\b\s*(.*?)\s*-?\}\}\s*$")
_NINDENT_LINE = re.compile(r"^\s*\{\{-\s*(.*?\|\s*nindent\s+\d+)\s*\}\}\s*$")


def render_str(template: str, ctx: Context, dot: Any) -> str:
    """Render a template body (helper defines use this with their own dot)."""
    out_lines: list[str] = []
    # Stack of (kind, emitting, saved_dot). Lines inside a false block are
    # dropped; `with` rebinds dot.
    stack: list[tuple[str, bool, Any]] = []

    def emitting() -> bool:
        return all(e for _, e, _ in stack)

    for raw in template.splitlines():
        m = _CTRL.match(raw)
        if m:
            _, kw, arg = m.groups()
            if kw == "end":
                if not stack:
                    raise ValueError("helm_mini: unmatched end")
                _, _, saved = stack.pop()
                dot = saved
            else:
                val = _eval_expr(arg, ctx, dot) if emitting() else None
                truthy = bool(val)
                saved = dot
                if kw == "with" and truthy:
                    dot = val
                stack.append((kw, truthy, saved))
            continue
        if not emitting():
            continue
        m = _NINDENT_LINE.match(raw)
        if m:
            # `  {{- expr | nindent N }}`: the `{{-` eats the line's own
            # leading whitespace+newline; nindent re-adds newline+indent.
            out_lines.append(_eval_expr(m.group(1), ctx, dot))
            continue
        line = _TAG.sub(
            lambda mm: str(_eval_expr(mm.group(1), ctx, dot)), raw
        )
        out_lines.append(line)
    if stack:
        raise ValueError("helm_mini: unclosed block")
    return "\n".join(out_lines)


def render_chart(
    chart_dir: str,
    release_name: str = "tpu-stack",
    namespace: str = "tpu-system",
    values_overrides: dict | None = None,
) -> dict[str, list[dict]]:
    """Render every template; returns {template_filename: [yaml docs]}."""
    ctx = Context(chart_dir, release_name, namespace, values_overrides)
    tdir = os.path.join(chart_dir, "templates")
    out: dict[str, list[dict]] = {}
    for fname in sorted(os.listdir(tdir)):
        if fname.startswith("_") or not fname.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tdir, fname)) as f:
            rendered = render_str(f.read(), ctx, ctx.root)
        docs = [d for d in yaml.safe_load_all(rendered) if d]
        out[fname] = docs
    return out
