"""Host-RAM page-spill tier: the cold store behind the KV fabric.

HBM holds the hot working set (the page arena); everything colder
lives here. Two kinds of entries share one LRU:

- ``"trie"``  — one evicted prefix-cache page (key: the full-page
  token path that produced it). Restoring one skips that chunk's
  prefill recompute AND its arena residency until re-referenced.
- ``"session"`` — one drained slot's complete page bundle (key: the
  sticky session id). Restoring one resumes a live generation on a
  different replica with zero token divergence.

Values are opaque bytes. By convention they are TPFB page bundles
(``tpufw.serve.bundle``): int8 codes + page-structured scales ship
raw, and the restore path is the same scatter/splice the migration
wire uses — so spill -> restore is bit-equal by construction. This
module never parses them: serialization stays with the engine layer
(``tpufw.serve.roles`` / ``tpufw.workloads.serve``), which also owns
the device <-> numpy hop. That keeps this module stdlib-only and
importable from any process, jax or not.

Capacity is counted in PAGES (the arena's own unit, so the spill
budget reads directly against ``TPUFW_SERVE_SLOTS`` arithmetic — see
PERF.md "KV fabric"). When the RAM budget overflows, LRU entries
demote to the optional directory tier (``TPUFW_KV_SPILL_DIR``); with
no directory they are dropped oldest-first. The directory tier is
also the cross-process session store the router reads during re-home
(file layout below — ``tpufw.serve.bundle.session_path`` computes the
same names on the router side).

File layout: ``<dir>/<kind>-<blake2b16(key)>.tpfb``, written via
temp-file + ``os.replace`` so a reader never sees a torn bundle.

Thread-safe: one lock around the index; file writes happen under it
too (spill sits off the decode hot path — eviction and drain are the
only writers).
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

#: Spill keys are (kind, name): kind selects the namespace, name is
#: the trie token-path repr or the session id.
Key = Tuple[str, str]


def key_name(kind: str, name: str) -> str:
    """Stable on-disk basename for a spill entry — blake2b keeps
    arbitrary session ids / token paths filesystem-safe and
    collision-resistant."""
    h = hashlib.blake2b(name.encode("utf-8"), digest_size=16)
    return f"{kind}-{h.hexdigest()}.tpfb"


def trie_key(tokens: Iterable[int]) -> str:
    """Canonical spill name for a trie page: the full token path from
    the root (a path, never a lone chunk — KV at slot j depends on
    every token <= j, same invariant as the trie itself)."""
    return ",".join(str(int(t)) for t in tokens)


class _Entry:
    __slots__ = ("data", "pages", "on_disk")

    def __init__(self, data: Optional[bytes], pages: int, on_disk: bool):
        self.data = data  # None once demoted to the directory tier
        self.pages = pages
        self.on_disk = on_disk


class SpillTier:
    """LRU byte store with a RAM budget (in pages) and an optional
    directory overflow/persistence tier.

    ``put`` admits at the MRU end and evicts LRU entries past the
    budget (demote-to-disk when a directory is set, drop otherwise).
    ``get`` touches LRU order and transparently reloads demoted
    entries from disk. ``pop`` removes an entry everywhere — the
    restore paths use it so a consumed spill entry frees its host RAM
    the moment its pages are back in the arena.
    """

    def __init__(
        self,
        max_ram_pages: int,
        directory: str = "",
        *,
        persist_kinds: Tuple[str, ...] = ("session",),
    ):
        self.max_ram_pages = int(max_ram_pages)
        self.directory = str(directory or "")
        #: Kinds written through to the directory at put time (not
        #: just on demotion): sessions must survive the PROCESS — the
        #: router re-homes them from another replica's filesystem
        #: view — so they hit disk while the drain handler still runs.
        self.persist_kinds = tuple(persist_kinds)
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        # Entry ledger (page-lifetime note: the tier stores BYTES, not
        # arena pages — the page obligations around spill/restore live
        # in pages.py under the `pages` resource contracts; an entry
        # here holds nothing the allocator tracks).
        self._entries: "OrderedDict[Key, _Entry]" = OrderedDict()
        # Counters for the tpufw_kv_* series (readers: signals(),
        # _gauge_values, bench). Monotonic ones never reset.
        self.spilled_bytes_total = 0
        self.spilled_pages_total = 0
        self.restored_total = 0
        self.dropped_total = 0

    # ------------------------------------------------------ helpers

    def _path(self, key: Key) -> str:
        return os.path.join(self.directory, key_name(key[0], key[1]))

    def _write_file(self, key: Key, data: bytes) -> bool:
        if not self.directory:
            return False
        path = self._path(key)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # readers never see a torn bundle
            return True
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def _ram_pages_locked(self) -> int:
        return sum(
            e.pages for e in self._entries.values() if e.data is not None
        )

    def _shrink_locked(self) -> None:
        """Demote/drop LRU entries until RAM is back under budget."""
        while self._ram_pages_locked() > self.max_ram_pages:
            victim_key = None
            for k, e in self._entries.items():  # LRU first
                if e.data is not None:
                    victim_key = k
                    break
            if victim_key is None:
                break
            e = self._entries[victim_key]
            if e.on_disk or self._write_file(victim_key, e.data):
                e.on_disk = True
                e.data = None  # demoted: pages accounted on disk now
            else:
                # No directory to demote into: the LRU entry drops.
                del self._entries[victim_key]
                self.dropped_total += 1

    # ------------------------------------------------------ public

    def put(self, kind: str, name: str, data: bytes, pages: int) -> None:
        """Admit ``data`` (a TPFB bundle covering ``pages`` arena
        pages) at the MRU end, evicting past the RAM budget."""
        key = (kind, name)
        with self._lock:
            old = self._entries.pop(key, None)
            on_disk = bool(old and old.on_disk)
            if kind in self.persist_kinds:
                on_disk = self._write_file(key, data) or on_disk
            self._entries[key] = _Entry(data, int(pages), on_disk)
            self.spilled_bytes_total += len(data)
            self.spilled_pages_total += int(pages)
            self._shrink_locked()

    def get(self, kind: str, name: str) -> Optional[bytes]:
        """Fetch bytes (touching LRU order), reloading a demoted entry
        from the directory tier; None on miss or torn file."""
        key = (kind, name)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            if e.data is not None:
                return e.data
            try:
                with open(self._path(key), "rb") as f:
                    return f.read()
            except OSError:
                # Torn/unreadable file: drop, never serve partial KV.
                del self._entries[key]
                self.dropped_total += 1
                return None

    def pop(self, kind: str, name: str) -> None:
        """Remove an entry from RAM and disk (consumed by a restore,
        or invalidated). Missing entries are a no-op."""
        key = (kind, name)
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self.restored_total += 1
                if e.on_disk:
                    try:
                        os.unlink(self._path(key))
                    except OSError:
                        pass

    def __contains__(self, key: Key) -> bool:
        with self._lock:
            return key in self._entries

    def names(self, kind: str) -> List[str]:
        """Current entry names of one kind, LRU -> MRU (the engine
        advertises trie names so the router's affinity hash can steer
        to restorable — not just resident — prefixes)."""
        with self._lock:
            return [k[1] for k in self._entries if k[0] == kind]

    def stats(self) -> Dict[str, int]:
        """Occupancy + lifetime counters for signals()/metrics: pages
        and bytes split by tier, plus monotonic spill/restore/drop
        totals."""
        with self._lock:
            ram_pages = ram_bytes = disk_pages = 0
            for e in self._entries.values():
                if e.data is not None:
                    ram_pages += e.pages
                    ram_bytes += len(e.data)
                elif e.on_disk:
                    disk_pages += e.pages
            return {
                "entries": len(self._entries),
                "ram_pages": ram_pages,
                "ram_bytes": ram_bytes,
                "dir_pages": disk_pages,
                "spilled_bytes_total": self.spilled_bytes_total,
                "spilled_pages_total": self.spilled_pages_total,
                "restored_total": self.restored_total,
                "dropped_total": self.dropped_total,
            }
