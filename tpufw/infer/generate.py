"""Autoregressive generation: KV-cache prefill + lax.scan decode, jitted.

TPU-first shape discipline: prompts are LEFT-padded to one static length,
the KV cache is a fixed [B, max_seq_len] ring of slots, and the decode loop
is a ``lax.scan`` over a static number of steps — one compiled program
regardless of prompt lengths or early EOS (finished rows keep stepping but
their outputs are frozen to ``pad_id``; masking, not control flow). The
reference has no inference stack to mirror (workload is ``nvidia-smi``,
reference ``README.md:314``) — this is the serving half a complete
framework needs next to the trainer.

Left-padding is what makes ragged batches one program: every live token
sits flush against the cache cursor, RoPE positions are slot - pad_len,
and pad slots carry segment 0 so attention never sees them
(tpufw.models.llama Attention._cached_attention).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.infer.sampling import SamplingConfig, sample_token


def cast_decode_params(params, dtype=jnp.bfloat16):
    """Serving-precision cast: float32 weights -> ``dtype``.

    Decode streams every weight once per token, so fp32 ``param_dtype``
    (the training default — fp32 master weights) DOUBLES the
    HBM-bandwidth bill of the bandwidth-bound phase for no serving
    benefit; the matmuls already compute in ``cfg.dtype``. The only
    leaves kept fp32 are int8 quant scales — identified by their
    ``q_kernel`` SIBLING, not by name, since flax RMSNorm weights are
    also called ``scale`` and those SHOULD cast."""

    def walk(node):
        if isinstance(node, dict):
            is_quant = "q_kernel" in node
            return {
                k: v if (is_quant and k == "scale") else walk(v)
                for k, v in node.items()
            }
        if getattr(node, "dtype", None) == jnp.float32:
            return node.astype(dtype)
        return node

    return walk(params)


def pad_prompts(
    prompts: Sequence[Sequence[int]], pad_id: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Left-pad ragged prompts to [B, max_len]; returns (tokens, pad_lens)."""
    max_len = max(len(p) for p in prompts)
    out = np.full((len(prompts), max_len), pad_id, np.int32)
    pads = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        pads[i] = max_len - len(p)
        if len(p):
            out[i, pads[i]:] = np.asarray(p, np.int32)
    return out, pads


def prefill_cache(
    apply,
    prompt_tokens: jax.Array,
    positions: jax.Array,
    seg: jax.Array,
    prefill_chunk_size: Optional[int],
):
    """Prefill: the whole (padded) prompt through the cache — one pass,
    or fixed-size chunks under ``prefill_chunk_size`` (the cache cursor
    advances per chunk; slot-ordered causality makes chunked and
    one-shot prefill write identical caches). Left-padding makes the
    last column the final real token of every row either way.
    ``apply(cache, tokens, positions, seg) -> (logits, cache)``; ONE
    copy shared by ``generate`` and ``speculative_generate`` so the
    long-prompt lever can't drift between plain and speculative
    serving. Full chunks run under ONE ``lax.scan`` program (O(1)
    trace cost regardless of prompt length); an indivisible tail adds
    at most one remainder program."""
    b, p = prompt_tokens.shape
    if not (prefill_chunk_size is not None and 1 <= prefill_chunk_size < p):
        return apply({}, prompt_tokens, positions, seg)
    c = prefill_chunk_size
    n_full = p // c
    # Chunk 0 outside the scan: its apply CREATES the cache
    # variables the scan then carries.
    logits, cache = apply(
        {}, prompt_tokens[:, :c], positions[:, :c], seg[:, :c]
    )

    def mid(a, n):  # [B, (n)*c] -> [n, B, c]
        return a[:, c: (n + 1) * c].reshape(b, n, c).swapaxes(0, 1)

    if n_full > 1:
        def chunk_step(carry, xs):
            cache, _ = carry
            tok_c, pos_c, seg_c = xs
            lg, cache = apply(cache, tok_c, pos_c, seg_c)
            return (cache, lg), None

        # Logits ride the CARRY (each chunk overwrites), so the
        # scan never stacks a [n_chunks, B, c, V] output.
        (cache, logits), _ = jax.lax.scan(
            chunk_step,
            (cache, logits),
            (
                mid(prompt_tokens, n_full - 1),
                mid(positions, n_full - 1),
                mid(seg, n_full - 1),
            ),
        )
    if p % c:
        s = n_full * c
        logits, cache = apply(
            cache, prompt_tokens[:, s:], positions[:, s:], seg[:, s:]
        )
    return logits, cache


@partial(
    jax.jit,
    static_argnames=(
        "model", "max_new_tokens", "sampling", "pad_id", "eos_id",
        "prefill_chunk_size",
    ),
)
def generate(
    model,
    params,
    prompt_tokens: jax.Array,
    pad_lens: jax.Array,
    rng: jax.Array,
    *,
    max_new_tokens: int,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    prefill_chunk_size: Optional[int] = None,
    live_rows: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate continuations. Returns [B, max_new_tokens] int32.

    Args:
      model: a decode-mode module (``Llama(cfg.decode_config())`` or
        ``Mixtral(...)``) — must populate the "cache" collection.
      params: trained params (the training-mode tree; identical structure).
      prompt_tokens: [B, P] int32, LEFT-padded (see ``pad_prompts``).
      pad_lens: [B] int32 pad count per row.
      rng: sampling key (unused for greedy).
      max_new_tokens: static decode length; rows that hit ``eos_id`` emit
        ``pad_id`` from then on.
      prefill_chunk_size: process the prompt through the cache in
        chunks of this many positions instead of one [B, P] forward —
        prefill's transient activations then scale with the CHUNK, not
        the prompt (the long-prompt serving lever; attention still sees
        every cached earlier chunk). Full chunks run under ONE
        ``lax.scan`` program (O(1) trace cost regardless of prompt
        length); an indivisible tail adds at most one remainder
        program. No padding, no extra cache slots; a chunk >= the
        prompt degrades to the one-shot path.
      live_rows: optional [B] bool mask; False rows (batch fillers —
        pow-2 padding, length-bucket sentinels) start done, so they
        emit ``pad_id`` from step 1 instead of decoding garbage and,
        in the streaming path, never hold up the all-done early exit.
    """
    b, p = prompt_tokens.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    max_seq = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    # Only p + max_new_tokens - 1 slots are written (the final sampled
    # token is never fed back). Past max_seq_len the cache cursor clamps
    # and silently overwrites the last slot — fail at trace time instead.
    if max_seq is not None and p + max_new_tokens - 1 > max_seq:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the KV cache (max_seq_len={max_seq})"
        )
    cache, first, pos0, done, seen, step_rngs = _prefill_and_first(
        model, params, prompt_tokens, pad_lens, rng,
        n_step_keys=max_new_tokens - 1, sampling=sampling,
        eos_id=eos_id, prefill_chunk_size=prefill_chunk_size,
        live_rows=live_rows,
    )
    if max_new_tokens == 1:
        return first[:, None]
    step = _decode_step(
        _model_apply(model, params), b,
        sampling=sampling, pad_id=pad_id, eos_id=eos_id,
    )
    (_, _, _, _, _), rest = jax.lax.scan(
        step, (cache, first, pos0, done, seen), step_rngs
    )
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def _model_apply(model, params):
    """The ONE cached-decode apply closure (mirrors the copy
    ``speculative_generate`` binds): tokens through the model with the
    cache collection mutable, MoE aux dropped."""

    def apply(cache, tokens, positions, seg):
        out, vars_ = model.apply(
            {"params": params, **cache},
            tokens,
            positions=positions,
            segment_ids=seg,
            mutable=["cache"],
        )
        logits = out[0] if isinstance(out, tuple) else out
        return logits, {"cache": vars_["cache"]}

    return apply


def split_prefill_keys(rng: jax.Array, n_step_keys: int):
    """THE key-split contract, extracted so every prefill flavor
    (``_prefill_and_first`` here, the prefix-shared suffix prefill in
    tpufw.infer.pages) derives identical keys from the same ``rng``:
    first = split(rng)[1], step i = split(split(rng)[0], n)[i-1] with
    n = max(n_step_keys, 1) — split(rng, n)[i] is NOT stable across n
    on every jax version, so parity consumers must reproduce this
    exact split count. Returns (first_rng, step_keys)."""
    next_rng, first_rng = jax.random.split(rng)
    return first_rng, jax.random.split(next_rng, max(n_step_keys, 1))


def _prefill_and_first(
    model,
    params,
    prompt_tokens: jax.Array,
    pad_lens: jax.Array,
    rng: jax.Array,
    *,
    n_step_keys: int,
    sampling: SamplingConfig,
    eos_id: Optional[int],
    prefill_chunk_size: Optional[int],
    live_rows: Optional[jax.Array] = None,
):
    """ONE copy of the prefill + first-token + key-split discipline,
    shared by ``generate`` and the streaming path — streamed chunks are
    bit-identical to the one-shot decode BY CONSTRUCTION, not by
    hand-synced duplicates (same rule as ``prefill_cache``'s sharing
    with the speculative path). Key order: first = split(rng)[1],
    step i = split(split(rng)[0], n)[i-1]; split(rng, n)[i] is NOT
    stable across n on every jax version, so every bit-parity consumer
    (streaming, speculative) must reproduce this exact split count,
    n = max(max_new_tokens - 1, 1). Returns
    (cache, first, pos0, done0, seen, step_keys); ``seen`` is None
    unless the repetition penalty needs the [B, V] presence mask (it
    costs B*V bools in the decode carry)."""
    b, p = prompt_tokens.shape
    seg = (jnp.arange(p)[None, :] >= pad_lens[:, None]).astype(jnp.int32)
    positions = jnp.maximum(jnp.arange(p)[None, :] - pad_lens[:, None], 0)
    apply = _model_apply(model, params)
    logits, cache = prefill_cache(
        apply, prompt_tokens, positions, seg, prefill_chunk_size
    )
    track_seen = (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    )
    seen = None
    if track_seen:
        vocab = logits.shape[-1]
        real = seg > 0  # seg is always built above; 0 marks padding
        seen = (
            jnp.zeros((b, vocab), bool)
            .at[jnp.arange(b)[:, None], prompt_tokens]
            .max(real)
        )
    first_rng, step_keys = split_prefill_keys(rng, n_step_keys)
    first = sample_token(logits[:, -1, :], sampling, first_rng, seen)
    if track_seen:
        seen = seen.at[jnp.arange(b), first].set(True)
    # The EOS token itself is emitted; only rows ALREADY done emit pad.
    done = jnp.zeros((b,), bool) if eos_id is None else first == eos_id
    if live_rows is not None:
        # Filler rows are born done: they emit pad from step 1 and never
        # gate the streaming all-done early exit.
        done = done | ~live_rows
    return cache, first, p - pad_lens, done, seen, step_keys


def _decode_step(apply, b: int, *, sampling, pad_id, eos_id):
    """ONE copy of the decode step body (sample → seen update → pad
    frozen rows → eos), scanned over all keys by ``generate`` and over
    per-chunk key slices by ``_stream_chunk`` — the other half of the
    stream/one-shot bit-parity contract."""
    track_seen = (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    )
    ones = jnp.ones((b, 1), jnp.int32)

    def step(carry, rng_step):
        cache, token, pos, done, seen = carry
        logits, cache = apply(cache, token[:, None], pos[:, None], ones)
        nxt = sample_token(logits[:, -1, :], sampling, rng_step, seen)
        if track_seen:
            seen = seen.at[jnp.arange(b), nxt].set(True)
        emitted = jnp.where(done, pad_id, nxt)
        if eos_id is not None:
            done = done | (nxt == eos_id)
        return (cache, emitted, pos + 1, done, seen), emitted

    return step


@partial(
    jax.jit,
    static_argnames=(
        "model", "n_step_keys", "sampling", "eos_id",
        "prefill_chunk_size",
    ),
)
def _stream_prefill(
    model,
    params,
    prompt_tokens: jax.Array,
    pad_lens: jax.Array,
    rng: jax.Array,
    *,
    n_step_keys: int,
    sampling: SamplingConfig,
    eos_id: Optional[int],
    prefill_chunk_size: Optional[int],
    live_rows: Optional[jax.Array] = None,
):
    """Streaming phase 1: jit boundary over the SHARED
    ``_prefill_and_first`` (the bit-parity contract lives there)."""
    return _prefill_and_first(
        model, params, prompt_tokens, pad_lens, rng,
        n_step_keys=n_step_keys, sampling=sampling, eos_id=eos_id,
        prefill_chunk_size=prefill_chunk_size, live_rows=live_rows,
    )


@partial(
    jax.jit,
    static_argnames=("model", "sampling", "pad_id", "eos_id"),
    donate_argnames=("cache", "seen"),
)
def _stream_chunk(
    model,
    params,
    cache,
    token: jax.Array,
    pos: jax.Array,
    done: jax.Array,
    seen: jax.Array,
    keys: jax.Array,
    *,
    sampling: SamplingConfig,
    pad_id: int,
    eos_id: Optional[int],
):
    """Streaming phase 2: decode ``len(keys)`` tokens from the carried
    cache — the SHARED ``_decode_step`` body ``generate`` scans
    (including the emitted-token feedback: done rows feed pad back),
    scanned over this chunk's key slice. One compiled program serves
    every full chunk of a stream AND every later stream with the same
    shapes; the cache/seen buffers are donated so chunks update in
    place."""
    step = _decode_step(
        _model_apply(model, params), token.shape[0],
        sampling=sampling, pad_id=pad_id, eos_id=eos_id,
    )
    (cache, token, pos, done, seen), out = jax.lax.scan(
        step, (cache, token, pos, done, seen), keys
    )
    return cache, token, pos, done, seen, out.T  # [B, chunk]


def generate_stream(
    model,
    params,
    prompts: Sequence[Sequence[int]],
    *,
    max_new_tokens: int,
    chunk_size: int = 16,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    prefill_chunk_size: Optional[int] = None,
    live_rows: Optional[Sequence[bool]] = None,
):
    """Streaming decode: yields ``[B, n]`` int32 numpy chunks whose
    concatenation is BIT-identical to ``generate``'s output under the
    same rng (greedy, sampled, penalized — every knob), truncated early
    when every row has passed its eos (the dropped tail is all pad).

    The stream pays one host round trip per chunk (the natural yield
    point) instead of per token; every full chunk reuses ONE compiled
    program, so time-to-first-token is prefill + one chunk and the
    steady rate approaches plain decode as chunk_size grows. First
    yield carries ``chunk_size`` tokens (the prefill-sampled token
    plus chunk_size - 1 steps), later yields ``chunk_size``, the tail
    whatever remains.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    max_seq = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    tokens, pads = pad_prompts(prompts, pad_id)
    p = tokens.shape[1]
    if max_seq is not None and p + max_new_tokens - 1 > max_seq:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the KV cache (max_seq_len={max_seq})"
        )
    if rng is None:
        rng = jax.random.key(seed)
    cache, token, pos, done, seen, step_keys = _stream_prefill(
        model,
        params,
        jnp.asarray(tokens),
        jnp.asarray(pads),
        rng,
        n_step_keys=max_new_tokens - 1,
        sampling=sampling,
        eos_id=eos_id,
        prefill_chunk_size=prefill_chunk_size,
        live_rows=(
            None if live_rows is None
            else jnp.asarray(np.asarray(live_rows, bool))
        ),
    )
    first = np.asarray(token)[:, None]
    if max_new_tokens == 1:
        yield first
        return
    emitted = 1
    head: Optional[np.ndarray] = first  # rides the first yield
    if chunk_size == 1:
        # A 1-token chunk can't carry the head plus a step: the
        # prefill-sampled token IS the first chunk.
        yield head
        head = None
        if eos_id is not None and bool(np.asarray(done).all()):
            return
    while emitted < max_new_tokens:
        n = min(
            chunk_size - 1 if head is not None else chunk_size,
            max_new_tokens - emitted,
        )
        # tpulint: disable=TPU007 -- the key slice's tail chunk
        # (n < chunk_size) is the ONE deliberately distinct shape per
        # stream; every full chunk reuses a single compiled program
        # (TRACE_COUNTS-asserted in tests), so the program ladder is
        # bounded by design, not churn.
        cache, token, pos, done, seen, out = _stream_chunk(
            model,
            params,
            cache,
            token,
            pos,
            done,
            seen,
            step_keys[emitted - 1: emitted - 1 + n],
            sampling=sampling,
            pad_id=pad_id,
            eos_id=eos_id,
        )
        chunk = np.asarray(out)
        if head is not None:
            chunk = np.concatenate([head, chunk], axis=1)
            head = None
        emitted += n
        yield chunk
        # After-yield: once every row is past eos the remaining
        # emissions are all pad — stop instead of decoding dead air.
        if eos_id is not None and bool(np.asarray(done).all()):
            return


def generate_text_stream(
    model,
    params,
    prompts: Sequence[Sequence[int]],
    *,
    max_new_tokens: int,
    chunk_size: int = 16,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    seed: int = 0,
    prefill_chunk_size: Optional[int] = None,
    live_rows: Optional[Sequence[bool]] = None,
):
    """Ragged streaming wrapper: yields, per chunk, one ``list[int]``
    of NEW tokens per row — rows stop emitting after their eos (the
    eos itself is included), mirroring ``generate_text``'s truncation
    row by row. Concatenating a row's chunks equals the row
    ``generate_text`` returns."""
    row_done = [False] * len(prompts)
    for chunk in generate_stream(
        model, params, prompts,
        max_new_tokens=max_new_tokens, chunk_size=chunk_size,
        sampling=sampling, pad_id=pad_id, eos_id=eos_id, seed=seed,
        prefill_chunk_size=prefill_chunk_size, live_rows=live_rows,
    ):
        out: list[list[int]] = []
        for i, row in enumerate(chunk):
            toks = [] if row_done[i] else row.tolist()
            if eos_id is not None and not row_done[i] and eos_id in toks:
                toks = toks[: toks.index(eos_id) + 1]
                row_done[i] = True
            out.append(toks)
        yield out


def generate_text(
    model,
    params,
    prompts: Sequence[Sequence[int]],
    *,
    max_new_tokens: int,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    seed: int = 0,
    prefill_chunk_size: Optional[int] = None,
    live_rows: Optional[Sequence[bool]] = None,
) -> list[list[int]]:
    """Convenience wrapper: ragged python prompts in, ragged lists out."""
    tokens, pads = pad_prompts(prompts, pad_id)
    out = generate(
        model,
        params,
        jnp.asarray(tokens),
        jnp.asarray(pads),
        jax.random.key(seed),
        max_new_tokens=max_new_tokens,
        sampling=sampling,
        pad_id=pad_id,
        eos_id=eos_id,
        prefill_chunk_size=prefill_chunk_size,
        live_rows=(
            None if live_rows is None
            else jnp.asarray(np.asarray(live_rows, bool))
        ),
    )
    result = []
    for row in np.asarray(out):
        toks = row.tolist()
        if eos_id is not None and eos_id in toks:
            toks = toks[: toks.index(eos_id) + 1]
        result.append(toks)
    return result
