"""Autoregressive generation: KV-cache prefill + lax.scan decode, jitted.

TPU-first shape discipline: prompts are LEFT-padded to one static length,
the KV cache is a fixed [B, max_seq_len] ring of slots, and the decode loop
is a ``lax.scan`` over a static number of steps — one compiled program
regardless of prompt lengths or early EOS (finished rows keep stepping but
their outputs are frozen to ``pad_id``; masking, not control flow). The
reference has no inference stack to mirror (workload is ``nvidia-smi``,
reference ``README.md:314``) — this is the serving half a complete
framework needs next to the trainer.

Left-padding is what makes ragged batches one program: every live token
sits flush against the cache cursor, RoPE positions are slot - pad_len,
and pad slots carry segment 0 so attention never sees them
(tpufw.models.llama Attention._cached_attention).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.infer.sampling import SamplingConfig, sample_token


def cast_decode_params(params, dtype=jnp.bfloat16):
    """Serving-precision cast: float32 weights -> ``dtype``.

    Decode streams every weight once per token, so fp32 ``param_dtype``
    (the training default — fp32 master weights) DOUBLES the
    HBM-bandwidth bill of the bandwidth-bound phase for no serving
    benefit; the matmuls already compute in ``cfg.dtype``. The only
    leaves kept fp32 are int8 quant scales — identified by their
    ``q_kernel`` SIBLING, not by name, since flax RMSNorm weights are
    also called ``scale`` and those SHOULD cast."""

    def walk(node):
        if isinstance(node, dict):
            is_quant = "q_kernel" in node
            return {
                k: v if (is_quant and k == "scale") else walk(v)
                for k, v in node.items()
            }
        if getattr(node, "dtype", None) == jnp.float32:
            return node.astype(dtype)
        return node

    return walk(params)


def pad_prompts(
    prompts: Sequence[Sequence[int]], pad_id: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """Left-pad ragged prompts to [B, max_len]; returns (tokens, pad_lens)."""
    max_len = max(len(p) for p in prompts)
    out = np.full((len(prompts), max_len), pad_id, np.int32)
    pads = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        pads[i] = max_len - len(p)
        if len(p):
            out[i, pads[i]:] = np.asarray(p, np.int32)
    return out, pads


def prefill_cache(
    apply,
    prompt_tokens: jax.Array,
    positions: jax.Array,
    seg: jax.Array,
    prefill_chunk_size: Optional[int],
):
    """Prefill: the whole (padded) prompt through the cache — one pass,
    or fixed-size chunks under ``prefill_chunk_size`` (the cache cursor
    advances per chunk; slot-ordered causality makes chunked and
    one-shot prefill write identical caches). Left-padding makes the
    last column the final real token of every row either way.
    ``apply(cache, tokens, positions, seg) -> (logits, cache)``; ONE
    copy shared by ``generate`` and ``speculative_generate`` so the
    long-prompt lever can't drift between plain and speculative
    serving. Full chunks run under ONE ``lax.scan`` program (O(1)
    trace cost regardless of prompt length); an indivisible tail adds
    at most one remainder program."""
    b, p = prompt_tokens.shape
    if not (prefill_chunk_size is not None and 1 <= prefill_chunk_size < p):
        return apply({}, prompt_tokens, positions, seg)
    c = prefill_chunk_size
    n_full = p // c
    # Chunk 0 outside the scan: its apply CREATES the cache
    # variables the scan then carries.
    logits, cache = apply(
        {}, prompt_tokens[:, :c], positions[:, :c], seg[:, :c]
    )

    def mid(a, n):  # [B, (n)*c] -> [n, B, c]
        return a[:, c: (n + 1) * c].reshape(b, n, c).swapaxes(0, 1)

    if n_full > 1:
        def chunk_step(carry, xs):
            cache, _ = carry
            tok_c, pos_c, seg_c = xs
            lg, cache = apply(cache, tok_c, pos_c, seg_c)
            return (cache, lg), None

        # Logits ride the CARRY (each chunk overwrites), so the
        # scan never stacks a [n_chunks, B, c, V] output.
        (cache, logits), _ = jax.lax.scan(
            chunk_step,
            (cache, logits),
            (
                mid(prompt_tokens, n_full - 1),
                mid(positions, n_full - 1),
                mid(seg, n_full - 1),
            ),
        )
    if p % c:
        s = n_full * c
        logits, cache = apply(
            cache, prompt_tokens[:, s:], positions[:, s:], seg[:, s:]
        )
    return logits, cache


@partial(
    jax.jit,
    static_argnames=(
        "model", "max_new_tokens", "sampling", "pad_id", "eos_id",
        "prefill_chunk_size",
    ),
)
def generate(
    model,
    params,
    prompt_tokens: jax.Array,
    pad_lens: jax.Array,
    rng: jax.Array,
    *,
    max_new_tokens: int,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    prefill_chunk_size: Optional[int] = None,
) -> jax.Array:
    """Generate continuations. Returns [B, max_new_tokens] int32.

    Args:
      model: a decode-mode module (``Llama(cfg.decode_config())`` or
        ``Mixtral(...)``) — must populate the "cache" collection.
      params: trained params (the training-mode tree; identical structure).
      prompt_tokens: [B, P] int32, LEFT-padded (see ``pad_prompts``).
      pad_lens: [B] int32 pad count per row.
      rng: sampling key (unused for greedy).
      max_new_tokens: static decode length; rows that hit ``eos_id`` emit
        ``pad_id`` from then on.
      prefill_chunk_size: process the prompt through the cache in
        chunks of this many positions instead of one [B, P] forward —
        prefill's transient activations then scale with the CHUNK, not
        the prompt (the long-prompt serving lever; attention still sees
        every cached earlier chunk). Full chunks run under ONE
        ``lax.scan`` program (O(1) trace cost regardless of prompt
        length); an indivisible tail adds at most one remainder
        program. No padding, no extra cache slots; a chunk >= the
        prompt degrades to the one-shot path.
    """
    b, p = prompt_tokens.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    max_seq = getattr(getattr(model, "cfg", None), "max_seq_len", None)
    # Only p + max_new_tokens - 1 slots are written (the final sampled
    # token is never fed back). Past max_seq_len the cache cursor clamps
    # and silently overwrites the last slot — fail at trace time instead.
    if max_seq is not None and p + max_new_tokens - 1 > max_seq:
        raise ValueError(
            f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"the KV cache (max_seq_len={max_seq})"
        )
    seg = (jnp.arange(p)[None, :] >= pad_lens[:, None]).astype(jnp.int32)
    positions = jnp.maximum(jnp.arange(p)[None, :] - pad_lens[:, None], 0)

    def apply(cache, tokens, positions, seg):
        out, vars_ = model.apply(
            {"params": params, **cache},
            tokens,
            positions=positions,
            segment_ids=seg,
            mutable=["cache"],
        )
        logits = out[0] if isinstance(out, tuple) else out  # MoE aux dropped
        return logits, {"cache": vars_["cache"]}

    logits, cache = prefill_cache(
        apply, prompt_tokens, positions, seg, prefill_chunk_size
    )
    # Repetition penalty needs a [B, V] presence mask of every token the
    # model has seen (prompt + generated). Built only when enabled — it
    # costs B*V bools in the scan carry.
    track_seen = (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    )
    vocab = logits.shape[-1]
    seen = None
    if track_seen:
        real = seg > 0  # seg is always built above; 0 marks padding
        seen = (
            jnp.zeros((b, vocab), bool)
            .at[jnp.arange(b)[:, None], prompt_tokens]
            .max(real)
        )
    next_rng, rng = jax.random.split(rng)
    first = sample_token(logits[:, -1, :], sampling, rng, seen)
    if track_seen:
        seen = seen.at[jnp.arange(b), first].set(True)
    # The EOS token itself is emitted; only rows ALREADY done emit pad.
    done = jnp.zeros((b,), bool) if eos_id is None else first == eos_id

    def step(carry, rng_step):
        cache, token, pos, done, seen = carry
        logits, cache = apply(
            cache,
            token[:, None],
            pos[:, None],
            jnp.ones((b, 1), jnp.int32),
        )
        nxt = sample_token(logits[:, -1, :], sampling, rng_step, seen)
        if track_seen:
            seen = seen.at[jnp.arange(b), nxt].set(True)
        emitted = jnp.where(done, pad_id, nxt)
        if eos_id is not None:
            done = done | (nxt == eos_id)
        return (cache, emitted, pos + 1, done, seen), emitted

    # Positions continue from each row's real length (p - pad_len).
    pos0 = p - pad_lens
    step_rngs = jax.random.split(next_rng, max(max_new_tokens - 1, 1))
    if max_new_tokens == 1:
        return first[:, None]
    (_, _, _, _, _), rest = jax.lax.scan(
        step, (cache, first, pos0, done, seen), step_rngs
    )
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def generate_text(
    model,
    params,
    prompts: Sequence[Sequence[int]],
    *,
    max_new_tokens: int,
    sampling: SamplingConfig = SamplingConfig(),
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    seed: int = 0,
    prefill_chunk_size: Optional[int] = None,
) -> list[list[int]]:
    """Convenience wrapper: ragged python prompts in, ragged lists out."""
    tokens, pads = pad_prompts(prompts, pad_id)
    out = generate(
        model,
        params,
        jnp.asarray(tokens),
        jnp.asarray(pads),
        jax.random.key(seed),
        max_new_tokens=max_new_tokens,
        sampling=sampling,
        pad_id=pad_id,
        eos_id=eos_id,
        prefill_chunk_size=prefill_chunk_size,
    )
    result = []
    for row in np.asarray(out):
        toks = row.tolist()
        if eos_id is not None and eos_id in toks:
            toks = toks[: toks.index(eos_id) + 1]
        result.append(toks)
    return result
