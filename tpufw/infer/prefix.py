"""Host-side radix/trie prefix cache over KV pages.

At millions-of-users scale most traffic opens with a long shared
system/few-shot prefix; without sharing, every request re-prefills it
and holds a private KV copy. This trie maps PAGE-GRANULAR token chunks
(the paged pool's fixed page size, ``tpufw.infer.pages``) to resident
physical pages: a new request walks its prompt down the trie, and every
matched full page is attached to the row's page table by reference —
prefill is skipped for the shared tokens and HBM holds one copy.

Copy-on-write is structural, not a device copy: only FULL pages strictly
before a row's first write slot are ever shared (the pool enforces
``shared_len <= prompt_len - 1``, and decode writes start at
``prompt_len``), so divergence after the shared point lands in the row's
private pages by construction.

Sharing/lifetime is split across two owners:
- rows reference pages via ``PageAllocator`` refcounts (released at
  retire);
- the trie HOLDS resident pages (``allocator.hold``) so they survive
  their origin row, until ``evict`` drops refcount-0 leaves LRU-first
  under HBM pressure.

All bookkeeping is pure host Python on the scheduler thread — nothing
here touches the device or a jit trace.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class _Node:
    __slots__ = ("children", "page", "stamp", "parent", "key")

    def __init__(self, parent: Optional["_Node"], key, page: int):
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.parent = parent
        self.key = key
        self.page = page
        self.stamp = 0


class PrefixCache:
    """Radix trie keyed by page-sized token chunks.

    Each node is one FULL page of tokens and carries the physical page
    id holding that chunk's K/V (valid only in the context of its
    ancestors — K/V at slot j depends on all tokens <= j, so a path
    from the root is the unit of reuse, never a node alone).
    """

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self.root = _Node(None, None, -1)
        self._tick = 0
        self._n_nodes = 0

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        p = self.page_size
        n_full = len(tokens) // p
        return [tuple(tokens[i * p:(i + 1) * p]) for i in range(n_full)]

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Physical page ids of the longest resident full-page prefix
        of ``tokens`` (possibly empty). Touches the path's LRU stamps;
        the CALLER takes row references (``allocator.ref``) on the ids
        it actually uses."""
        ids: List[int] = []
        node = self.root
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            self._tick += 1
            child.stamp = self._tick
            ids.append(child.page)
            node = child
        return ids

    def insert(
        self, tokens: Sequence[int], page_ids: Sequence[int]
    ) -> List[int]:
        """Register ``tokens``' full-page chunks as resident in
        ``page_ids`` (one id per full page, the row's own pages).
        Chunks already on the trie keep their EXISTING page (same
        tokens => same K/V content; the duplicate page stays row-owned
        and dies with the row). Returns the ids newly adopted by the
        trie — the caller must ``allocator.hold`` exactly those."""
        node = self.root
        adopted: List[int] = []
        for chunk, pid in zip(self._chunks(tokens), page_ids):
            child = node.children.get(chunk)
            if child is None:
                child = _Node(node, chunk, int(pid))
                node.children[chunk] = child
                self._n_nodes += 1
                adopted.append(int(pid))
            self._tick += 1
            child.stamp = self._tick
            node = child
        return adopted

    def evict(self, n: int, allocator) -> List[int]:
        """Drop up to ``n`` refcount-0 LEAF pages, least-recently-used
        first, cascading into parents as they become leaves. Returns
        the dropped page ids (the caller's ``allocator.drop`` already
        ran — ids are free iff no row still references them)."""
        dropped: List[int] = []
        while len(dropped) < n:
            victim = None
            stack = [self.root]
            while stack:
                node = stack.pop()
                if node is not self.root and not node.children:
                    if allocator.refs.get(node.page, 0) == 0 and (
                        victim is None or node.stamp < victim.stamp
                    ):
                        victim = node
                else:
                    stack.extend(node.children.values())
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self._n_nodes -= 1
            allocator.drop([victim.page])
            dropped.append(victim.page)
        return dropped

    def __len__(self) -> int:
        return self._n_nodes
