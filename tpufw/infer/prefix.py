"""Host-side radix/trie prefix cache over KV pages.

At millions-of-users scale most traffic opens with a long shared
system/few-shot prefix; without sharing, every request re-prefills it
and holds a private KV copy. This trie maps PAGE-GRANULAR token chunks
(the paged pool's fixed page size, ``tpufw.infer.pages``) to resident
physical pages: a new request walks its prompt down the trie, and every
matched full page is attached to the row's page table by reference —
prefill is skipped for the shared tokens and HBM holds one copy.

Copy-on-write is structural, not a device copy: only FULL pages strictly
before a row's first write slot are ever shared (the pool enforces
``shared_len <= prompt_len - 1``, and decode writes start at
``prompt_len``), so divergence after the shared point lands in the row's
private pages by construction.

Sharing/lifetime is split across two owners:
- rows reference pages via ``PageAllocator`` refcounts (released at
  retire);
- the trie HOLDS resident pages (``allocator.hold``) so they survive
  their origin row, until ``evict`` drops refcount-0 leaves LRU-first
  under HBM pressure.

All bookkeeping is pure host Python on the scheduler thread — nothing
here touches the device or a jit trace.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple


class _Node:
    __slots__ = ("children", "page", "stamp", "parent", "key")

    def __init__(self, parent: Optional["_Node"], key, page: int):
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.parent = parent
        self.key = key
        self.page = page
        self.stamp = 0


class PrefixCache:
    """Radix trie keyed by page-sized token chunks.

    Each node is one FULL page of tokens and carries the physical page
    id holding that chunk's K/V (valid only in the context of its
    ancestors — K/V at slot j depends on all tokens <= j, so a path
    from the root is the unit of reuse, never a node alone).
    """

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self.root = _Node(None, None, -1)
        self._tick = 0
        self._n_nodes = 0
        #: Content version: bumps on insert/evict, NOT on match — so
        #: digest advertisement (tpufw.serve.roles signals()) can
        #: cache its path walk and recompute only when the resident
        #: set actually changed ("digest updates at chunk boundaries").
        self.version = 0

    def _chunks(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        p = self.page_size
        n_full = len(tokens) // p
        return [tuple(tokens[i * p:(i + 1) * p]) for i in range(n_full)]

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Physical page ids of the longest resident full-page prefix
        of ``tokens`` (possibly empty). Touches the path's LRU stamps;
        the CALLER takes row references (``allocator.ref``) on the ids
        it actually uses."""
        ids: List[int] = []
        node = self.root
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            self._tick += 1
            child.stamp = self._tick
            ids.append(child.page)
            node = child
        return ids

    def insert(
        self, tokens: Sequence[int], page_ids: Sequence[int]
    ) -> List[int]:
        """Register ``tokens``' full-page chunks as resident in
        ``page_ids`` (one id per full page, the row's own pages).
        Chunks already on the trie keep their EXISTING page (same
        tokens => same K/V content; the duplicate page stays row-owned
        and dies with the row). Returns the ids newly adopted by the
        trie — the caller must ``allocator.hold`` exactly those."""
        node = self.root
        adopted: List[int] = []
        for chunk, pid in zip(self._chunks(tokens), page_ids):
            child = node.children.get(chunk)
            if child is None:
                child = _Node(node, chunk, int(pid))
                node.children[chunk] = child
                self._n_nodes += 1
                self.version += 1
                adopted.append(int(pid))
            self._tick += 1
            child.stamp = self._tick
            node = child
        return adopted

    @staticmethod
    def _path_tokens(node: _Node) -> Tuple[int, ...]:
        """Full token path from the root to ``node`` (the unit a spill
        entry is keyed by — a page's KV is only valid under its
        ancestors, so the path IS the identity)."""
        chunks: List[Tuple[int, ...]] = []
        while node.parent is not None:
            chunks.append(node.key)
            node = node.parent
        out: List[int] = []
        for chunk in reversed(chunks):
            out.extend(chunk)
        return tuple(out)

    def evict(
        self,
        n: int,
        allocator,
        on_evict: "Optional[Callable[[Tuple[int, ...], int], None]]" = None,
    ) -> List[int]:
        """Drop up to ``n`` refcount-0 LEAF pages, least-recently-used
        first, cascading into parents as they become leaves. Returns
        the dropped page ids (the caller's ``allocator.drop`` already
        ran — ids are free iff no row still references them).

        ``on_evict(path_tokens, page_id)`` fires BEFORE the drop,
        while the page's arena bytes are still valid — the spill
        tier's hook point: it exports the page to host RAM so the
        eviction frees HBM without forgetting the KV."""
        dropped: List[int] = []
        while len(dropped) < n:
            victim = None
            stack = [self.root]
            while stack:
                node = stack.pop()
                if node is not self.root and not node.children:
                    if allocator.refs.get(node.page, 0) == 0 and (
                        victim is None or node.stamp < victim.stamp
                    ):
                        victim = node
                else:
                    stack.extend(node.children.values())
            if victim is None:
                break
            if on_evict is not None:
                on_evict(self._path_tokens(victim), victim.page)
            del victim.parent.children[victim.key]
            self._n_nodes -= 1
            self.version += 1
            allocator.drop([victim.page])
            dropped.append(victim.page)
        return dropped

    def paths(
        self, max_depth: int, limit: int = 0
    ) -> List[Tuple[int, ...]]:
        """Token paths of every resident node up to ``max_depth``
        chunks deep (optionally capped at ``limit`` paths, deepest
        last) — the digest-advertisement walk. Read-only: no LRU
        touch, no version bump."""
        out: List[Tuple[int, ...]] = []
        stack: List[Tuple[_Node, Tuple[int, ...], int]] = [
            (self.root, (), 0)
        ]
        while stack:
            node, toks, depth = stack.pop()
            if depth >= max_depth:
                continue
            for chunk, child in node.children.items():
                path = toks + chunk
                out.append(path)
                if limit and len(out) >= limit:
                    return out
                stack.append((child, path, depth + 1))
        return out

    def __len__(self) -> int:
        return self._n_nodes
