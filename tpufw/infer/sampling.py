"""Token sampling transforms: temperature, top-k, top-p, greedy.

Pure [B, V] logits -> [B] token functions, compiled into the decode loop
(tpufw.infer.generate). All masking is static-shape friendly: top-k uses
``lax.top_k``'s threshold rather than a gather, top-p masks on the sorted
cumulative distribution — no data-dependent shapes anywhere, per the XLA
tracing rules.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

_NEG = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    # 0.0 = greedy (argmax); otherwise logits are divided by temperature.
    temperature: float = 0.0
    # Keep only the k most likely tokens (0/None disables).
    top_k: Optional[int] = None
    # Nucleus sampling: keep the smallest set of tokens whose cumulative
    # probability reaches top_p (1.0/None disables).
    top_p: Optional[float] = None


def apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits. [B, V] -> [B, V]."""
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG, logits)


def apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus mask: keep the smallest prefix of the sorted distribution
    with cumulative probability >= p (the top token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Token i is kept while the mass BEFORE it is < p; the top token always
    # survives (p <= 0 must degrade to greedy-candidates, not mask-all).
    keep_sorted = (cum - probs) < p
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # Threshold = smallest kept logit; everything below it is masked.
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, _NEG, logits)


def sample_token(
    logits: jax.Array, cfg: SamplingConfig, rng: jax.Array
) -> jax.Array:
    """[B, V] float logits -> [B] int32 sampled tokens."""
    logits = logits.astype(jnp.float32)
    if cfg.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / cfg.temperature
    if cfg.top_k:
        logits = apply_top_k(logits, cfg.top_k)
    if cfg.top_p is not None and cfg.top_p < 1.0:
        logits = apply_top_p(logits, cfg.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
