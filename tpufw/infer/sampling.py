"""Token sampling transforms: temperature, top-k, top-p, greedy.

Pure [B, V] logits -> [B] token functions, compiled into the decode loop
(tpufw.infer.generate). All masking is static-shape friendly: top-k uses
``lax.top_k``'s threshold rather than a gather, top-p masks on the sorted
cumulative distribution — no data-dependent shapes anywhere, per the XLA
tracing rules.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

_NEG = -1e30


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    # 0.0 = greedy (argmax); otherwise logits are divided by temperature.
    temperature: float = 0.0
    # Keep only the k most likely tokens (0/None disables).
    top_k: Optional[int] = None
    # Nucleus sampling: keep the smallest set of tokens whose cumulative
    # probability reaches top_p (1.0/None disables).
    top_p: Optional[float] = None
    # Drop tokens whose probability is below min_p * max probability
    # (None disables) — a length-adaptive alternative to top_p.
    min_p: Optional[float] = None
    # HF-style repetition penalty (> 1.0 discourages): logits of tokens
    # already seen (prompt + generated so far) are divided by the
    # penalty when positive, multiplied when negative. 1.0/None
    # disables. Applied BEFORE temperature, matching transformers.
    repetition_penalty: Optional[float] = None


def apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits. [B, V] -> [B, V]."""
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG, logits)


def apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus mask: keep the smallest prefix of the sorted distribution
    with cumulative probability >= p (the top token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Token i is kept while the mass BEFORE it is < p; the top token always
    # survives (p <= 0 must degrade to greedy-candidates, not mask-all).
    keep_sorted = (cum - probs) < p
    keep_sorted = keep_sorted.at[..., 0].set(True)
    # Threshold = smallest kept logit; everything below it is masked.
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, _NEG, logits)


def apply_min_p(logits: jax.Array, p: float) -> jax.Array:
    """Mask tokens with probability < p * max probability."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    threshold = logprobs.max(axis=-1, keepdims=True) + jnp.log(p)
    return jnp.where(logprobs < threshold, _NEG, logits)


def apply_repetition_penalty(
    logits: jax.Array, seen: jax.Array, penalty: float
) -> jax.Array:
    """HF rule: for tokens in ``seen`` ([B, V] bool), positive logits
    divide by the penalty, negative multiply — both push probability
    down for penalty > 1."""
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def transform_logits(
    logits: jax.Array,
    cfg: SamplingConfig,
    seen: Optional[jax.Array] = None,
) -> jax.Array:
    """Apply cfg's distribution transforms to [..., V] logits — the
    exact distribution ``sample_token`` draws from, exposed separately
    so speculative rejection-resampling can compare draft and target
    distributions post-transform (the scheme's correctness requires the
    ratio test on the distributions actually sampled, not the raw
    logits). Greedy (temperature 0) returns after the penalty: argmax
    consumers need no masks."""
    logits = logits.astype(jnp.float32)
    if (
        cfg.repetition_penalty is not None
        and cfg.repetition_penalty != 1.0
        and seen is not None
    ):
        logits = apply_repetition_penalty(
            logits, seen, cfg.repetition_penalty
        )
    if cfg.temperature == 0.0:
        return logits
    logits = logits / cfg.temperature
    if cfg.top_k:
        logits = apply_top_k(logits, cfg.top_k)
    if cfg.top_p is not None and cfg.top_p < 1.0:
        logits = apply_top_p(logits, cfg.top_p)
    if cfg.min_p is not None and cfg.min_p > 0.0:
        logits = apply_min_p(logits, cfg.min_p)
    return logits


def sample_token(
    logits: jax.Array,
    cfg: SamplingConfig,
    rng: jax.Array,
    seen: Optional[jax.Array] = None,
) -> jax.Array:
    """[B, V] float logits -> [B] int32 sampled tokens. ``seen`` is the
    [B, V] bool presence mask the repetition penalty applies to (the
    decode loop maintains it; None skips the penalty)."""
    logits = transform_logits(logits, cfg, seen)
    if cfg.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
