from tpufw.infer.generate import (  # noqa: F401
    cast_decode_params,
    generate,
    generate_stream,
    generate_text,
    generate_text_stream,
    pad_prompts,
)
from tpufw.infer.pages import (  # noqa: F401
    PageAllocator,
    PagedSlotPool,
    paged_pool_cache,
)
from tpufw.infer.prefix import PrefixCache  # noqa: F401
from tpufw.infer.slots import (  # noqa: F401
    SlotPool,
    pool_cache,
    prefill_row,
)
from tpufw.infer.speculative import (  # noqa: F401
    speculative_generate,
    speculative_generate_text,
)
from tpufw.infer.sampling import (  # noqa: F401
    SamplingConfig,
    apply_top_k,
    apply_top_p,
    sample_token,
)
