"""Speculative decoding: draft proposes, target verifies in one pass.

Autoregressive decode is HBM-bandwidth-bound — every emitted token
streams every target weight once. Speculative decoding spends a small
draft model's tokens to buy back target bandwidth: the draft proposes
``k`` tokens autoregressively, the target scores ALL of them in ONE
cached forward (k+1 tokens through the weights instead of k+1 separate
full-weight streams), and the longest accepted prefix is kept plus one
token from the target's own distribution. Worst case one token per
iteration (plain decode cost + draft overhead); best case k+1.

Two acceptance modes, selected by ``sampling.temperature``:

- **Greedy** (temperature 0): accept while the draft token equals the
  target argmax — the output is EXACTLY the target model's greedy
  continuation, pinned against ``tpufw.infer.generate`` in
  tests/test_speculative.py.
- **Stochastic** (temperature > 0): the rejection-resample scheme.
  Draft token ``x_j ~ q_j`` is accepted iff ``u_j < p_j(x_j)/q_j(x_j)``
  (``u_j`` uniform); on first rejection the replacement is drawn from
  the residual ``norm(max(p_j - q_j, 0))``, and when every draft
  survives the bonus comes from ``p_k`` directly. Marginally, each
  emitted token is distributed EXACTLY as target-only sampling — draft
  quality changes speed, never the distribution. ``p``/``q`` are the
  post-transform distributions (temperature/top-k/top-p/min-p/
  repetition_penalty applied to both), so speculation composes with
  EVERY serving sampler knob. The repetition penalty's seen-token
  state is sequential by construction, but sequential-in-k is cheap
  when k is static: the draft updates its mask as it proposes, and
  the verify pass rebuilds the k+1 per-position masks cumulatively
  (seen_j = seen ∪ drafts[:, :j]) — each position's transformed
  target distribution is exactly what ``generate`` would have used at
  that emission index, so the acceptance test and residual stay
  distribution-exact.

RNG discipline: emission index ``n`` consumes the same key
``generate()`` would use for that index (first = split(rng)[1], rest =
split(split(rng)[0], ...)[n-1]), draft proposals draw with the RAW
per-index key, and acceptance/residual draws use fold_in(key, 1)/
fold_in(key, 2). Consequence: with draft == target every proposal is
accepted and the output is BIT-IDENTICAL to ``generate`` under the same
rng — the distributional-equivalence pin in tests/test_speculative.py.

TPU-first shape discipline, mirroring ``generate``:
- the whole loop is one jitted program: ``lax.while_loop`` over
  iterations (dynamic trip count, bounded by max_new_tokens since every
  iteration emits at least one token), static k, static buffer sizes;
- acceptance is uniform across the batch (the min over rows): the
  KV-cache cursor is one scalar. Rows that matched further simply take
  the bonus token — which equals their draft token there, so every row
  still gets its exact greedy continuation;
- cache rollback is O(1) bookkeeping: rewind the scalar ``cache_index``
  and zero ``cached_segment_ids`` beyond it — never-valid slots are
  masked by segment 0 exactly like never-written ones
  (tpufw.models.llama Attention._cached_attention), and the next
  iteration's write overwrites them.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.infer.generate import (
    _model_apply,
    pad_prompts,
    prefill_cache,
)
from tpufw.infer.sampling import SamplingConfig, sample_token, transform_logits

# Trace-time counters for the CHUNKED slot-pool speculation below —
# same contract as tpufw.infer.slots.TRACE_COUNTS: bumped once per
# (re)trace inside the jitted bodies, so tests can pin "varying accept
# counts and page churn never recompile the verify program".
TRACE_COUNTS: Dict[str, int] = {"spec_verify": 0, "spec_draft_verify": 0}


def _rollback(cache: dict, new_cursor: jax.Array) -> dict:
    """Rewind a decode cache to ``new_cursor`` valid entries: slots at
    or beyond the cursor become segment-0 (masked) and the next write
    lands on them. Keys/values stay — masking, not control flow."""

    def fix(path, leaf):
        name = getattr(path[-1], "key", None)
        if name == "cache_index":
            # nn.scan stacks per-layer cursors into [L]; keep the shape.
            return jnp.full(leaf.shape, new_cursor, leaf.dtype)
        if name == "cached_segment_ids":
            # [*stack, B, S]: mask the trailing slot axis.
            live = jnp.arange(leaf.shape[-1]) < new_cursor
            return jnp.where(live, leaf, 0)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _cursor(cache: dict) -> jax.Array:
    """The shared cache_index of a decode cache pytree as a scalar
    (nn.scan stacks identical per-layer cursors into [L])."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if getattr(path[-1], "key", None) == "cache_index":
            return jnp.max(leaf)
    raise ValueError("no cache_index in cache pytree")


@partial(
    jax.jit,
    static_argnames=(
        "draft_model", "model", "k", "max_new_tokens", "pad_id", "eos_id",
        "sampling", "prefill_chunk_size",
    ),
)
def speculative_generate(
    draft_model,
    draft_params,
    model,
    params,
    prompt_tokens: jax.Array,
    pad_lens: jax.Array,
    *,
    max_new_tokens: int,
    k: int = 4,
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    live_rows: Optional[jax.Array] = None,
    sampling: SamplingConfig = SamplingConfig(),
    rng: Optional[jax.Array] = None,
    prefill_chunk_size: Optional[int] = None,
) -> tuple[jax.Array, dict]:
    """Decode ``model`` with ``draft_model`` speculation.

    Same contract as ``tpufw.infer.generate`` (left-padded prompts,
    [B, max_new_tokens] out, eos rows freeze to pad) plus a stats dict
    {"iterations", "emitted"} — mean tokens/iteration is the speedup
    diagnostic (k+1 max). Both models must share the tokenizer/vocab.
    With the default greedy ``sampling`` the output is exactly
    ``model``'s greedy continuation regardless of draft quality (only
    speed varies); with ``sampling.temperature > 0`` (``rng`` required)
    each token is rejection-resampled to the target's post-transform
    distribution — see the module docstring for the scheme.

    ``live_rows`` ([B] bool): rows whose acceptance should count toward
    the batch-min. Serving passes False for its shape-bucketing filler
    rows — otherwise a degenerate filler prompt drags every tick's
    acceptance toward zero and the real rows pay the draft overhead for
    ~1 token/iteration. Dead rows' outputs are NOT guaranteed to be
    their greedy continuation (draft tokens past their own match point
    go unvalidated) — the caller must discard them, which is exactly
    what serving's filler-row slicing does.
    """
    b, p = prompt_tokens.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    stochastic = sampling.temperature != 0.0
    if stochastic and rng is None:
        raise ValueError(
            "sampling.temperature > 0 requires an rng key for the "
            "rejection-resample draws"
        )
    track_seen = (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    )
    for m, who in ((model, "model"), (draft_model, "draft_model")):
        max_seq = getattr(getattr(m, "cfg", None), "max_seq_len", None)
        # The verify block may overrun the accepted stream by up to k
        # slots before rollback, so budget for it.
        if max_seq is not None and p + max_new_tokens + k > max_seq:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) + "
                f"k ({k}) exceeds {who}'s KV cache "
                f"(max_seq_len={max_seq})"
            )

    seg = (jnp.arange(p)[None, :] >= pad_lens[:, None]).astype(jnp.int32)
    positions = jnp.maximum(jnp.arange(p)[None, :] - pad_lens[:, None], 0)

    def apply(m, prm, cache, tokens, pos, sg):
        out, vars_ = m.apply(
            {"params": prm, **cache},
            tokens,
            positions=pos,
            segment_ids=sg,
            mutable=["cache"],
        )
        logits = out[0] if isinstance(out, tuple) else out
        return logits, {"cache": vars_["cache"]}

    # Prefill both models over the (padded) prompt — chunked under
    # prefill_chunk_size (the long-prompt lever, shared with generate).
    t_logits, t_cache = prefill_cache(
        partial(apply, model, params), prompt_tokens, positions, seg,
        prefill_chunk_size,
    )
    _, d_cache = prefill_cache(
        partial(apply, draft_model, draft_params), prompt_tokens,
        positions, seg, prefill_chunk_size,
    )
    # Repetition-penalty seen mask: prompt tokens (padding excluded via
    # seg) — the exact construction generate() uses, so the two loops'
    # transformed distributions match position for position.
    seen0 = None
    if track_seen:
        vocab = t_logits.shape[-1]
        real = seg > 0
        seen0 = (
            jnp.zeros((b, vocab), bool)
            .at[jnp.arange(b)[:, None], prompt_tokens]
            .max(real)
        )
    all_keys = None
    if stochastic:
        # Emission index n consumes the key generate() would use for
        # that index — same split order (first = split(rng)[1], step i
        # = split(split(rng)[0], ...)[i-1]) and, crucially, the SAME
        # split count: split(rng, n)[i] is not stable across n on
        # every jax version, so the shared indices must come from the
        # exact split generate() performs. The k overrun-slack keys
        # cover emission indices >= max_new_tokens, whose draws are
        # sliced off at return — any deterministic stream works there.
        next_rng, first_key = jax.random.split(rng)
        step_keys = jax.random.split(next_rng, max(max_new_tokens - 1, 1))
        # tpulint: disable=TPU003 — fold_in(next_rng, 7) deliberately
        # derives the overrun stream from the already-split parent: the
        # shared prefix must replay generate()'s exact splits (comment
        # above), and the fold_in constant keeps the slack keys disjoint.
        overrun_keys = jax.random.split(jax.random.fold_in(next_rng, 7), k)
        all_keys = jnp.concatenate(
            [first_key[None], step_keys, overrun_keys]
        )
        first = sample_token(
            t_logits[:, -1, :], sampling, first_key, seen0
        )
    else:
        # transform_logits is an identity (up to f32 cast) for greedy
        # without a penalty; with one it applies the seen-mask rule
        # before the argmax, exactly like sample_token at temp 0.
        first = jnp.argmax(
            transform_logits(t_logits[:, -1, :], sampling, seen0),
            axis=-1,
        ).astype(jnp.int32)
    if track_seen:
        seen0 = seen0.at[jnp.arange(b), first].set(True)
    done0 = (
        jnp.zeros((b,), bool) if eos_id is None else first == eos_id
    )

    # Output buffer with k+1 slack: a block write near the end may
    # overrun max_new_tokens; the tail is sliced off at return.
    buf = jnp.full((b, max_new_tokens + k + 1), pad_id, jnp.int32)
    buf = buf.at[:, 0].set(first)  # the eos token itself is emitted
    pos0 = p - pad_lens  # `first`'s RoPE position when fed back, per row

    ones = jnp.ones((b, 1), jnp.int32)

    def draft_propose(d_cache, prev, pos, keys_blk, seen):
        """k proposals + one filler step so the draft cache holds every
        proposed token (the a == k acceptance case needs d_k cached).
        Stochastic proposals draw from the TRANSFORMED draft
        distribution with the raw per-emission-index key (the coupling
        that makes draft == target bit-match ``generate``); the
        distributions are returned for the acceptance ratio test.
        With a repetition penalty the seen mask advances over the
        draft's OWN proposals — its proposal distribution q_j is
        conditioned on the same prefix the target's p_j will be."""
        toks, qs = [], []
        tok = prev
        for i in range(k + 1):
            logits, d_cache = apply(
                draft_model, draft_params, d_cache,
                tok[:, None], (pos + i)[:, None], ones,
            )
            if i < k:
                if stochastic:
                    q_i = transform_logits(
                        logits[:, -1, :], sampling, seen
                    )
                    tok = jax.random.categorical(
                        keys_blk[i], q_i, axis=-1
                    ).astype(jnp.int32)
                    qs.append(q_i)
                elif track_seen:
                    tok = jnp.argmax(
                        transform_logits(
                            logits[:, -1, :], sampling, seen
                        ),
                        axis=-1,
                    ).astype(jnp.int32)
                else:
                    tok = jnp.argmax(
                        logits[:, -1, :], axis=-1
                    ).astype(jnp.int32)
                if track_seen:
                    seen = seen.at[jnp.arange(b), tok].set(True)
                toks.append(tok)
        q_trans = jnp.stack(qs, axis=1) if stochastic else None
        return jnp.stack(toks, axis=1), q_trans, d_cache  # [B, k]

    def body(carry):
        t_cache, d_cache, prev, pos, done, n, buf, iters, seen = carry
        t_cur0 = _cursor(t_cache)
        d_cur0 = _cursor(d_cache)
        keys_blk = (
            jax.lax.dynamic_slice_in_dim(all_keys, n, k + 1)
            if stochastic
            else None
        )
        drafts, q_trans, d_cache = draft_propose(
            d_cache, prev, pos, keys_blk, seen
        )

        # One target pass scores prev + all k drafts: logits[:, i] is
        # the target's next-token distribution after input i.
        verify_in = jnp.concatenate([prev[:, None], drafts], axis=1)
        verify_pos = pos[:, None] + jnp.arange(k + 1)[None, :]
        t_logits, t_cache = apply(
            model, params, t_cache, verify_in, verify_pos,
            jnp.ones((b, k + 1), jnp.int32),
        )

        def transform_positions(logits):
            """Per-position transformed target distributions. Without a
            penalty one vectorized transform covers all k+1 positions;
            with one, position j's mask is seen ∪ drafts[:, :j] —
            built cumulatively over the STATIC k (k+1 [B, V] transforms
            instead of 1; k is small and this is the exactness
            requirement: each position's distribution must equal the
            one generate() would sample at that emission index)."""
            if not track_seen:
                return transform_logits(logits, sampling)
            outs, s = [], seen
            for j in range(k + 1):
                outs.append(
                    transform_logits(logits[:, j], sampling, s)
                )
                if j < k:
                    s = s.at[jnp.arange(b), drafts[:, j]].set(True)
            return jnp.stack(outs, axis=1)

        if stochastic:
            # Rejection test on the post-transform distributions:
            # accept x_j iff u_j < p_j(x_j)/q_j(x_j).
            p_trans = transform_positions(t_logits)  # [B,k+1,V]
            logp = jax.nn.log_softmax(p_trans, axis=-1)
            logq = jax.nn.log_softmax(q_trans, axis=-1)
            lp = jnp.take_along_axis(
                logp[:, :k], drafts[..., None], -1
            )[..., 0]
            lq = jnp.take_along_axis(logq, drafts[..., None], -1)[..., 0]
            us = jnp.stack(
                [
                    jax.random.uniform(
                        jax.random.fold_in(keys_blk[j], 1), (b,)
                    )
                    for j in range(k)
                ],
                axis=1,
            )  # [B, k]
            match = us < jnp.exp(lp - lq)
        else:
            greedy = jnp.argmax(
                transform_positions(t_logits), axis=-1
            ).astype(jnp.int32)  # [B, k+1]
            match = drafts == greedy[:, :k]  # [B, k]

        # Per-row longest accepted prefix, then the batch-uniform min
        # (one scalar cache cursor). Rows that matched further lose
        # nothing: their col-a token is their own ACCEPTED draft.
        row_accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), 1), 1)
        # Rows whose output no longer matters must not throttle the
        # batch min: filler rows (live_rows) never did, and eos-DONE
        # rows' post-eos continuations diverge target-vs-draft forever
        # (their emissions are frozen to pad_id regardless), so without
        # this mask one finished row pins every live row to ~1
        # token/iteration.
        row_accept = jnp.where(done, k, row_accept)
        if live_rows is not None:
            row_accept = jnp.where(live_rows, row_accept, k)
        a = jnp.min(row_accept)  # scalar in [0, k]

        cols = jnp.arange(k + 1)[None, :]
        drafts_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
        if stochastic:
            # Col-a token per row: rows that accepted past a keep their
            # own accepted draft x_a; rows rejected AT a draw from the
            # residual norm(max(p_a - q_a, 0)). When a == k (everyone
            # accepted everything) the where() below bypasses the
            # residual entirely and selects logp_a — the bonus draw
            # straight from the target's p_k — and the RAW
            # index key is used there so it matches generate()'s
            # categorical for that emission index bit-for-bit; the
            # a < k resample folds the key (the raw one was consumed by
            # the draft proposal, and reusing its gumbel noise would
            # correlate the resample with the rejection event).
            # Only the col-a slice is ever drawn from: index FIRST,
            # softmax one [B, V] row (not k+1 of them per iteration —
            # V is the vocab in serving). p_a rides the existing logp.
            # The a == k clamp feeds a real-but-irrelevant q row to the
            # residual branch; the where() below picks logp_a there.
            logp_a = jax.lax.dynamic_index_in_dim(
                logp, a, axis=1, keepdims=False
            )
            p_a = jnp.exp(logp_a)
            q_a = jax.nn.softmax(
                jax.lax.dynamic_index_in_dim(
                    q_trans, jnp.minimum(a, k - 1), axis=1,
                    keepdims=False,
                ),
                axis=-1,
            )
            alt_logits = jnp.where(
                a == k, logp_a, jnp.log(jnp.maximum(p_a - q_a, 0.0))
            )
            key_a = jax.lax.dynamic_index_in_dim(
                keys_blk, a, keepdims=False
            )
            key_used = jax.lax.cond(
                a == k,
                lambda: key_a,
                lambda: jax.random.fold_in(key_a, 2),
            )
            tok_alt = jax.random.categorical(
                key_used, alt_logits, axis=-1
            ).astype(jnp.int32)
            x_a = jax.lax.dynamic_index_in_dim(
                drafts_pad, a, axis=1, keepdims=False
            )
            col_a_tok = jnp.where(row_accept > a, x_a, tok_alt)  # [B]
            block = jnp.where(cols < a, drafts_pad, col_a_tok[:, None])
        else:
            # Emitted block: drafts[0..a-1] then the bonus greedy[a].
            greedy_a = jnp.take_along_axis(
                greedy, jnp.broadcast_to(a[None, None], (b, 1)), 1
            )
            block = jnp.where(cols < a, drafts_pad, greedy_a)
        # [B, k+1]; cols > a are dont-cares (masked below)
        n_block = jnp.minimum(a + 1, max_new_tokens - n)

        # EOS + emission masking: freeze rows after their eos, blank
        # columns beyond this block's length.
        live_col = cols < n_block
        if eos_id is None:
            done_before = jnp.broadcast_to(done[:, None], (b, k + 1))
            new_done = done
        else:
            hits = (block == eos_id) & live_col
            ihits = hits.astype(jnp.int32)
            # done before col j = done at entry, or an eos hit in a
            # STRICTLY earlier column (the eos itself is emitted).
            done_before = done[:, None] | (
                (jnp.cumsum(ihits, axis=1) - ihits) > 0
            )
            new_done = done | jnp.any(hits, axis=1)
        emitted = jnp.where(
            live_col & ~done_before, block, pad_id
        ).astype(jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, emitted, (0, n))

        # Rollback: target verified k+1 inputs but only prev + a drafts
        # are part of the stream; draft processed prev + k drafts, keep
        # prev + a. (The next iteration re-feeds the bonus token to
        # both.)
        t_cache = _rollback(t_cache, t_cur0 + a + 1)
        d_cache = _rollback(d_cache, d_cur0 + a + 1)

        # Next input token = the bonus (block col a, traced index).
        nxt = jax.lax.dynamic_index_in_dim(
            block, a, axis=1, keepdims=False
        )
        if track_seen:
            # Mark this block's emissions (cols < n_block) — the same
            # tokens generate() would have marked one step at a time.
            # Done rows mark their (unvalidated) block values; their
            # outputs are pad-frozen, so the divergence is unobservable.
            seen = seen.at[jnp.arange(b)[:, None], block].max(
                jnp.broadcast_to(live_col, (b, k + 1))
            )
        return (
            t_cache, d_cache, nxt, pos + a + 1, new_done,
            n + n_block, buf, iters + 1, seen,
        )

    def cond(carry):
        return carry[5] < max_new_tokens  # carry[5] = tokens emitted

    if max_new_tokens == 1:
        return buf[:, :1], {
            "iterations": jnp.zeros((), jnp.int32),
            "emitted": jnp.ones((), jnp.int32),
        }

    init = (
        t_cache, d_cache, first, pos0, done0,
        jnp.asarray(1, jnp.int32), buf, jnp.asarray(0, jnp.int32),
        # The seen mask rides the carry (placeholder scalar when the
        # penalty is off, so the loop signature stays uniform).
        seen0 if track_seen else jnp.zeros((), bool),
    )
    *_, n_final, buf, iters, _seen = jax.lax.while_loop(cond, body, init)
    return buf[:, :max_new_tokens], {
        "iterations": iters,
        "emitted": jnp.minimum(n_final, max_new_tokens),
    }


def speculative_generate_text(
    draft_model,
    draft_params,
    model,
    params,
    prompts: Sequence[Sequence[int]],
    *,
    max_new_tokens: int,
    k: int = 4,
    pad_id: int = 0,
    eos_id: Optional[int] = None,
    live_rows: Optional[Sequence[bool]] = None,
    sampling: SamplingConfig = SamplingConfig(),
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    prefill_chunk_size: Optional[int] = None,
) -> tuple[list[list[int]], dict]:
    """Ragged-python convenience wrapper (mirrors ``generate_text``,
    including its ``seed`` knob; an explicit ``rng`` wins over seed).
    Returns (outputs, stats) with stats as plain ints."""
    if rng is None and sampling.temperature != 0.0:
        rng = jax.random.key(seed)
    tokens, pads = pad_prompts(prompts, pad_id)
    out, stats = speculative_generate(
        draft_model,
        draft_params,
        model,
        params,
        jnp.asarray(tokens),
        jnp.asarray(pads),
        max_new_tokens=max_new_tokens,
        k=k,
        pad_id=pad_id,
        eos_id=eos_id,
        live_rows=(
            None if live_rows is None else jnp.asarray(live_rows, bool)
        ),
        sampling=sampling,
        rng=rng,
        prefill_chunk_size=prefill_chunk_size,
    )
    result = []
    for row in np.asarray(out):
        toks = row.tolist()
        if eos_id is not None and eos_id in toks:
            toks = toks[: toks.index(eos_id) + 1]
        result.append(toks)
    return result, {k_: int(v) for k_, v in stats.items()}


# ---------------------------------------------------------------------------
# Chunked slot-pool speculation
# ---------------------------------------------------------------------------
# Everything below makes speculation a first-class citizen of the
# tpufw.infer.slots / tpufw.infer.pages slot pool, replacing the
# whole-batch tick path above for continuous-batching serving:
#
# - ONE verify program per (pool, k): draft k tokens, feed the
#   [token, p_1..p_k] block through the target in a single t=k+1 pass
#   (the models' paged/contiguous decode branches scatter the block
#   then gather it back, so intra-block causality is the same
#   slot-ordered mask), and fold PER-SLOT acceptance into the program
#   as data — accept counts become dynamic cursor advances under the
#   existing done/remaining masks. Occupancy, page tables, accept
#   counts: all DATA, never shapes, so page churn and varying accept
#   counts never retrace (TRACE_COUNTS-pinned, like decode_steps).
# - Rollback is per-slot cursor rewind ONLY: stale segment-1 entries
#   beyond the rewound cursor sit at slots > any future query slot
#   until overwritten in slot order, so the causal mask already hides
#   them (no segment zeroing — that would be a [S, cache_len] write
#   per pass for bookkeeping the mask does for free).
# - Greedy (temperature 0) emissions are argmax of the same float32
#   logits decode_steps takes, so spec-on-slots is BIT-EQUAL to plain
#   decode_steps regardless of accept counts. Stochastic uses per-slot
#   rejection-resampling (distributionally exact, not bit-equal).
# - Self-drafting (ngram_propose) needs no draft model: proposals are
#   host-side prompt-lookup, q is a one-hot, and the accept test
#   degrades to u < p(x_j).
#
# Callers with a repetition penalty are rejected: the penalty makes
# each position's distribution depend on acceptance of every previous
# one, which breaks the one-pass verify factorization. Those pools
# stay on plain chunked decode.


def _pool_cursor(cache: dict, n_slots: int) -> jax.Array:
    """Per-slot cursor vector [S] from a slot-pool cache (any
    cache_index leaf: [S] or nn.scan-stacked [L, S] — rows identical
    by construction)."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(cache):
        if getattr(path[-1], "key", None) == "cache_index":
            return leaf.reshape(-1, n_slots)[0]
    raise ValueError("no cache_index in cache pytree")


def _set_pool_cursor(cache: dict, new: jax.Array) -> dict:
    """Write per-slot cursors ``new`` [S] into every cache_index leaf
    (broadcast over the stacked layer axis when present). Cursor-only:
    see the module comment on mask-covered stale entries."""

    def fix(path, leaf):
        if getattr(path[-1], "key", None) == "cache_index":
            return jnp.broadcast_to(new.astype(leaf.dtype), leaf.shape)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _spec_advance(
    logits, proposals, q_trans, key, token, pos, done, remaining,
    *, sampling, pad_id, eos_id,
):
    """Shared verify tail: target logits [S, k+1, V] for the block
    [token, p_1..p_k] -> per-slot emissions + advanced slot state.

    Emission j is the successor of block position j (so col 0 is the
    token after ``token``, col k the bonus after a full accept). The
    valid mask composes acceptance (col <= accept), the per-slot
    budget, first-EOS-inclusive truncation, and entry done — the same
    masking discipline as _decode_steps_jit, vectorized over the
    block. ``q_trans`` is the draft's transformed logits [S, k, V], or
    None for deterministic proposals (greedy and self-draft: q is a
    one-hot at the proposal).

    Returns (out [S, k+1] pad-masked, n_emit [S], accept [S], token,
    pos, done, remaining).
    """
    s, kp1 = logits.shape[:2]
    k = kp1 - 1
    cols = jnp.arange(kp1)[None, :]
    p_trans = transform_logits(logits, sampling)
    if sampling.temperature == 0.0:
        # Greedy: the target's choice at every block position in one
        # argmax — acceptance only decides how MANY columns are real.
        block = jnp.argmax(p_trans, axis=-1).astype(jnp.int32)
        match = proposals == block[:, :k]
        accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), 1), 1)
    else:
        logp = jax.nn.log_softmax(p_trans, axis=-1)
        lp = jnp.take_along_axis(
            logp[:, :k], proposals[..., None], axis=-1
        )[..., 0]
        if q_trans is None:
            lq = jnp.zeros_like(lp)  # one-hot q: accept iff u < p(x_j)
        else:
            lq = jnp.take_along_axis(
                jax.nn.log_softmax(q_trans, axis=-1),
                proposals[..., None], axis=-1,
            )[..., 0]
        us = jax.random.uniform(jax.random.fold_in(key, 1), (s, k))
        match = jnp.log(us) < (lp - lq)
        accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), 1), 1)
        # Column `accept` resamples: from p on a full accept, else from
        # the residual norm(max(p - q, 0)) at the first rejection (for
        # one-hot q the residual is p with the proposal masked out).
        logp_a = jnp.take_along_axis(
            logp, accept[:, None, None], axis=1
        )[:, 0]
        if q_trans is None:
            x_a = jnp.take_along_axis(
                proposals, jnp.minimum(accept, k - 1)[:, None], axis=1
            )[:, 0]
            residual = logp_a.at[jnp.arange(s), x_a].set(-1e30)
        else:
            q_a = jax.nn.softmax(
                jnp.take_along_axis(
                    q_trans, jnp.minimum(accept, k - 1)[:, None, None],
                    axis=1,
                )[:, 0],
                axis=-1,
            )
            residual = jnp.log(
                jnp.maximum(jnp.exp(logp_a) - q_a, 1e-30)
            )
        alt_logits = jnp.where((accept == k)[:, None], logp_a, residual)
        alt = jax.random.categorical(
            # tpulint: disable=TPU003 — fold_in(key, 2) is a distinct
            # stream from the fold_in(key, 1) acceptance uniforms.
            jax.random.fold_in(key, 2), alt_logits, axis=-1
        ).astype(jnp.int32)
        props_pad = jnp.concatenate(
            [proposals, jnp.zeros((s, 1), jnp.int32)], axis=1
        )
        block = jnp.where(cols < accept[:, None], props_pad, alt[:, None])
    valid = (cols <= accept[:, None]) & (cols < remaining[:, None])
    hits = None
    if eos_id is not None:
        hits = (block == eos_id) & valid
        ih = hits.astype(jnp.int32)
        # Inclusive first-EOS truncation: the EOS itself is delivered,
        # everything after it in the block is masked.
        valid = valid & ((jnp.cumsum(ih, axis=1) - ih) == 0)
    emit = valid & ~done[:, None]
    out = jnp.where(emit, block, pad_id).astype(jnp.int32)
    n_emit = emit.sum(axis=1).astype(jnp.int32)
    accept = jnp.where(done, 0, accept).astype(jnp.int32)
    remaining = jnp.where(done, remaining, remaining - n_emit)
    newly = remaining <= 0
    if eos_id is not None:
        newly = newly | jnp.any(hits & emit, axis=1)
    # Next feed = last emitted token; a live row always emits >= 1
    # (col 0 is acceptance-free and budget >= 1 while live).
    last = jnp.maximum(n_emit - 1, 0)
    nxt = jnp.take_along_axis(block, last[:, None], axis=1)[:, 0]
    token = jnp.where(done, pad_id, nxt).astype(jnp.int32)
    pos = jnp.where(done, pos, pos + n_emit)
    return out, n_emit, accept, token, pos, done | newly, remaining


@partial(
    jax.jit,
    static_argnames=("model", "sampling", "pad_id", "eos_id"),
    donate_argnames=("cache", "token", "pos", "done", "remaining"),
)
def _spec_verify_jit(
    model, params, cache, token, pos, done, remaining, proposals, key,
    *, sampling, pad_id, eos_id,
):
    """Verify host-supplied proposals [S, k] in ONE t=k+1 target pass
    and advance the pool. Self-drafting path (n-gram / prompt-lookup):
    q is a one-hot at the proposal."""
    TRACE_COUNTS["spec_verify"] += 1
    apply = _model_apply(model, params)
    s, k = proposals.shape
    cur0 = _pool_cursor(cache, s)
    block_in = jnp.concatenate([token[:, None], proposals], axis=1)
    positions = pos[:, None] + jnp.arange(k + 1)[None, :]
    logits, cache = apply(
        cache, block_in, positions, jnp.ones((s, k + 1), jnp.int32)
    )
    out, n_emit, accept, token, pos, done_new, remaining = _spec_advance(
        logits, proposals, None, key, token, pos, done, remaining,
        sampling=sampling, pad_id=pad_id, eos_id=eos_id,
    )
    # Rollback = cursor rewind (done rows pinned at entry cursor).
    cache = _set_pool_cursor(cache, jnp.where(done, cur0, cur0 + n_emit))
    return cache, token, pos, done_new, remaining, out, n_emit, accept


@partial(
    jax.jit,
    static_argnames=(
        "model", "draft_model", "k", "sampling", "pad_id", "eos_id",
    ),
    donate_argnames=(
        "cache", "d_cache", "token", "pos", "done", "remaining",
    ),
)
def _spec_draft_verify_jit(
    model, params, draft_model, draft_params, cache, d_cache,
    token, pos, done, remaining, key,
    *, k, sampling, pad_id, eos_id,
):
    """Fused draft+verify: k single-token draft passes propose, one
    t=k+1 target pass verifies, and BOTH pools' cursors advance in
    lockstep by the per-slot emit count. The draft cache ingests
    [token, p_1..p_{k-1}] — exactly the entries that are correct for
    any accepted prefix — so rewinding its cursor by the same n_emit
    keeps it one-entry behind the target (the next pass feeds the
    corrected last token to both), and no draft entry ever needs
    patching."""
    TRACE_COUNTS["spec_draft_verify"] += 1
    apply = _model_apply(model, params)
    d_apply = _model_apply(draft_model, draft_params)
    s = token.shape[0]
    cur0 = _pool_cursor(cache, s)
    d_cur0 = _pool_cursor(d_cache, s)
    stochastic = sampling.temperature != 0.0
    ones = jnp.ones((s, 1), jnp.int32)
    draft_keys = (
        jax.random.split(jax.random.fold_in(key, 3), k)
        if stochastic else None
    )
    toks, qs = [], []
    tok = token
    for i in range(k):
        d_logits, d_cache = d_apply(
            d_cache, tok[:, None], (pos + i)[:, None], ones
        )
        if stochastic:
            q_i = transform_logits(d_logits[:, -1, :], sampling)
            tok = jax.random.categorical(
                draft_keys[i], q_i, axis=-1
            ).astype(jnp.int32)
            qs.append(q_i)
        else:
            tok = jnp.argmax(
                d_logits[:, -1, :].astype(jnp.float32), axis=-1
            ).astype(jnp.int32)
        toks.append(tok)
    proposals = jnp.stack(toks, axis=1)  # [S, k]
    q_trans = jnp.stack(qs, axis=1) if stochastic else None
    block_in = jnp.concatenate([token[:, None], proposals], axis=1)
    positions = pos[:, None] + jnp.arange(k + 1)[None, :]
    logits, cache = apply(
        cache, block_in, positions, jnp.ones((s, k + 1), jnp.int32)
    )
    # tpulint: disable=TPU003 — _spec_advance folds key with constants
    # 1/2, disjoint from the fold_in(key, 3) draft split above.
    out, n_emit, accept, token, pos, done_new, remaining = _spec_advance(
        logits, proposals, q_trans, key, token, pos, done, remaining,
        sampling=sampling, pad_id=pad_id, eos_id=eos_id,
    )
    cache = _set_pool_cursor(cache, jnp.where(done, cur0, cur0 + n_emit))
    d_cache = _set_pool_cursor(
        d_cache, jnp.where(done, d_cur0, d_cur0 + n_emit)
    )
    return (
        cache, d_cache, token, pos, done_new, remaining, out, n_emit,
        accept,
    )


def _reject_penalty(sampling: SamplingConfig) -> None:
    if (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    ):
        raise ValueError(
            "speculative slot-pool decode does not compose with a "
            "repetition penalty (acceptance at position j would change "
            "the penalized distribution at j+1, breaking the one-pass "
            "verify) — use plain decode_steps for penalty pools"
        )


def spec_verify_steps(pool, proposals, key):
    """One self-draft speculative pass over ``pool`` (a SlotPool /
    PagedSlotPool): verify host proposals [S, k], advance the pool,
    return (out [S, k+1], n_emit [S], accept [S]) as device arrays."""
    _reject_penalty(pool.sampling)
    proposals = jnp.asarray(proposals, jnp.int32)
    perf = getattr(pool, "perf", None)
    if perf is not None:
        perf.observe_jit(
            f"serve_spec_k{proposals.shape[1]}",
            _spec_verify_jit,
            (
                pool.model, pool.params, pool.cache, pool.token,
                pool.pos, pool.done, pool.remaining, proposals, key,
            ),
            kwargs=dict(
                sampling=pool.sampling, pad_id=pool.pad_id,
                eos_id=pool.eos_id,
            ),
        )
    (
        pool.cache, pool.token, pool.pos, pool.done, pool.remaining,
        out, n_emit, accept,
    ) = _spec_verify_jit(
        pool.model, pool.params, pool.cache, pool.token, pool.pos,
        pool.done, pool.remaining, proposals, key,
        sampling=pool.sampling, pad_id=pool.pad_id, eos_id=pool.eos_id,
    )
    return out, n_emit, accept


def spec_draft_steps(pool, draft_pool, key, k: int):
    """One fused draft+verify pass: ``draft_pool`` (same n_slots,
    cursors in lockstep with ``pool``) proposes k tokens, the target
    verifies. Returns (out [S, k+1], n_emit [S], accept [S])."""
    _reject_penalty(pool.sampling)
    perf = getattr(pool, "perf", None)
    if perf is not None:
        perf.observe_jit(
            f"serve_spec_draft_k{k}",
            _spec_draft_verify_jit,
            (
                pool.model, pool.params, draft_pool.model,
                draft_pool.params, pool.cache, draft_pool.cache,
                pool.token, pool.pos, pool.done, pool.remaining, key,
            ),
            kwargs=dict(
                k=k, sampling=pool.sampling, pad_id=pool.pad_id,
                eos_id=pool.eos_id,
            ),
        )
    (
        pool.cache, draft_pool.cache, pool.token, pool.pos, pool.done,
        pool.remaining, out, n_emit, accept,
    ) = _spec_draft_verify_jit(
        pool.model, pool.params, draft_pool.model, draft_pool.params,
        pool.cache, draft_pool.cache, pool.token, pool.pos, pool.done,
        pool.remaining, key,
        k=k, sampling=pool.sampling, pad_id=pool.pad_id,
        eos_id=pool.eos_id,
    )
    return out, n_emit, accept


def ngram_propose(
    history: Sequence[int], k: int, *, max_n: int = 3, pad_id: int = 0
) -> List[int]:
    """Prompt-lookup self-drafting (host-side, O(len * n) per call):
    match the longest trailing n-gram (n = max_n..1) of ``history``
    against its earlier occurrences and propose the k tokens that
    followed the MOST RECENT match. A cold miss returns pad fill — the
    verify pass then accepts 0 columns and the pass degrades to plain
    single-token yield, never to a wrong emission."""
    h = list(history)
    length = len(h)
    for n in range(min(max_n, length - 1), 0, -1):
        tail = h[length - n:]
        for i in range(length - n - 1, -1, -1):
            if h[i:i + n] == tail:
                cont = h[i + n:i + n + k]
                if cont:
                    return (cont + [pad_id] * (k - len(cont)))[:k]
    return [pad_id] * k


class AcceptEMA:
    """Per-slot EMA of the accepted-draft fraction (accept / k) — the
    host-side signal behind acceptance-aware scheduling. Slots start
    OPTIMISTIC (EMA 1.0 on occupy) so every request gets at least one
    speculative pass; the pool runs spec while the mean EMA over
    active slots clears ``min_accept``, and otherwise falls back to
    plain chunked decode, re-probing with one spec pass every
    ``probe_every`` fallback chunks (0 disables probing — draft-model
    pools set this, because plain chunks leave the draft KV stale and
    a probe would measure the stale-context draft)."""

    def __init__(
        self,
        n_slots: int,
        *,
        alpha: float = 0.25,
        min_accept: float = 0.25,
        probe_every: int = 8,
    ) -> None:
        self.alpha = float(alpha)
        self.min_accept = float(min_accept)
        self.probe_every = int(probe_every)
        self.ema: List[Optional[float]] = [None] * n_slots
        self._since_spec = 0

    def occupy(self, slot: int) -> None:
        self.ema[slot] = 1.0

    def vacate(self, slot: int) -> None:
        self.ema[slot] = None

    def update(self, slot: int, frac: float) -> None:
        prev = self.ema[slot]
        if prev is None:
            prev = 1.0
        self.ema[slot] = (1.0 - self.alpha) * prev + self.alpha * float(
            frac
        )

    def fallback_slots(self, slots: Sequence[int]) -> int:
        """Active slots currently below the acceptance threshold."""
        return sum(
            1
            for s in slots
            if self.ema[s] is not None and self.ema[s] < self.min_accept
        )

    def use_spec(self, slots: Sequence[int]) -> bool:
        vals = [self.ema[s] for s in slots if self.ema[s] is not None]
        if not vals:
            return False
        if sum(vals) / len(vals) >= self.min_accept:
            self._since_spec = 0
            return True
        self._since_spec += 1
        if self.probe_every and self._since_spec >= self.probe_every:
            self._since_spec = 0
            return True
        return False
