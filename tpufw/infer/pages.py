"""Paged KV pool: fixed-size page arena + per-slot page tables.

The contiguous ``SlotPool`` (tpufw.infer.slots) charges every occupied
slot a full ``[cache_len]`` KV row, so HBM — not compute — caps
concurrent rows per chip, and identical prompt prefixes are prefilled
and stored once PER ROW. This module keeps the slot scheduler's whole
zero-recompile contract (occupancy, cursors, and now page-table churn
are all DATA) while storing KV in a global arena of ``kv_pages`` pages
of ``kv_page`` slots each:

- the MODEL owns the arena + table + gather/scatter reads
  (``Attention._paged_cached_attention`` in llama/deepseek — the cache
  leaves just have a different shape, so ``_decode_steps_jit`` is
  reused verbatim);
- this module owns moving rows in and out: ``_paged_insert_jit``
  scatters a B=1 contiguous prefilled row into the slot's pages,
  ``PagedSlotPool.release_slot`` zeroes the table row (stale writes
  from a done-but-stepped row then land in reserved page 0, never in a
  reused page) and returns the pages to the host-side
  ``PageAllocator``;
- prefix sharing rides on top: ``PrefixCache`` (tpufw.infer.prefix)
  maps full-page token chunks to resident pages, ``prefill_shared``
  gathers the shared pages into a fresh row cache and prefills ONLY
  the suffix. Only full pages strictly before the row's first write
  slot are shared, so copy-on-write is structural — divergence lands
  in private pages, never needs a device copy.

Static-shape discipline and retrace budget: ``decode_steps`` stays ONE
program forever. Insert/attach/suffix-prefill programs are keyed by
(prompt-length, shared-page-count) — bounded by the traffic's distinct
prompt shapes, paid at admission (the same place the contiguous path
pays its prefill-bucket programs), never per decode step.

int8 KV (``cfg.kv_quant == "int8"``): arenas are int8 with per-token
fp32 scales stored page-structured ``[kv_pages, kv_page]``. Decode
tokens are quantized inside the model at append; prompt tokens are
quantized HERE at insert (prefill itself runs full-precision through
the contiguous row cache).
"""

from __future__ import annotations

import contextlib
import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.infer.generate import _model_apply, split_prefill_keys
from tpufw.infer.prefix import PrefixCache
from tpufw.infer.sampling import sample_token
from tpufw.infer.slots import SlotPool, _retire_jit, _track_seen
from tpufw.ops.quant import dequantize_kv, quantize_kv

# Trace-time counters, same contract as tpufw.infer.slots.TRACE_COUNTS:
# bumped once per (re)trace so tests can pin the retrace budget.
TRACE_COUNTS: Dict[str, int] = {
    "paged_insert": 0, "clear_table": 0, "prefix_attach": 0,
    "suffix_prefill": 0, "page_export": 0, "page_splice": 0,
    "prefill_chunk": 0, "page_import": 0,
}

#: unstacked rank of each KV arena leaf — (n_pages, page, *feat); the
#: trailing ``rank - 2`` dims are the per-token feature block a single
#: int8 scale covers. Matching row-cache leaves are (1, W, *feat) at
#: the same rank.
_ARENA_RANK = {
    "cached_key": 4, "cached_value": 4,  # llama-family K/V heads
    "cached_ckv": 3, "cached_kpe": 3,    # deepseek MLA latents
}


def _export_rank(name: str) -> Optional[int]:
    """Collapse rank of a leaf that travels in a page bundle (arena KV,
    page-structured scales, segment ids); None for per-slot leaves
    (page_table, cache_index) the importer rebuilds locally."""
    if name in _ARENA_RANK:
        return _ARENA_RANK[name]
    if name.endswith("_scale") or name == "cached_segment_ids":
        return 2
    return None


def _leaf_name(path) -> str:
    last = path[-1]
    return str(getattr(last, "key", last))


def _flatten_with_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = tuple(jax.tree_util.keystr(p) for p, _ in flat)
    names = tuple(_leaf_name(p) for p, _ in flat)
    leaves = [leaf for _, leaf in flat]
    return paths, names, leaves, treedef


def _collapse_arena(leaf, rank):
    """[*stack, n_pages, page, *feat] -> [stacks, n_pages, page, *feat]
    (stacks = nn.scan layer axes collapsed; 1 when unscanned)."""
    return leaf.reshape((-1,) + leaf.shape[leaf.ndim - rank:])


def _collapse_row(row, rank):
    """[*stack, 1, W, *feat] -> [stacks, W, *feat] (B=1 absorbed)."""
    return row.reshape((-1,) + row.shape[row.ndim - rank + 1:])


def paged_pool_cache(model, params, n_slots: int):
    """Zeroed paged cache for ``model`` (cfg.kv_page > 0) at B=n_slots.

    The paged branch creates its per-row cursor/table as [B] vectors
    directly, so — unlike the contiguous ``pool_cache`` — no axis
    probing or trailing-slot-axis surgery is needed: the model's own
    init shapes ARE the pool shapes. Zeros are safe initial state
    (page 0 reserved, segment 0 everywhere)."""

    def init(p):
        toks = jnp.zeros((n_slots, 1), jnp.int32)
        pos = jnp.zeros((n_slots, 1), jnp.int32)
        seg = jnp.ones((n_slots, 1), jnp.int32)
        _, vars_ = model.apply(
            {"params": p}, toks, positions=pos, segment_ids=seg,
            mutable=["cache"],
        )
        return vars_["cache"]

    shapes = jax.eval_shape(init, params)
    tree = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, l.dtype), shapes
    )
    return {"cache": tree}


def _row_zeros_tree(row_model, params):
    """Zeroed B=1 CONTIGUOUS row cache for ``row_model`` (the paged
    model's contiguous twin) — the shape ``prefill_row`` hands back,
    used as the canvas ``prefill_shared`` gathers shared pages into."""

    def init(p):
        toks = jnp.zeros((1, 1), jnp.int32)
        pos = jnp.zeros((1, 1), jnp.int32)
        seg = jnp.ones((1, 1), jnp.int32)
        _, vars_ = row_model.apply(
            {"params": p}, toks, positions=pos, segment_ids=seg,
            mutable=["cache"],
        )
        return vars_["cache"]

    shapes = jax.eval_shape(init, params)
    # Wrapped in the same {"cache": ...} form prefill_row returns, so
    # path alignment against the pool tree lines up leaf-for-leaf.
    return {
        "cache": jax.tree_util.tree_map(
            lambda l: jnp.zeros(l.shape, l.dtype), shapes
        )
    }


class PageAllocator:
    """Host-side free-list + refcounts over the device page arena.

    Page 0 is reserved (the causally-masked junk sink unmapped table
    entries point at) and never enters the free list. A page is free
    iff its row refcount is 0 AND the prefix trie does not hold it."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"kv_pages={n_pages}: need >= 2 (page 0 is reserved)"
            )
        self.n_pages = int(n_pages)
        # LIFO free list: recently-freed pages are re-used first (their
        # arena lines are warm).
        self.free: List[int] = list(range(n_pages - 1, 0, -1))
        self.refs: Dict[int, int] = {}
        self.held: set = set()
        self.freed_total = 0

    @property
    def n_free(self) -> int:
        return len(self.free)

    @property
    def capacity(self) -> int:
        return self.n_pages - 1

    @property
    def in_use(self) -> int:
        return self.capacity - len(self.free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` free pages with refcount 1, or None (all-or-
        nothing — a partial grab would deadlock two part-admitted
        rows)."""
        # resource: acquires pages
        if n > len(self.free):
            return None
        ids = [self.free.pop() for _ in range(n)]
        for i in ids:
            self.refs[i] = 1
        return ids

    def ref(self, ids: Sequence[int]) -> None:
        for i in ids:
            self.refs[i] = self.refs.get(i, 0) + 1

    def release(self, ids: Sequence[int]) -> int:
        """Drop one row reference per id; free those that hit 0 and are
        not trie-held. Returns the number actually freed."""
        # resource: releases pages
        freed = 0
        for i in ids:
            r = self.refs.get(i, 0) - 1
            if r > 0:
                self.refs[i] = r
            else:
                self.refs.pop(i, None)
                if i not in self.held:
                    self.free.append(i)
                    freed += 1
        self.freed_total += freed
        return freed

    def hold(self, ids: Sequence[int]) -> None:
        self.held.update(int(i) for i in ids)

    def drop(self, ids: Sequence[int]) -> int:
        """Trie eviction path: drop the hold; free ids no row uses."""
        freed = 0
        for i in ids:
            self.held.discard(i)
            if self.refs.get(i, 0) == 0:
                self.free.append(i)
                freed += 1
        self.freed_total += freed
        return freed


@partial(
    jax.jit,
    static_argnames=("names", "scale_src", "page", "quant"),
    donate_argnames=("leaves", "token", "pos", "done", "remaining", "seen"),
)
def _paged_insert_jit(
    leaves, row_leaves, table_row, slot, start, first, pos0, budget,
    token, pos, done, remaining, seen, row_seen,
    *, names, scale_src, page, quant,
):
    """Scatter a B=1 contiguous prefilled row into slot ``slot``'s
    pages. ``table_row`` [per_row] holds the slot's physical page ids
    (0-padded past the row's need); ``start`` (TRACED — shared vs cold
    never retraces) is the first logical slot this row owns: slots
    below it belong to shared prefix pages and are redirected into
    reserved page 0 (harmless duplicate junk) instead of overwriting
    shared content."""
    TRACE_COUNTS["paged_insert"] += 1
    per_row = table_row.shape[0]
    w = per_row * page
    idx = jnp.arange(w)
    off = idx % page
    phys = jnp.where(idx >= start, table_row[idx // page], 0)

    quantized = {}
    if quant:
        for i, name in enumerate(names):
            if name in _ARENA_RANK:
                rank = _ARENA_RANK[name]
                rr = _collapse_row(row_leaves[i], rank)
                quantized[i] = quantize_kv(rr, n_feat=rank - 2)

    out = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        if name == "page_table":
            out.append(leaf.at[..., slot, :].set(table_row))
        elif name == "cache_index":
            out.append(leaf.at[..., slot].set(row_leaves[i]))
        elif name.endswith("_scale"):
            scales = quantized[scale_src[i]][1]  # [stacks, W] fp32
            a = _collapse_arena(leaf, 2)
            out.append(a.at[:, phys, off].set(scales).reshape(leaf.shape))
        elif name in _ARENA_RANK:
            rank = _ARENA_RANK[name]
            if quant:
                vals = quantized[i][0]
            else:
                vals = _collapse_row(row_leaves[i], rank).astype(leaf.dtype)
            a = _collapse_arena(leaf, rank)
            out.append(a.at[:, phys, off].set(vals).reshape(leaf.shape))
        elif name == "cached_segment_ids":
            vals = _collapse_row(row_leaves[i], 2).astype(leaf.dtype)
            a = _collapse_arena(leaf, 2)
            out.append(a.at[:, phys, off].set(vals).reshape(leaf.shape))
        else:
            raise ValueError(
                f"unknown paged cache leaf {name!r}: the paged insert "
                "must know every leaf's role (an untouched leaf would "
                "leak the previous occupant's state)"
            )
    token = token.at[slot].set(first)
    pos = pos.at[slot].set(pos0)
    done = done.at[slot].set(False)
    remaining = remaining.at[slot].set(budget)
    if seen is not None:
        seen = seen.at[slot].set(row_seen[0])
    return tuple(out), token, pos, done, remaining, seen


@partial(jax.jit, donate_argnames=("tables",))
def _clear_tables_jit(tables, slot):
    """Zero slot ``slot``'s page-table row in every layer: a retired
    row's residual writes (done rows keep stepping under static shapes)
    then land in reserved page 0 instead of a page someone else may
    have been handed."""
    TRACE_COUNTS["clear_table"] += 1
    return tuple(t.at[..., slot, :].set(0) for t in tables)


@partial(
    jax.jit,
    static_argnames=("names", "scale_of", "page", "quant"),
    donate_argnames=("row_leaves",),
)
def _attach_shared_jit(
    row_leaves, pool_leaves, ids, *, names, scale_of, page, quant,
):
    """Gather ``ids``' pages out of the arena into logical slots
    [0, len(ids)*page) of a zeroed B=1 contiguous row cache (dequantized
    in int8 mode — suffix prefill attends full-precision), segment 1,
    cursor = shared length. Programs are keyed by the shared-page
    count. Inputs/outputs ride in POOL leaf order; entries with no row
    counterpart (page_table, scales) pass None through."""
    TRACE_COUNTS["prefix_attach"] += 1
    n = ids.shape[0]
    length = n * page
    out = []
    for i, name in enumerate(names):
        row = row_leaves[i]
        if row is None:
            out.append(None)
        elif name == "cache_index":
            out.append(jnp.full(row.shape, length, row.dtype))
        elif name == "cached_segment_ids":
            rr = _collapse_row(row, 2)
            a = _collapse_arena(pool_leaves[i], 2)
            g = a[:, ids].reshape(a.shape[0], length)
            out.append(
                rr.at[:, :length].set(g.astype(rr.dtype)).reshape(row.shape)
            )
        elif name in _ARENA_RANK:
            rank = _ARENA_RANK[name]
            a = _collapse_arena(pool_leaves[i], rank)
            g = a[:, ids]  # [stacks, n, page, *feat]
            if quant:
                sa = _collapse_arena(pool_leaves[scale_of[i]], 2)
                g = dequantize_kv(g, sa[:, ids], row.dtype)
            g = g.reshape((g.shape[0], length) + g.shape[3:])
            rr = _collapse_row(row, rank)
            out.append(
                rr.at[:, :length].set(g.astype(rr.dtype)).reshape(row.shape)
            )
        else:
            raise ValueError(f"unknown row cache leaf {name!r}")
    return tuple(out)


@partial(jax.jit, static_argnames=("names",))
def _export_pages_jit(leaves, ids, *, names):
    """Gather pages ``ids`` out of every bundle-traveling arena leaf,
    RAW (int8 codes + their scales ship as stored — no dequantize, so
    a splice on the receiving arena is bit-identical storage and the
    wire stays ~4x cheaper in int8 mode). NOT donating: the arena
    stays live — export observes, it never consumes. Programs are
    keyed by the page count, same budget class as prefix attach."""
    TRACE_COUNTS["page_export"] += 1
    out = []
    for name, leaf in zip(names, leaves):
        rank = _export_rank(name)
        if rank is None:
            continue
        a = _collapse_arena(leaf, rank)
        out.append(a[:, ids])  # [stacks, n, page, *feat]
    return tuple(out)


@partial(jax.jit, static_argnames=("names",), donate_argnames=("leaves",))
def _import_pages_jit(leaves, page_arrays, ids, *, names):
    """Scatter spilled pages back into arena pages ``ids`` — the
    donating twin of ``_export_pages_jit`` and exactly the page-payload
    half of ``_splice_pages_jit`` (no table row, no cursors: trie pages
    belong to no slot, rows find them through the prefix match). Raw
    stores both ways means spill -> restore is bit-identical storage.
    Programs are keyed by the page count, same budget class as
    export."""
    TRACE_COUNTS["page_import"] += 1
    k = 0
    out = []
    for name, leaf in zip(names, leaves):
        rank = _export_rank(name)
        if rank is None:
            out.append(leaf)
            continue
        a = _collapse_arena(leaf, rank)
        vals = page_arrays[k].astype(leaf.dtype)
        out.append(a.at[:, ids].set(vals).reshape(leaf.shape))
        k += 1
    return tuple(out)


@partial(
    jax.jit,
    static_argnames=("names",),
    donate_argnames=(
        "leaves", "token", "pos", "done", "remaining", "seen",
    ),
)
def _splice_pages_jit(
    leaves, page_arrays, ids, table_row, slot, cache_idx,
    first, pos0, budget, done0,
    token, pos, done, remaining, seen, row_seen,
    *, names,
):
    """Scatter a migrated bundle's pages into freshly allocated arena
    pages ``ids`` and point slot ``slot``'s table row at them. The
    page-table indirection is what makes migration invisible to the
    math: physical ids differ per replica, but the gather reconstructs
    the same logical row, so greedy decode after a splice is bit-equal
    to the never-migrated run — and the cache shapes are untouched, so
    ``decode_steps`` stays the one program it always was."""
    TRACE_COUNTS["page_splice"] += 1
    k = 0
    out = []
    for name, leaf in zip(names, leaves):
        if name == "page_table":
            out.append(leaf.at[..., slot, :].set(table_row))
            continue
        if name == "cache_index":
            out.append(leaf.at[..., slot].set(cache_idx))
            continue
        rank = _export_rank(name)
        if rank is None:
            raise ValueError(
                f"unknown paged cache leaf {name!r}: the page splice "
                "must know every leaf's role (an untouched leaf would "
                "leak the previous occupant's state)"
            )
        a = _collapse_arena(leaf, rank)
        vals = page_arrays[k].astype(leaf.dtype)
        out.append(a.at[:, ids].set(vals).reshape(leaf.shape))
        k += 1
    token = token.at[slot].set(first)
    pos = pos.at[slot].set(pos0)
    done = done.at[slot].set(done0)
    remaining = remaining.at[slot].set(budget)
    if seen is not None:
        seen = seen.at[slot].set(row_seen)
    return tuple(out), token, pos, done, remaining, seen


@partial(
    jax.jit,
    static_argnames=("model", "sampling", "eos_id"),
    donate_argnames=("cache",),
)
def _suffix_prefill_jit(
    model, params, cache, suffix, prompt_full, start_pos, rng,
    *, sampling, eos_id,
):
    """Prefill ONLY the unshared suffix over an attached row cache and
    sample the first token with ``split_prefill_keys``' first key — the
    exact key a cold ``prefill_row`` of the full prompt would use, so
    shared and cold admissions draw identical sample streams."""
    TRACE_COUNTS["suffix_prefill"] += 1
    b, t = suffix.shape
    seg = jnp.ones((b, t), jnp.int32)
    positions = start_pos + jnp.arange(t)[None, :]
    apply = _model_apply(model, params)
    logits, cache = apply(cache, suffix, positions, seg)
    seen = None
    if _track_seen(sampling):
        # Repetition-penalty presence mask over the FULL prompt (the
        # shared tokens count even though they were never re-run).
        vocab = logits.shape[-1]
        seen = (
            jnp.zeros((b, vocab), bool)
            .at[jnp.arange(b)[:, None], prompt_full]
            .set(True)
        )
    first_rng, _ = split_prefill_keys(rng, 1)
    first = sample_token(logits[:, -1, :], sampling, first_rng, seen)
    if seen is not None:
        seen = seen.at[jnp.arange(b), first].set(True)
    done = jnp.zeros((b,), bool) if eos_id is None else first == eos_id
    return cache, first, done, seen


@partial(
    jax.jit,
    static_argnames=(
        "row_model", "sampling", "eos_id", "paths", "names",
        "scale_src", "page", "quant",
    ),
    donate_argnames=("leaves", "row_cache", "seen_row"),
)
def _prefill_chunk_jit(
    leaves, row_cache, params, tokens, chunk_ids, start, n_real,
    is_final, rng, seen_row,
    *, row_model, sampling, eos_id, paths, names, scale_src, page,
    quant,
):
    """Advance one in-flight chunked prefill by ONE page-aligned chunk:
    run ``tokens`` (right-padded to a whole number of pages) through
    the contiguous row cache at logical offset ``start``, then scatter
    the freshly written window straight into the chunk's arena pages
    ``chunk_ids``. Programs are keyed by (chunk width, quant) — mid
    chunks all share the ``chunk_pages`` program and tails reuse one
    program per page-granular width, so chunk-COUNT variation and page
    churn never retrace (``start``/``n_real``/``is_final``/``rng`` are
    all traced).

    Bit-parity with monolithic prefill holds per query: every apply
    attends the full row cache under the causal + segment mask, padded
    tail slots carry segment 0 (their logits weights underflow to an
    exact 0.0), and the window scatter quantizes per token — identical
    values to a whole-row insert. Sampling runs every chunk (one
    program), but only the final chunk's draw is kept by the host; the
    key is ``split_prefill_keys``' first key, the exact key a cold
    ``prefill_row`` of the full prompt would use.

    The model leaves cursor = start + width after a padded tail; the
    row's cache_index leaves are rewritten to ``start + n_real`` here
    so finalize (``_paged_insert_jit`` reading the row leaf) sees the
    true prompt length."""
    TRACE_COUNTS["prefill_chunk"] += 1
    b, width = tokens.shape
    in_win = jnp.arange(width)
    valid = in_win < n_real
    seg = valid.astype(jnp.int32)[None, :]
    positions = start + in_win[None, :]
    apply = _model_apply(row_model, params)
    logits, row_cache = apply(row_cache, tokens, positions, seg)
    row_paths, row_names, row_leaves, row_treedef = _flatten_with_names(
        row_cache
    )
    row_leaves = [
        jnp.full(l.shape, start + n_real, l.dtype)
        if n == "cache_index" else l
        for n, l in zip(row_names, row_leaves)
    ]
    if seen_row is not None:
        # Prompt tokens enter the presence mask BEFORE the (possibly
        # final) sample, matching _suffix_prefill_jit's ordering.
        seen_row = seen_row.at[0, tokens[0]].max(valid)
    last = jax.lax.dynamic_slice_in_dim(logits, n_real - 1, 1, axis=1)
    first_rng, _ = split_prefill_keys(rng, 1)
    first = sample_token(last[:, 0, :], sampling, first_rng, seen_row)
    if seen_row is not None:
        # Only the kept (final-chunk) draw marks the mask.
        seen_row = seen_row.at[jnp.arange(b), first].max(is_final)
    done0 = (
        jnp.zeros((b,), bool) if eos_id is None else first == eos_id
    )
    # Pool and row trees flatten to identical path strings (same
    # module tree, different leaf shapes); the row simply lacks
    # page_table/scale leaves, so .get() -> None for those.
    row_map = dict(zip(row_paths, row_leaves))
    aligned = [row_map.get(p) for p in paths]
    off = in_win % page
    # Padded tail slots scatter into reserved page 0 — the same junk
    # sink unmapped table entries read through.
    phys = jnp.where(valid, chunk_ids[in_win // page], 0)
    quantized = {}
    if quant:
        for i, name in enumerate(names):
            if name in _ARENA_RANK:
                rank = _ARENA_RANK[name]
                rr = _collapse_row(aligned[i], rank)
                win = jax.lax.dynamic_slice_in_dim(rr, start, width, axis=1)
                quantized[i] = quantize_kv(win, n_feat=rank - 2)
    out = []
    for i, (name, leaf) in enumerate(zip(names, leaves)):
        if name in ("page_table", "cache_index"):
            out.append(leaf)  # finalize owns the pool-side cursors
        elif name.endswith("_scale"):
            scales = quantized[scale_src[i]][1]
            a = _collapse_arena(leaf, 2)
            out.append(a.at[:, phys, off].set(scales).reshape(leaf.shape))
        elif name in _ARENA_RANK:
            rank = _ARENA_RANK[name]
            if quant:
                vals = quantized[i][0]
            else:
                rr = _collapse_row(aligned[i], rank)
                vals = jax.lax.dynamic_slice_in_dim(
                    rr, start, width, axis=1
                ).astype(leaf.dtype)
            a = _collapse_arena(leaf, rank)
            out.append(a.at[:, phys, off].set(vals).reshape(leaf.shape))
        elif name == "cached_segment_ids":
            rr = _collapse_row(aligned[i], 2)
            vals = jax.lax.dynamic_slice_in_dim(
                rr, start, width, axis=1
            ).astype(leaf.dtype)
            a = _collapse_arena(leaf, 2)
            out.append(a.at[:, phys, off].set(vals).reshape(leaf.shape))
        else:
            raise ValueError(
                f"unknown paged cache leaf {name!r}: the chunk scatter "
                "must know every leaf's role (an untouched leaf would "
                "leak the previous occupant's state)"
            )
    row_out = jax.tree_util.tree_unflatten(row_treedef, row_leaves)
    return tuple(out), row_out, first, done0, seen_row


@dataclasses.dataclass
class ChunkedPrefill:
    """Host-side cursor of one in-flight chunked prefill: the prompt,
    its contiguous row cache mid-flight, the pages committed so far,
    and the rng the final chunk samples with. Created by
    ``PagedSlotPool.start_chunked``, advanced by ``chunk_step``,
    consumed by ``finalize_chunked`` (or ``abandon_chunked`` on
    preemption — the trie checkpoint keeps every completed full page,
    so a re-admission resumes instead of restarting)."""

    prompt: List[int]
    rng: Any
    chunk_pages: int
    n_total: int  # pages the finished row owns (incl. decode budget)
    row_cache: Any  # None until the first chunk_step attaches it
    seen_row: Any
    cursor: int  # logical slots committed so far (page-aligned)
    page_ids: List[int]
    shared_n: int  # trie-shared pages attached at start
    n_chunks: int = 0
    first: Any = None
    first_int: int = -1
    done0: bool = False

    @property
    def resumed(self) -> bool:
        return self.shared_n > 0

    @property
    def deficit(self) -> int:
        """Pages still to acquire before this prefill can finish —
        admission guards sum this across in-flight chunked prefills so
        two part-admitted rows can never deadlock on the arena."""
        return self.n_total - len(self.page_ids)


@dataclasses.dataclass
class PagedSlotPool(SlotPool):
    """SlotPool whose KV lives in a shared page arena.

    ``decode_steps`` is INHERITED unchanged — paging is internal to the
    model's cache leaves. Insert/retire are replaced by page-aware
    versions, and two host-side owners ride along: ``allocator``
    (free list + refcounts) and ``prefix`` (radix trie; None when
    prefix caching is off). ``row_model`` is the contiguous twin
    (kv_page=0, same max_seq_len) prefill runs through."""

    row_model: Any = None
    page: int = 0
    allocator: Any = None
    prefix: Any = None
    slot_pages: Any = None  # per-slot page ids this row references
    #: Spill-tier callbacks (tpufw.serve.roles wires them to a
    #: tpufw.infer.spill.SpillTier + the TPFB codec; None = no spill).
    #: trie_spill(path_tokens, state) receives an evicted trie page's
    #: export state; trie_restore(path_tokens) -> state | None CONSUMES
    #: the matching spill entry (the pages are back in the arena — a
    #: kept copy would go stale the moment decode appends).
    trie_spill: Any = None
    trie_restore: Any = None
    # Admission-outcome counters for signals()/bench: requests whose
    # trie match (incl. spill restores) covered >= 1 page vs not, and
    # pages moved across the HBM <-> spill boundary.
    prefix_hits: int = 0
    prefix_misses: int = 0
    spill_pages_out: int = 0
    spill_pages_in: int = 0

    @classmethod
    def create_paged(
        cls,
        model,
        row_model,
        params,
        n_slots: int,
        *,
        sampling,
        pad_id: int = 0,
        eos_id: Optional[int] = None,
        prefix_cache: bool = True,
        allocator: Optional[PageAllocator] = None,
    ) -> "PagedSlotPool":
        cfg = model.cfg
        cache = paged_pool_cache(model, params, n_slots)
        seen = None
        if _track_seen(sampling):
            seen = jnp.zeros((n_slots, cfg.vocab_size), bool)
        if allocator is not None and allocator.n_pages != int(cfg.kv_pages):
            # Shared-allocator mode (speculative draft pool riding the
            # target's arena budget): one page-id space over the two
            # physically separate arenas, so both must be sized alike.
            raise ValueError(
                f"shared allocator covers {allocator.n_pages} pages but "
                f"cfg.kv_pages={cfg.kv_pages}"
            )
        return cls(
            model=model,
            params=params,
            n_slots=n_slots,
            sampling=sampling,
            pad_id=pad_id,
            eos_id=eos_id,
            cache=cache,
            axes=(),
            token=jnp.zeros((n_slots,), jnp.int32),
            pos=jnp.zeros((n_slots,), jnp.int32),
            done=jnp.ones((n_slots,), bool),
            remaining=jnp.zeros((n_slots,), jnp.int32),
            seen=seen,
            row_model=row_model,
            page=int(cfg.kv_page),
            allocator=(
                PageAllocator(int(cfg.kv_pages))
                if allocator is None else allocator
            ),
            prefix=PrefixCache(int(cfg.kv_page)) if prefix_cache else None,
            slot_pages=[[] for _ in range(n_slots)],
        )

    # ---- host-side page bookkeeping -------------------------------

    @property
    def per_row(self) -> int:
        return self.cache_len // self.page

    def n_pages_for(self, need: int) -> int:
        """Pages covering ``need`` logical slots (= prompt_len +
        max_new - 1: a live row's cursor never passes its budget)."""
        return -(-need // self.page)

    def acquire_pages(
        self, prompt: Sequence[int], need: int
    ) -> Optional[Tuple[List[int], int]]:
        """Reserve pages for a row: match the prompt against the prefix
        trie, then allocate the rest — evicting refcount-0 trie leaves
        under pressure. Returns (page_ids, shared_n) with row refs
        taken on every id, or None if the arena can't fit the row right
        now (the scheduler treats that like a closed KV budget and
        retries after the next retire)."""
        p = len(prompt)
        n_total = self.n_pages_for(need)
        shared: List[int] = []
        if self.prefix is not None and p > 1:
            # Cap so >= 1 suffix token always remains: the first output
            # token's logits need a real forward pass.
            shared = self.prefix.match(prompt)[: (p - 1) // self.page]
        # resource: acquires pages
        # Reference the shared pages FIRST so eviction below can't free
        # them out from under us (match() alone leaves refcount at 0
        # for pages only the trie holds).
        self.allocator.ref(shared)  # resource: acquires pages
        try:
            # Where the resident match ends, the spill tier may still
            # know the next chunks — restore them before prefilling.
            self._extend_shared_from_spill(
                prompt, shared, (p - 1) // self.page
            )
            n_new = n_total - len(shared)
            ids = self.allocator.alloc(n_new)
            if ids is None and self.prefix is not None:
                self.prefix.evict(
                    n_new - self.allocator.n_free, self.allocator,
                    on_evict=self._spill_hook(),
                )
                ids = self.allocator.alloc(n_new)
        except BaseException:
            # Trie surgery raising mid-evict must not strand the
            # shared-page refs taken above (TPU019). ``shared`` was
            # extended in place, so restored pages release too (their
            # trie hold keeps them resident — work not lost).
            self.allocator.release(shared)
            raise
        if ids is None:
            self.allocator.release(shared)
            return None
        if self.prefix is not None and p > 1:
            if shared:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1
        return shared + ids, len(shared)

    def release_pages(self, ids: Sequence[int]) -> int:
        # resource: releases pages
        return self.allocator.release(ids)

    def register_prefix(
        self, prompt: Sequence[int], page_ids: Sequence[int]
    ) -> None:
        """Adopt the row's FULL prompt pages into the trie (partial
        trailing page and decode pages stay private — they're the
        copy-on-write divergence zone)."""
        if self.prefix is None:
            return
        n_full = len(prompt) // self.page
        adopted = self.prefix.insert(prompt, list(page_ids)[:n_full])
        self.allocator.hold(adopted)

    # ---- spill tier (KV fabric) -----------------------------------

    def _spill_hook(self):
        """``on_evict`` callback for ``PrefixCache.evict``: export each
        victim page's bytes to the spill tier while the arena content
        is still valid. Best-effort — a failed spill degrades to the
        plain eviction this always was, never breaks an admission."""
        if self.trie_spill is None:
            return None

        def cb(path_tokens, page_id):
            try:
                state = self.export_pages_state([page_id])
                # wire: produces kv-spill-page via callback
                self.trie_spill(tuple(path_tokens), state)
                self.spill_pages_out += 1
            except Exception:
                pass

        return cb

    def export_pages_state(self, ids: Sequence[int]) -> Dict[str, Any]:
        """Snapshot arbitrary arena pages (no slot attached) as a
        migration-shaped state dict — the trie-spill serialization.
        Cursors are zeroed placeholders so ``tpufw.serve.bundle``'s
        required header fields are satisfied; ``import_pages`` ignores
        them. Same raw gather as ``export_slot``, so int8 codes +
        scales ship as stored and a later import is bit-identical."""
        ids = [int(i) for i in ids]
        paths, names, leaves, _ = self._pool_flat()
        arrays = _export_pages_jit(
            tuple(leaves),
            jnp.asarray(np.asarray(ids, np.int32)),
            names=names,
        )
        return {
            "page": self.page,
            "kv_quant": self.model.cfg.kv_quant or "",
            "n_pages": len(ids),
            "paths": [
                p for p, n in zip(paths, names)
                if _export_rank(n) is not None
            ],
            "arrays": [np.asarray(a) for a in arrays],
            "token": 0, "pos": 0, "remaining": 0, "done": True,
            "cache_index": 0, "seen": None,
        }

    def import_pages(
        self, page_ids: Sequence[int], state: Dict[str, Any]
    ) -> None:
        """Scatter a spill bundle's page payload into freshly
        allocated arena pages — the restore half of the spill tier and
        the same layout contract as ``splice_slot`` (page size, quant
        mode, leaf paths all validated before anything touches the
        arena). No cursors, no table row: the pages re-enter service
        through the prefix trie, not a slot."""
        # resource: transfers pages
        if int(state["page"]) != self.page:
            raise ValueError(
                f"spill page size {state['page']} != pool page "
                f"{self.page}"
            )
        if (state.get("kv_quant") or "") != (
            self.model.cfg.kv_quant or ""
        ):
            raise ValueError(
                f"spill kv_quant {state.get('kv_quant')!r} != pool "
                f"kv_quant {self.model.cfg.kv_quant!r}"
            )
        if len(page_ids) != int(state["n_pages"]):
            raise ValueError(
                f"spill bundle carries {state['n_pages']} pages but "
                f"{len(page_ids)} were allocated"
            )
        paths, names, leaves, treedef = self._pool_flat()
        want = [
            p for p, n in zip(paths, names)
            if _export_rank(n) is not None
        ]
        if list(state["paths"]) != want:
            raise ValueError(
                "spill bundle leaf layout does not match this pool "
                f"(got {list(state['paths'])!r}, want {want!r})"
            )
        out = _import_pages_jit(
            tuple(leaves),
            tuple(jnp.asarray(a) for a in state["arrays"]),
            jnp.asarray(np.asarray(page_ids, np.int32)),
            names=names,
        )
        self.cache = jax.tree_util.tree_unflatten(treedef, list(out))

    def _extend_shared_from_spill(
        self, prompt: Sequence[int], shared: List[int], cap: int
    ) -> None:
        """Extend a trie match chunk-by-chunk from the spill tier:
        while the NEXT full-page chunk of ``prompt`` has a spill entry,
        allocate one fresh page (its alloc ref IS the row's reference,
        matching ``ref(shared)`` on matched pages), scatter the bytes
        back in, and re-adopt the path into the trie (held) so later
        requests hit it resident. Mutates ``shared`` in place.

        Best-effort and non-raising: under arena pressure (alloc
        fails) it stops rather than evicting — restoring by evicting
        would just churn pages through the spill tier — and a torn or
        mismatched entry stops the walk; the row prefills the rest."""
        if self.trie_restore is None or self.prefix is None:
            return
        while len(shared) < cap:
            end = (len(shared) + 1) * self.page
            try:
                # wire: consumes kv-spill-page via callback
                state = self.trie_restore(
                    tuple(int(t) for t in prompt[:end])
                )
            except Exception:
                return
            if state is None:
                return
            ids = self.allocator.alloc(1)  # resource: acquires pages
            if ids is None:
                return
            try:
                self.import_pages(ids, state)
            except Exception:
                self.allocator.release(ids)  # resource: releases pages
                return
            adopted = self.prefix.insert(prompt[:end], shared + ids)
            self.allocator.hold(adopted)
            shared.extend(ids)
            self.spill_pages_in += 1

    # ---- device ops -----------------------------------------------

    def _pool_flat(self):
        paths, names, leaves, treedef = _flatten_with_names(self.cache)
        return paths, names, leaves, treedef

    def _aligned_row(self, paths, row_cache):
        row_paths, _, row_leaves, _ = _flatten_with_names(row_cache)
        row_map = dict(zip(row_paths, row_leaves))
        return [row_map.get(p) for p in paths]

    @staticmethod
    def _scale_src(paths, names) -> Tuple[int, ...]:
        """scale-leaf index -> its KV leaf's index (same path, name
        minus the "_scale" suffix); -1 elsewhere."""
        by_path = {p: i for i, p in enumerate(paths)}
        src = []
        for p, name in zip(paths, names):
            if name.endswith("_scale"):
                src.append(by_path[p.replace(name, name[: -len("_scale")])])
            else:
                src.append(-1)
        return tuple(src)

    def insert_paged(
        self,
        slot: int,
        row_cache,
        first,
        pos0: int,
        budget: int,
        page_ids: Sequence[int],
        shared_n: int,
        row_seen=None,
    ) -> None:
        """Occupy ``slot`` with a prefilled contiguous row scattered
        into ``page_ids`` (row refs already taken by
        ``acquire_pages``); the first ``shared_n`` ids are prefix pages
        attached by reference, never written."""
        paths, names, leaves, treedef = self._pool_flat()
        # resource: transfers pages
        row_leaves = self._aligned_row(paths, row_cache)
        table_row = np.zeros((self.per_row,), np.int32)
        table_row[: len(page_ids)] = page_ids
        quant = self.model.cfg.kv_quant == "int8"
        perf = getattr(self, "perf", None)
        if perf is not None:
            # Cost harvest (tpufw.obs.perf; once per program).
            perf.observe_jit(
                "serve_paged_insert",
                _paged_insert_jit,
                (
                    tuple(leaves), tuple(row_leaves),
                    jnp.asarray(table_row), slot, shared_n * self.page,
                    first, pos0, budget, self.token, self.pos,
                    self.done, self.remaining, self.seen, row_seen,
                ),
                kwargs=dict(
                    names=names, scale_src=self._scale_src(paths, names),
                    page=self.page, quant=quant,
                ),
            )
        leaves, self.token, self.pos, self.done, self.remaining, \
            self.seen = _paged_insert_jit(
                tuple(leaves), tuple(row_leaves), jnp.asarray(table_row),
                slot, shared_n * self.page, first, pos0, budget,
                self.token, self.pos, self.done, self.remaining,
                self.seen, row_seen,
                names=names, scale_src=self._scale_src(paths, names),
                page=self.page, quant=quant,
            )
        self.cache = jax.tree_util.tree_unflatten(treedef, list(leaves))
        self.slot_pages[slot] = list(page_ids)

    def _attach_row(self, shared_ids):
        """Fresh B=1 contiguous row cache with ``shared_ids``' pages
        gathered into its first ``len(shared_ids) * page`` slots
        (cursor set accordingly); plain zeros when nothing is shared.

        A fresh template every call: the attach jit DONATES the row
        leaves (their memory becomes the attached cache), so a cached
        tree would hand already-deleted buffers to the second prefix
        hit. The zeros alloc is trivia next to the prefill."""
        row_tree = _row_zeros_tree(self.row_model, self.params)
        if not len(shared_ids):
            return row_tree
        paths, names, leaves, _ = self._pool_flat()
        row_paths, _, row_leaves, row_treedef = _flatten_with_names(
            row_tree
        )
        row_map = dict(zip(row_paths, row_leaves))
        aligned = [row_map.get(p) for p in paths]
        quant = self.model.cfg.kv_quant == "int8"
        src = self._scale_src(paths, names)
        scale_of = tuple(
            src.index(i) if i in src else -1 for i in range(len(paths))
        )
        attached = _attach_shared_jit(
            tuple(aligned), tuple(leaves),
            jnp.asarray(np.asarray(shared_ids, np.int32)),
            names=names, scale_of=scale_of, page=self.page, quant=quant,
        )
        return jax.tree_util.tree_unflatten(
            row_treedef, [a for a in attached if a is not None]
        )

    def prefill_shared(self, prompt: Sequence[int], shared_ids, rng):
        """Prefix-hit admission: attach ``shared_ids``' pages to a
        fresh row cache, prefill only the suffix. Same return contract
        as ``tpufw.infer.slots.prefill_row`` — (row_cache, first_arr,
        first_int, done0, seen)."""
        row_cache = self._attach_row(shared_ids)
        length = len(shared_ids) * self.page
        suffix = jnp.asarray(
            np.asarray(prompt[length:], np.int32)[None, :]
        )
        full = jnp.asarray(np.asarray(prompt, np.int32)[None, :])
        cache, first, done, seen = _suffix_prefill_jit(
            self.row_model, self.params, row_cache, suffix, full,
            length, rng, sampling=self.sampling, eos_id=self.eos_id,
        )
        return cache, first, int(np.asarray(first)[0]), done, seen

    # ---- chunked prefill ------------------------------------------

    def start_chunked(
        self, prompt: Sequence[int], need: int, rng,
        chunk_pages: int,
    ) -> ChunkedPrefill:
        """Open a chunked prefill: match the prompt against the prefix
        trie (a checkpoint from a preempted admission resumes here for
        free), reference whatever is shared, and return the cursor
        object ``chunk_step`` advances. Acquires NO new pages — every
        page grab happens page-aligned inside ``chunk_step`` — and
        reads NO pool leaves: the shared-prefix attach (the one
        admission-time device read) is deferred into the first
        ``chunk_step``, whose caller already guarantees leaf
        exclusivity, so an engine may admit mid-chunk even while a
        donated chunk jit is in flight. ``need`` is
        the slot count the FINISHED row must own pages for (prompt +
        decode budget for an in-place admission; just the prompt for a
        prefill engine exporting prompt-only bundles)."""
        prompt = [int(t) for t in prompt]
        p = len(prompt)
        shared: List[int] = []
        if self.prefix is not None and p > 1:
            # Same cap as acquire_pages: >= 1 suffix token must remain
            # so the first output token's logits get a real forward.
            shared = self.prefix.match(prompt)[: (p - 1) // self.page]
        # resource: acquires pages
        # ref() pins the shared pages host-side right now (eviction
        # can't reclaim them); their KV is gathered lazily by the
        # first chunk_step. refcounts make the deferral safe: pinned
        # pages are never reallocated, so their content is stable.
        self.allocator.ref(shared)  # resource: acquires pages
        try:
            # Spill-tier continuation of the resident match, same as
            # acquire_pages (restored pages join the deferred attach).
            self._extend_shared_from_spill(
                prompt, shared, (p - 1) // self.page
            )
            if self.prefix is not None and p > 1:
                if shared:
                    self.prefix_hits += 1
                else:
                    self.prefix_misses += 1
            seen = None
            if _track_seen(self.sampling):
                m = np.zeros((1, self.model.cfg.vocab_size), bool)
                if shared:
                    m[0, np.asarray(
                        prompt[: len(shared) * self.page], np.int64
                    )] = True
                seen = jnp.asarray(m)
            cp = ChunkedPrefill(
                prompt=prompt,
                rng=rng,
                chunk_pages=max(1, int(chunk_pages)),
                n_total=self.n_pages_for(max(need, p)),
                row_cache=None,  # first chunk_step attaches (leaf read)
                seen_row=seen,
                cursor=len(shared) * self.page,
                page_ids=list(shared),
                shared_n=len(shared),
            )
        except BaseException:
            # A host-array failure here must not strand the shared
            # refs: nobody has the cursor object yet (TPU019).
            self.allocator.release(shared)
            raise
        return cp

    def chunk_step(
        self, cp: ChunkedPrefill, unlocked=None
    ) -> str:
        """Advance ``cp`` by one page-aligned chunk. Returns "ran"
        (progress, more chunks to go), "done" (first token sampled,
        ready for ``finalize_chunked``), or "stalled" (the arena could
        not supply this chunk's pages right now — safe to retry after
        the next release; nothing was consumed).

        Completed full pages are checkpointed into the prefix trie
        after EVERY chunk, so an abandon at any point leaves a resume
        point behind — and concurrent identical prompts start sharing
        pages before this prefill even finishes.

        ``unlocked``, if given, is a context-manager FACTORY that
        releases the caller's pool mutex around the pure-compute jit
        call: every shared-state mutation (allocator, trie, pool
        leaves) happens outside it, so admissions and abandons can
        interleave with a chunk's device time — but the CALLER must
        still guarantee only one chunk_step is in flight per pool
        (concurrent calls would fork the arena leaves)."""
        # No acquires-contract here: every page this call grabs is
        # transferred into cp.page_ids before it can return or raise,
        # so the CALLER holds nothing — cp's owner discharges via
        # finalize_chunked / abandon_chunked.
        p = len(cp.prompt)
        start = cp.cursor
        left = p - start
        width = min(cp.chunk_pages, -(-left // self.page)) * self.page
        n_real = min(left, width)
        is_final = left <= width
        # The final chunk acquires the full remaining page need —
        # including the decode-budget tail — BEFORE compute, so a
        # finished prefill can always finalize.
        target = cp.n_total if is_final else (start + width) // self.page
        n_new = target - len(cp.page_ids)
        if n_new > 0:
            ids = self.allocator.alloc(n_new)
            if ids is None and self.prefix is not None:
                self.prefix.evict(
                    n_new - self.allocator.n_free, self.allocator,
                    on_evict=self._spill_hook(),
                )
                ids = self.allocator.alloc(n_new)
            if ids is None:
                return "stalled"
            cp.page_ids.extend(ids)  # resource: transfers pages
        tokens = np.zeros((1, width), np.int32)
        tokens[0, :n_real] = np.asarray(
            cp.prompt[start:start + n_real], np.int32
        )
        first_pg = start // self.page
        chunk_ids = np.asarray(
            cp.page_ids[first_pg:first_pg + width // self.page],
            np.int32,
        )
        paths, names, leaves, treedef = self._pool_flat()
        quant = self.model.cfg.kv_quant == "int8"
        with (unlocked() if unlocked is not None
              else contextlib.nullcontext()):
            if cp.row_cache is None:
                # Deferred shared-prefix attach: the one pool-leaf
                # read of a chunked admission, pulled out of
                # start_chunked and into this busy window so
                # admissions never race a donated in-flight chunk.
                # Safe here — the single-flight contract means no
                # other chunk can donate these leaves mid-read.
                cp.row_cache = self._attach_row(
                    cp.page_ids[: cp.shared_n]
                )
            out_leaves, cp.row_cache, first, done0, cp.seen_row = (  # resource: donates leaves
                _prefill_chunk_jit(
                    tuple(leaves), cp.row_cache, self.params,
                    jnp.asarray(tokens), jnp.asarray(chunk_ids),
                    np.int32(start), np.int32(n_real),
                    np.bool_(is_final), cp.rng, cp.seen_row,
                    row_model=self.row_model, sampling=self.sampling,
                    eos_id=self.eos_id, paths=paths, names=names,
                    scale_src=self._scale_src(paths, names),
                    page=self.page, quant=quant,
                )
            )
            if unlocked is not None:
                # Dispatch is async — pin the device wall inside the
                # lock-released window, not under some later holder.
                jax.block_until_ready(
                    (out_leaves, cp.row_cache, first, done0)
                )
        self.cache = jax.tree_util.tree_unflatten(
            treedef, list(out_leaves)
        )
        cp.cursor = start + n_real
        cp.n_chunks += 1
        if self.prefix is not None:
            # Per-chunk trie checkpoint: the committed prefix's full
            # pages become shareable (and survive an abandon).
            n_full = cp.cursor // self.page
            adopted = self.prefix.insert(
                cp.prompt[:cp.cursor], cp.page_ids[:n_full]
            )
            self.allocator.hold(adopted)
        if is_final:
            cp.first = first
            cp.first_int = int(np.asarray(first)[0])
            cp.done0 = bool(np.asarray(done0)[0])
            return "done"
        return "ran"

    def finalize_chunked(
        self, slot: int, cp: ChunkedPrefill, budget: int
    ) -> None:
        """Occupy ``slot`` with a completed chunked prefill. The arena
        already holds every prompt page (chunk_step scattered them), so
        ``insert_paged`` is reused with ``shared_n = per_row``: its
        window scatter redirects entirely into reserved page 0 and the
        call just installs the table row + cursors — zero new program
        keys. The row cache's cache_index (fixed to the prompt length
        inside the chunk jit) supplies the slot cursor."""
        # resource: transfers pages
        self.insert_paged(
            slot, cp.row_cache, cp.first_int, len(cp.prompt), budget,
            cp.page_ids, self.per_row, row_seen=cp.seen_row,
        )

    def abandon_chunked(self, cp: ChunkedPrefill) -> int:
        """Preempt/fail path: drop the row's page references. Trie-
        checkpointed full pages stay resident (held) — that IS the
        resume point a re-admission's ``start_chunked`` picks up —
        while unheld pages free immediately. Returns pages freed."""
        # resource: releases pages
        freed = self.allocator.release(cp.page_ids)
        cp.page_ids = []
        return freed

    def release_slot(self, slot: int) -> int:
        """Free ``slot``: freeze its masks, zero its page-table row,
        return its pages to the allocator. Returns pages actually freed
        (shared/held pages may stay resident)."""
        # resource: releases pages
        # resource: releases slot
        self.done, self.remaining = _retire_jit(
            self.done, self.remaining, slot
        )
        paths, names, leaves, treedef = self._pool_flat()
        t_idx = [i for i, n in enumerate(names) if n == "page_table"]
        cleared = _clear_tables_jit(
            tuple(leaves[i] for i in t_idx), slot
        )
        for i, t in zip(t_idx, cleared):
            leaves[i] = t
        self.cache = jax.tree_util.tree_unflatten(treedef, leaves)
        freed = self.allocator.release(self.slot_pages[slot])
        self.slot_pages[slot] = []
        return freed

    # ---- page migration (disaggregated serving) -------------------

    def exported_paths(self) -> List[str]:
        """Leaf paths that travel in a page bundle, in pool-flat order
        — the layout contract both ends of a migration must agree on."""
        paths, names, _, _ = self._pool_flat()
        return [
            p for p, n in zip(paths, names)
            if _export_rank(n) is not None
        ]

    def export_slot(
        self, slot: int, page_ids: Optional[Sequence[int]] = None
    ) -> Dict[str, Any]:
        """Snapshot slot ``slot``'s KV pages + cursors as a host-side
        migration state dict (tpufw.serve.bundle serializes it).

        MUST run before ``release_slot``: after release the device
        table row is zeroed (reads would gather reserved page 0's
        junk) and the pages may already belong to a new admission.
        ``page_ids`` lets the caller pass the page-table snapshot it
        took at the chunk boundary — the scheduler's retire path does,
        so a row finishing mid-chunk exports the pages it owned when
        the chunk was launched, not whatever the list mutated to."""
        # resource: transfers slot
        ids = list(
            self.slot_pages[slot] if page_ids is None else page_ids
        )
        paths, names, leaves, _ = self._pool_flat()
        arrays = _export_pages_jit(
            tuple(leaves),
            jnp.asarray(np.asarray(ids, np.int32)),
            names=names,
        )
        cache_index = 0
        for n, leaf in zip(names, leaves):
            if n == "cache_index":
                # Every layer carries the same per-slot value.
                cache_index = int(
                    np.asarray(leaf).reshape(-1, self.n_slots)[0, slot]
                )
                break
        seen_row = None
        if self.seen is not None:
            seen_row = np.asarray(self.seen[slot])
        return {
            "page": self.page,
            "kv_quant": self.model.cfg.kv_quant or "",
            "n_pages": len(ids),
            "paths": [
                p for p, n in zip(paths, names)
                if _export_rank(n) is not None
            ],
            "arrays": [np.asarray(a) for a in arrays],
            "token": int(np.asarray(self.token)[slot]),
            "pos": int(np.asarray(self.pos)[slot]),
            "remaining": int(np.asarray(self.remaining)[slot]),
            "done": bool(np.asarray(self.done)[slot]),
            "cache_index": cache_index,
            "seen": seen_row,
        }

    def splice_slot(
        self, slot: int, state: Dict[str, Any],
        page_ids: Sequence[int],
    ) -> None:
        """Occupy ``slot`` with a migrated bundle: scatter its page
        payload into ``page_ids`` (already allocated, row refs taken)
        and restore the cursors. Raises ValueError on any layout
        mismatch — a bundle from a differently-shaped pool must be
        rejected before it scribbles on the arena."""
        # resource: transfers pages
        if int(state["page"]) != self.page:
            raise ValueError(
                f"bundle page size {state['page']} != pool page "
                f"{self.page}"
            )
        if (state.get("kv_quant") or "") != (
            self.model.cfg.kv_quant or ""
        ):
            raise ValueError(
                f"bundle kv_quant {state.get('kv_quant')!r} != pool "
                f"kv_quant {self.model.cfg.kv_quant!r}"
            )
        if len(page_ids) < int(state["n_pages"]):
            raise ValueError(
                f"bundle carries {state['n_pages']} pages but only "
                f"{len(page_ids)} were allocated"
            )
        paths, names, leaves, treedef = self._pool_flat()
        want = [
            p for p, n in zip(paths, names)
            if _export_rank(n) is not None
        ]
        if list(state["paths"]) != want:
            raise ValueError(
                "bundle leaf layout does not match this pool "
                f"(got {list(state['paths'])!r}, want {want!r}) — "
                "model config / cache structure drift between replicas"
            )
        seen_row = state.get("seen")
        if (seen_row is None) != (self.seen is None):
            raise ValueError(
                "bundle and pool disagree on repetition-penalty "
                "tracking (seen mask present on one side only)"
            )
        # The table row maps EVERY allocated page (a prompt-only bundle
        # from a chunked prefill ships fewer pages than the row's full
        # prompt+budget need — the extra tail pages hold junk until
        # decode's append writes them, and slots past the cursor are
        # causally masked until then); the payload scatter only touches
        # the pages the bundle actually carries.
        table_row = np.zeros((self.per_row,), np.int32)
        table_row[: len(page_ids)] = page_ids
        leaves_out, self.token, self.pos, self.done, self.remaining, \
            self.seen = _splice_pages_jit(
                tuple(leaves),
                tuple(jnp.asarray(a) for a in state["arrays"]),
                jnp.asarray(np.asarray(
                    page_ids[: int(state["n_pages"])], np.int32
                )),
                jnp.asarray(table_row),
                slot,
                np.int32(state["cache_index"]),
                np.int32(state["token"]),
                np.int32(state["pos"]),
                np.int32(state["remaining"]),
                np.bool_(state["done"]),
                self.token, self.pos, self.done, self.remaining,
                self.seen,
                None if seen_row is None else jnp.asarray(seen_row),
                names=names,
            )
        self.cache = jax.tree_util.tree_unflatten(
            treedef, list(leaves_out)
        )
        self.slot_pages[slot] = list(page_ids)

    def retire(self, slot: int) -> None:
        """Error-path retire — page-aware (frees the row's pages)."""
        self.release_slot(slot)

    def insert(self, *a, **k):  # pragma: no cover - guard rail
        raise TypeError(
            "PagedSlotPool: use insert_paged (pages must be acquired "
            "through the allocator first)"
        )
