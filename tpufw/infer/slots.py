"""Persistent device-resident KV slot pool for continuous batching.

The tick batcher in ``tpufw.workloads.serve`` coalesces waiting requests
into ONE ``generate`` scan: every row rides to the group's bucketed
``max_new``, EOS'd rows decode dead air, and arrivals wait a whole tick.
This module is the Orca/vLLM-style alternative at decode-STEP
granularity, mapped onto TPU static-shape discipline: the KV cache is a
pool of ``S`` slots with FIXED shapes (``[S, cache_len, heads, dim]``
leaves from the serving ``_cache_bucket`` ladder), and three jitted ops
move requests through it —

- ``insert``: copy one B=1 prefilled row cache into slot ``i`` with
  ``lax.dynamic_update_slice`` (the slot index is a TRACED scalar, so
  every slot shares one compiled program);
- ``decode_steps``: advance ALL slots ``k`` tokens in one device call
  (a ``lax.scan`` over the shared ``_decode_step``-style body) under
  per-slot ``(position, done, remaining)`` masks — occupancy is DATA,
  never a shape, so join/leave mid-flight can't recompile;
- ``retire``: freeze a slot's masks (error paths; natural completions
  are already frozen by the step body).

Per-slot cache cursors ride the flax "cache" collection as a ``[S]``
vector ``cache_index`` (trailing-slot-axis convention; the models'
``_cached_attention`` branches on cursor rank). ``TRACE_COUNTS`` is
bumped at TRACE time inside each op, so tests (and operators) can
assert the shape-stability contract: inserts/retires at steady state
add ZERO new traces.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.infer.generate import _model_apply, _stream_prefill
from tpufw.infer.sampling import SamplingConfig, sample_token

# Bumped INSIDE the jitted bodies, i.e. once per (re)trace, never per
# call: the cheap, version-proof way to assert "occupancy changes do
# not recompile" without reaching into jax internals.
TRACE_COUNTS: Dict[str, int] = {"insert": 0, "decode_steps": 0, "retire": 0}


def _track_seen(sampling: SamplingConfig) -> bool:
    return (
        sampling.repetition_penalty is not None
        and sampling.repetition_penalty != 1.0
    )


def pool_cache(model, params, n_slots: int) -> Tuple[Any, Tuple]:
    """Allocate a zeroed S-slot cache for ``model`` + its batch axes.

    Two ``eval_shape`` probes (B = S and B = S + 1) of the model's own
    cache init find, per leaf, the ONE axis that scales with batch —
    robust to scanned trunks (leading ``[L]`` stack), MLA latent caches,
    and any future cache layout. A leaf with NO batch axis is a cursor:
    it gets a trailing slot axis (``[] -> [S]``, ``[L] -> [L, S]``), so
    inside the model (after nn.scan slices the layer axis) the cursor
    arrives as the ``[B]`` vector the per-row attention branch expects.

    Zeros are safe initial state: never-written cache slots keep
    segment 0, and the segment mask hides them.
    """

    def shapes(b):
        def init(p):
            toks = jnp.zeros((b, 1), jnp.int32)
            pos = jnp.zeros((b, 1), jnp.int32)
            seg = jnp.ones((b, 1), jnp.int32)
            _, vars_ = model.apply(
                {"params": p}, toks, positions=pos, segment_ids=seg,
                mutable=["cache"],
            )
            return vars_["cache"]

        return jax.eval_shape(init, params)

    base = shapes(n_slots)
    probe = shapes(n_slots + 1)
    base_leaves, treedef = jax.tree_util.tree_flatten(base)
    probe_leaves = jax.tree_util.tree_leaves(probe)
    axes = []
    leaves = []
    for bl, pl in zip(base_leaves, probe_leaves):
        diff = [
            i for i, (x, y) in enumerate(zip(bl.shape, pl.shape)) if x != y
        ]
        if not diff:
            axes.append(None)
            leaves.append(jnp.zeros((*bl.shape, n_slots), bl.dtype))
        elif len(diff) == 1:
            axes.append(diff[0])
            leaves.append(jnp.zeros(bl.shape, bl.dtype))
        else:
            raise ValueError(
                "cache leaf with multiple batch-dependent axes "
                f"{bl.shape} vs {pl.shape} — slot pooling needs exactly "
                "one"
            )
    # Wrapped in the {"cache": ...} variables form the shared decode
    # apply closure (_model_apply) threads — same shape prefill hands
    # back, so insert's leaf zip lines up one-to-one.
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return {"cache": tree}, tuple(axes)


@partial(
    jax.jit,
    static_argnames=("axes",),
    donate_argnames=("leaves", "token", "pos", "done", "remaining", "seen"),
)
def _insert_jit(
    leaves, row_leaves, slot, first, pos0, budget,
    token, pos, done, remaining, seen, row_seen, *, axes,
):
    """Copy a B=1 prefilled row into slot ``slot`` (traced scalar)."""
    TRACE_COUNTS["insert"] += 1
    out = []
    for leaf, row, axis in zip(leaves, row_leaves, axes):
        if axis is None:  # cursor leaf: trailing slot axis
            out.append(leaf.at[..., slot].set(row))
        else:
            start = tuple(
                slot if i == axis else 0 for i in range(leaf.ndim)
            )
            out.append(
                jax.lax.dynamic_update_slice(
                    leaf, row.astype(leaf.dtype), start
                )
            )
    token = token.at[slot].set(first)
    pos = pos.at[slot].set(pos0)
    done = done.at[slot].set(False)
    remaining = remaining.at[slot].set(budget)
    if seen is not None:
        seen = seen.at[slot].set(row_seen[0])
    return tuple(out), token, pos, done, remaining, seen


@partial(jax.jit, donate_argnames=("done", "remaining"))
def _retire_jit(done, remaining, slot):
    TRACE_COUNTS["retire"] += 1
    return done.at[slot].set(True), remaining.at[slot].set(0)


@partial(
    jax.jit,
    static_argnames=("model", "sampling", "pad_id", "eos_id"),
    donate_argnames=("cache", "token", "pos", "done", "remaining", "seen"),
)
def _decode_steps_jit(
    model, params, cache, token, pos, done, remaining, seen, keys,
    *, sampling, pad_id, eos_id,
):
    """Advance every slot ``len(keys)`` tokens in ONE device call.

    Mirrors ``generate``'s ``_decode_step`` body (sample -> seen update
    -> pad frozen rows -> eos) plus the per-slot ``remaining`` budget:
    a row emits its token THEN burns budget, so the EOS/boundary token
    itself is delivered and the row freezes after. Done rows keep
    stepping (static shapes; masking, not control flow) but feed pad
    back and emit pad out.
    """
    TRACE_COUNTS["decode_steps"] += 1
    apply = _model_apply(model, params)
    s = token.shape[0]
    track = _track_seen(sampling)
    ones = jnp.ones((s, 1), jnp.int32)

    def step(carry, rng_step):
        cache, token, pos, done, remaining, seen = carry
        logits, cache = apply(cache, token[:, None], pos[:, None], ones)
        nxt = sample_token(logits[:, -1, :], sampling, rng_step, seen)
        if track:
            seen = seen.at[jnp.arange(s), nxt].set(True)
        emitted = jnp.where(done, pad_id, nxt)
        remaining = jnp.where(done, remaining, remaining - 1)
        newly = remaining <= 0
        if eos_id is not None:
            newly = newly | (nxt == eos_id)
        done = done | newly
        return (cache, emitted, pos + 1, done, remaining, seen), emitted

    (cache, token, pos, done, remaining, seen), out = jax.lax.scan(
        step, (cache, token, pos, done, remaining, seen), keys
    )
    return cache, token, pos, done, remaining, seen, out.T  # [S, k]


def prefill_row(
    model,
    params,
    prompt,
    rng,
    *,
    sampling: SamplingConfig,
    eos_id: Optional[int],
    pad_to: Optional[int] = None,
    prefill_chunk_size: Optional[int] = None,
    pad_id: int = 0,
):
    """B=1 prefill for one request row, reusing ``_stream_prefill`` (the
    shared prefill + first-token discipline). ``pad_to`` left-pads the
    prompt to a bucketed static width so prefill programs are shared
    across lengths. Returns ``(row_cache, first_arr, first_int, done0,
    seen)`` — ``first_int`` is synced to host (the admission point is
    the scheduler's one natural sync; the next RoPE position is just
    ``len(prompt)``, no device read needed)."""
    p = len(prompt)
    width = max(pad_to or p, p)
    tokens = np.full((1, width), pad_id, np.int32)
    if p:
        tokens[0, width - p:] = np.asarray(prompt, np.int32)
    pads = np.full((1,), width - p, np.int32)
    cache, first, pos0, done, seen, _ = _stream_prefill(
        model,
        params,
        jnp.asarray(tokens),
        jnp.asarray(pads),
        rng,
        n_step_keys=1,
        sampling=sampling,
        eos_id=eos_id,
        prefill_chunk_size=prefill_chunk_size,
    )
    return cache, first, int(np.asarray(first)[0]), done, seen


@dataclasses.dataclass
class SlotPool:
    """Device state + jit plumbing for one (cache_len, sampling) pool.

    Host-side occupancy bookkeeping (which request owns which slot)
    lives in the scheduler; this object only carries the device arrays
    and re-binds them across the donated jit calls.
    """

    model: Any
    params: Any
    n_slots: int
    sampling: SamplingConfig
    pad_id: int
    eos_id: Optional[int]
    cache: Any
    axes: Tuple
    token: jax.Array
    pos: jax.Array
    done: jax.Array
    remaining: jax.Array
    seen: Any

    @classmethod
    def create(
        cls,
        model,
        params,
        n_slots: int,
        *,
        sampling: SamplingConfig = SamplingConfig(),
        pad_id: int = 0,
        eos_id: Optional[int] = None,
    ) -> "SlotPool":
        cache, axes = pool_cache(model, params, n_slots)
        seen = None
        if _track_seen(sampling):
            seen = jnp.zeros((n_slots, model.cfg.vocab_size), bool)
        return cls(
            model=model,
            params=params,
            n_slots=n_slots,
            sampling=sampling,
            pad_id=pad_id,
            eos_id=eos_id,
            cache=cache,
            axes=axes,
            token=jnp.zeros((n_slots,), jnp.int32),
            pos=jnp.zeros((n_slots,), jnp.int32),
            # Empty slots are born done with no budget: they emit pad
            # and their (zeroed, segment-0) cache rows stay invisible.
            done=jnp.ones((n_slots,), bool),
            remaining=jnp.zeros((n_slots,), jnp.int32),
            seen=seen,
        )

    @property
    def cache_len(self) -> int:
        return int(self.model.cfg.max_seq_len)

    def insert(self, slot: int, row_cache, first, pos0: int, budget: int,
               row_seen=None) -> None:
        """Occupy ``slot`` with a prefilled row. ``budget`` is the
        number of DECODE steps left (max_new - 1; the prefill-sampled
        first token is already out)."""
        leaves, treedef = jax.tree_util.tree_flatten(self.cache)
        row_leaves = jax.tree_util.tree_leaves(row_cache)
        perf = getattr(self, "perf", None)
        if perf is not None:
            # Cost harvest (tpufw.obs.perf; once per program): the
            # scheduler mounts ``pool.perf`` after _build_pool.
            perf.observe_jit(
                "serve_insert",
                _insert_jit,
                (
                    tuple(leaves), tuple(row_leaves), slot, first, pos0,
                    budget, self.token, self.pos, self.done,
                    self.remaining, self.seen, row_seen,
                ),
                kwargs=dict(axes=self.axes),
            )
        leaves, self.token, self.pos, self.done, self.remaining, \
            self.seen = _insert_jit(
                tuple(leaves), tuple(row_leaves), slot, first, pos0,
                budget, self.token, self.pos, self.done, self.remaining,
                self.seen, row_seen, axes=self.axes,
            )
        self.cache = jax.tree_util.tree_unflatten(treedef, list(leaves))

    def decode_steps(self, keys) -> jax.Array:
        """Advance all slots ``len(keys)`` tokens; returns [S, k]."""
        perf = getattr(self, "perf", None)
        if perf is not None:
            # One program per chunk-ladder rung (k is a shape).
            perf.observe_jit(
                f"serve_decode_k{len(keys)}",
                _decode_steps_jit,
                (
                    self.model, self.params, self.cache, self.token,
                    self.pos, self.done, self.remaining, self.seen, keys,
                ),
                kwargs=dict(
                    sampling=self.sampling, pad_id=self.pad_id,
                    eos_id=self.eos_id,
                ),
            )
        (
            self.cache, self.token, self.pos, self.done, self.remaining,
            self.seen, out,
        ) = _decode_steps_jit(
            self.model, self.params, self.cache, self.token, self.pos,
            self.done, self.remaining, self.seen, keys,
            sampling=self.sampling, pad_id=self.pad_id,
            eos_id=self.eos_id,
        )
        return out

    def spec_steps(self, proposals, key):
        """One self-draft speculative pass: verify host proposals
        [S, k] in a single t=k+1 target call and advance every slot by
        its per-slot accept count (tpufw.infer.speculative chunked
        path). Returns (out [S, k+1], n_emit [S], accept [S])."""
        from tpufw.infer import speculative as _spec

        return _spec.spec_verify_steps(self, proposals, key)

    def spec_draft_steps(self, draft_pool, key, k: int):
        """One fused draft+verify speculative pass against
        ``draft_pool`` (same slot count, cursors in lockstep).
        Returns (out [S, k+1], n_emit [S], accept [S])."""
        from tpufw.infer import speculative as _spec

        return _spec.spec_draft_steps(self, draft_pool, key, k)

    def retire(self, slot: int) -> None:
        """Freeze ``slot`` (error paths — natural completions are
        already frozen by the step body's done/remaining masks)."""
        self.done, self.remaining = _retire_jit(
            self.done, self.remaining, slot
        )
