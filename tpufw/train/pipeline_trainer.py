"""Pipeline-parallel trainer: the Trainer's operational surface over the
GPipe schedule (tpufw.parallel.pipeline).

The Trainer's operational surface — jitted donated-state step,
tokens/s-per-chip + MFU metrics, async Orbax checkpoint/resume,
multi-host batch globalization — with the layer stack executing on the
``pipe`` mesh axis instead of under the flax scan trunk. The functional
pipeline params (stage stacks sharded over ``pipe``) replace the flax
TrainState; Meter, CheckpointManager, optimizer recipe, and
globalize_batch are the shared machinery.

Packed batches (segment_ids + loss_mask) train with the same masking as
the flax trainer (shift_and_mask); segment ids ride the pipe ring with
their microbatch. Held-out eval runs the forward-only pipeline
(pipeline_eval) with the flax trainer's token-weighted loss/ppl
surface. Chunked-vocab CE runs the head inside tpufw.ops.loss (the
pipelined forward returns hidden states), and XProf step windows work
as in the flax trainer; grad_accum is rejected loudly — microbatching
IS the GPipe schedule (size it via PipelineConfig.n_microbatches).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from tpufw.mesh import MeshConfig, build_mesh
from tpufw.models.llama import LlamaConfig
from tpufw.parallel.pipeline import (
    PipelineConfig,
    init_pipeline_params,
    pipeline_eval,
    pipeline_loss,
    pipeline_param_shardings,
)
from tpufw.train.metrics import Meter, StepMetrics, timed_batches
from tpufw.train.trainer import (
    TrainerConfig,
    default_optimizer,
    maybe_inloop_eval,
)


class PipeTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


def _pipe_state_step(
    state: PipeTrainState,
    batch: dict,
    tx,
    model_cfg: LlamaConfig,
    pipe: PipelineConfig,
    mesh,
    loss_chunk_size=None,
    loss_chunk_dtype=None,
) -> tuple[PipeTrainState, dict]:
    """TrainState-shaped step (the functional
    tpufw.parallel.pipeline.pipeline_train_step stays the public
    params/opt_state API; this private wrapper is the trainer's)."""
    if pipe.schedule == "1f1b":
        from tpufw.parallel.pipeline_1f1b import (
            pipeline_1f1b_value_and_grad,
        )

        loss, grads = pipeline_1f1b_value_and_grad(
            state.params, batch, model_cfg, pipe, mesh,
            loss_chunk_size=loss_chunk_size,
            loss_chunk_dtype=loss_chunk_dtype,
        )
    elif pipe.schedule == "interleaved":
        from tpufw.parallel.pipeline_interleaved import (
            pipeline_interleaved_value_and_grad,
        )

        loss, grads = pipeline_interleaved_value_and_grad(
            state.params, batch, model_cfg, pipe, mesh,
            loss_chunk_size=loss_chunk_size,
            loss_chunk_dtype=loss_chunk_dtype,
        )
    elif pipe.schedule == "zb1":
        from tpufw.parallel.pipeline_zb1 import (
            pipeline_zb1_value_and_grad,
        )

        loss, grads = pipeline_zb1_value_and_grad(
            state.params, batch, model_cfg, pipe, mesh,
            loss_chunk_size=loss_chunk_size,
            loss_chunk_dtype=loss_chunk_dtype,
        )
    else:
        loss, grads = jax.value_and_grad(pipeline_loss)(
            state.params, batch, model_cfg, pipe, mesh,
            loss_chunk_size, loss_chunk_dtype,
        )
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    return (
        PipeTrainState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=new_opt,
        ),
        {"loss": loss, "grad_norm": optax.global_norm(grads)},
    )


class PipelineTrainer:
    """Drives pipeline-parallel training with the standard tpufw surface."""

    def __init__(
        self,
        model_cfg: LlamaConfig,
        pipe: PipelineConfig,
        trainer_cfg: TrainerConfig,
        mesh_cfg: MeshConfig | None = None,
        tx: optax.GradientTransformation | None = None,
    ):
        # TrainerConfig schedule knob overrides the PipelineConfig —
        # one source of truth for workloads/manifests/autotuner, and
        # the replace keeps validate() as the single gatekeeper.
        if trainer_cfg.pipeline_schedule:
            pipe = dataclasses.replace(
                pipe,
                schedule=trainer_cfg.pipeline_schedule,
                n_virtual=(
                    trainer_cfg.pipeline_vstages
                    if trainer_cfg.pipeline_schedule == "interleaved"
                    else 1
                ),
            )
        if mesh_cfg is None:
            mesh_cfg = MeshConfig(pipe=pipe.n_stages, fsdp=-1)
        if mesh_cfg.pipe != pipe.n_stages:
            raise ValueError(
                f"mesh_cfg.pipe={mesh_cfg.pipe} != "
                f"PipelineConfig.n_stages={pipe.n_stages}"
            )
        pipe.validate(model_cfg, trainer_cfg.batch_size)
        unsupported = {
            # grad accumulation IS the GPipe schedule: n_microbatches
            # already splits the batch; a second accumulation layer
            # would just change the schedule's own knob.
            "grad_accum": trainer_cfg.grad_accum != 1,
        }
        bad = [k for k, v in unsupported.items() if v]
        if bad:
            raise NotImplementedError(
                f"PipelineTrainer does not implement TrainerConfig "
                f"fields {bad}; unset them (the flax Trainer supports "
                "them all)"
            )
        self.model_cfg = model_cfg
        self.pipe = pipe
        self.cfg = trainer_cfg
        self.mesh = build_mesh(mesh_cfg)
        self.tx = tx or default_optimizer(
            lr=trainer_cfg.lr,
            warmup_steps=trainer_cfg.warmup_steps,
            total_steps=trainer_cfg.total_steps,
            mu_dtype=trainer_cfg.adam_mu_dtype,
        )
        self.state: PipeTrainState | None = None
        self._step_fn = None
        self._eval_fn = None
        self.preempted = False
        # TuneResult of the last apply_autotune (tpufw.tune.runner);
        # None until cfg.autotune resolves in run().
        self.last_tune = None
        from tpufw.obs import Telemetry

        self.telemetry = Telemetry.disabled()

    # -- state ---------------------------------------------------------

    def _init_fn(self, key):
        """ONE init body for both the abstract (restore-target) and real
        state so the two can never diverge."""
        params = init_pipeline_params(key, self.model_cfg, self.pipe)
        return PipeTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self.tx.init(params),
        )

    def _abstract_state(self) -> PipeTrainState:
        return jax.eval_shape(self._init_fn, jax.random.key(0))

    def _state_shardings(self, abstract: PipeTrainState) -> PipeTrainState:
        p_sh = pipeline_param_shardings(
            self.mesh, abstract.params,
            virtual=self.pipe.virtual_layout,
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        # Optimizer moments mirror the params they track. optax state
        # trees interleave param-shaped moment trees with scalars, so
        # match by FULL shape against the stage stacks — every stage
        # stack is >=3-D with a distinct shape, so a collision would
        # need an identically-shaped replicated tensor (none exist).
        # The looked-up sharding is the param's own (pipe + tensor
        # split), so pp x tp moments shard exactly like their weights.
        stage_sharding_by_shape = {
            tuple(x.shape): s
            for x, s in zip(
                jax.tree.leaves(abstract.params["stages"]),
                jax.tree.leaves(p_sh["stages"]),
            )
        }

        def opt_shard(leaf):
            if hasattr(leaf, "shape"):
                hit = stage_sharding_by_shape.get(tuple(leaf.shape))
                if hit is not None:
                    return hit
            return rep

        return PipeTrainState(
            step=rep,
            params=p_sh,
            opt_state=jax.tree.map(opt_shard, abstract.opt_state),
        )

    def init_state(self, seed: int = 0) -> PipeTrainState:
        shardings = self._state_shardings(self._abstract_state())
        self.state = jax.jit(self._init_fn, out_shardings=shardings)(
            jax.random.key(seed)
        )
        self._shardings = shardings
        return self.state

    def maybe_restore(self) -> bool:
        if not self.cfg.checkpoint_dir:
            return False
        from tpufw.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(self.cfg.checkpoint_dir)
        try:
            if mgr.latest_step() is None:
                return False
            abstract = self._abstract_state()
            shardings = self._state_shardings(abstract)
            target = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(
                    x.shape, x.dtype, sharding=s
                ),
                abstract,
                shardings,
                is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
            )
            self.state = mgr.restore(target)
            self._shardings = shardings
            return True
        finally:
            mgr.close()

    # -- loop ----------------------------------------------------------

    def _chunk_dtype(self):
        return (
            jnp.dtype(self.cfg.loss_chunk_dtype)
            if self.cfg.loss_chunk_size
            else None
        )

    def _batch_shardings(self, key) -> dict:
        """Batch-major row sharding over data x fsdp — ONE definition so
        the train and eval jits cannot disagree on batch layout."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        row = NamedSharding(self.mesh, P(("data", "fsdp")))
        return {k: row for k in key}

    def _compiled_step(self, batch: dict):
        key = tuple(sorted(batch.keys()))
        if self._step_fn is None:
            self._step_fn = {}
        if key not in self._step_fn:
            batch_sh = self._batch_shardings(key)
            self._step_fn[key] = jax.jit(
                partial(
                    _pipe_state_step,
                    tx=self.tx,
                    model_cfg=self.model_cfg,
                    pipe=self.pipe,
                    mesh=self.mesh,
                    loss_chunk_size=self.cfg.loss_chunk_size,
                    loss_chunk_dtype=self._chunk_dtype(),
                ),
                in_shardings=(self._shardings, batch_sh),
                out_shardings=(self._shardings, None),
                donate_argnums=(0,),
            )
        return self._step_fn[key]

    def _compiled_eval(self, batch: dict):
        key = tuple(sorted(batch.keys()))
        if self._eval_fn is None:
            self._eval_fn = {}
        if key not in self._eval_fn:
            batch_sh = self._batch_shardings(key)
            eval_pipe, eval_fn = self.pipe, pipeline_eval
            if self.pipe.virtual_layout:
                # The forward-only eval path speaks the canonical
                # [S, lps] layout; regroup INSIDE the jit (a reshape +
                # one resharding collective, amortized per eval batch)
                # and run the vanilla schedule.
                from tpufw.parallel.pipeline import to_canonical_stages

                eval_pipe = dataclasses.replace(
                    self.pipe, schedule="gpipe", n_virtual=1
                )

                def eval_fn(params, batch, **kw):
                    params = dict(params)
                    params["stages"] = to_canonical_stages(
                        params["stages"], self.pipe.n_stages
                    )
                    return pipeline_eval(params, batch, **kw)

            self._eval_fn[key] = jax.jit(
                partial(
                    eval_fn,
                    cfg=self.model_cfg,
                    pipe=eval_pipe,
                    mesh=self.mesh,
                    loss_chunk_size=self.cfg.loss_chunk_size,
                    loss_chunk_dtype=self._chunk_dtype(),
                ),
                in_shardings=(self._shardings.params, batch_sh),
                out_shardings=None,
            )
        return self._eval_fn[key]

    def evaluate(
        self, data: Iterator[dict], n_batches: Optional[int] = None
    ) -> dict:
        """Token-weighted held-out loss + perplexity through the
        forward-only pipeline — same reporting surface as
        Trainer.evaluate, so curves are directly comparable."""
        if self.state is None:
            raise RuntimeError("evaluate() before init_state()/restore")
        from tpufw.train.trainer import globalize_batch, run_evaluation

        return run_evaluation(
            data,
            n_batches,
            lambda b: self._compiled_eval(b)(self.state.params, b),
            lambda b: globalize_batch(self.mesh, b),
        )

    def run(
        self,
        data: Iterator[dict],
        model_flops_per_token: float,
        on_metrics: Callable[[StepMetrics], None] | None = None,
        eval_data: Callable[[], Iterator[dict]] | None = None,
        on_eval: Callable[[dict], None] | None = None,
        shutdown: "GracefulShutdown | None" = None,
    ) -> list[StepMetrics]:
        owns_shutdown = False
        self.preempted = False
        from tpufw.obs import Telemetry

        tel = self.telemetry = Telemetry.create(
            telemetry_dir=self.cfg.telemetry_dir,
            metrics_port=self.cfg.metrics_port,
            straggler_factor=self.cfg.straggler_factor,
        )
        from tpufw.train.trainer import _mesh_label

        tel.set_run_info(
            backend=jax.default_backend(),
            mesh=_mesh_label(self.mesh),
            model=f"pipeline:{type(self.model_cfg).__name__}",
        )
        if self.cfg.autotune != "off":
            # Resolve BEFORE state init: a schedule winner changes the
            # stage layout ([S,...] vs [v,S,...]) the state is built in,
            # so tuning first skips the re-layout path entirely.
            from tpufw.tune.runner import apply_autotune

            with tel.tracer.span("tune"):
                apply_autotune(self, events=tel.events, perf=tel.perf)
        if self.state is None:
            self.init_state()
        if tel.perf.enabled:
            # programs.json keyed like the tune winner cache (same
            # discipline as Trainer.run).
            from tpufw.tune.runner import _trainer_cache_key

            tel.perf.set_key(_trainer_cache_key(self))
        tel.record_config(
            {
                "trainer": dataclasses.asdict(self.cfg),
                "pipeline": dataclasses.asdict(self.pipe),
            }
        )
        meter = Meter(
            tokens_per_step=self.cfg.batch_size * (self.cfg.seq_len - 1),
            flops_per_token=model_flops_per_token,
            n_chips=len(self.mesh.devices.flatten()),
            registry=tel.registry,
        )
        # Analytic schedule bubble for THIS run's (schedule, S, v, M)
        # — a constant, so one set at run start; the bench tier pairs
        # it with the measured value (docs/OBSERVABILITY.md).
        if tel.registry is not None:
            tel.registry.gauge(
                "tpufw_pipeline_bubble_fraction",
                "Analytic pipeline bubble fraction of the active schedule",
            ).set(self.pipe.bubble_fraction())
        ckpt = None
        if self.cfg.checkpoint_dir:
            from tpufw.train.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                self.cfg.checkpoint_dir,
                save_interval_steps=self.cfg.checkpoint_every,
                events=tel.events,
                tracer=tel.tracer,
            )
        from tpufw.train.trainer import globalize_batch

        from tpufw.obs.perf import resolve_profile_window
        from tpufw.train.preemption import checkpoint_stop, owned_shutdown
        from tpufw.utils.profiling import StepProfiler

        # TPUFW_PROFILE_STEPS=a:b overrides the config window (see
        # Trainer.run).
        prof = StepProfiler(
            *resolve_profile_window(
                self.cfg.profile_dir,
                self.cfg.profile_start,
                self.cfg.profile_stop,
                telemetry_dir=self.cfg.telemetry_dir,
            )
        )
        shutdown, owns_shutdown = owned_shutdown(
            shutdown,
            self.cfg.handle_preemption,
            self.cfg.preemption_sync_every,
            events=tel.events,
        )
        # Global step budget: a restored run finishes the remainder.
        start_step = int(self.state.step)
        remaining = max(0, self.cfg.total_steps - start_step)
        se = max(1, self.cfg.sync_every)
        window_n, window_wait = 0, 0.0
        history: list[StepMetrics] = []
        tel.events.emit(
            "run_start",
            workload="train_pipeline",
            start_step=start_step,
            total_steps=self.cfg.total_steps,
            batch_size=self.cfg.batch_size,
            seq_len=self.cfg.seq_len,
            sync_every=se,
            n_chips=len(self.mesh.devices.flatten()),
        )

        def record_window(py_step, loss):
            # Same shape as Trainer.run's: meter.stop (the float(loss)
            # barrier) + step event + skew allgather, all on the one
            # host sync per window.
            with tel.tracer.span("host_sync"):
                sm = meter.stop(
                    py_step, loss,
                    data_wait_s=window_wait, n_steps=window_n,
                )
                tel.events.emit(
                    "step",
                    step=sm.step,
                    loss=round(sm.loss, 6),
                    step_time_s=round(sm.step_time_s, 6),
                    data_wait_s=round(sm.data_wait_s, 6),
                    mfu=round(sm.mfu, 5),
                    tokens_per_sec_per_chip=round(
                        sm.tokens_per_sec_per_chip, 1
                    ),
                    window_steps=sm.window_steps,
                )
                if tel.skew is not None:
                    tel.skew.record(
                        sm.step,
                        sm.step_time_s * sm.window_steps,
                        sm.data_wait_s,
                    )
                # Average per-tick wall of this window, derived
                # host-side (the scan's ticks run inside the jit where
                # the host tracer cannot see them). Against the chip
                # profile this localizes schedule stalls to a tick
                # budget without an XProf round trip.
                tel.tracer.complete(
                    "pipeline_tick",
                    sm.step_time_s / max(1, self.pipe.n_ticks()),
                )
                # Static FLOPs x measured wall -> per-program MFU
                # (tpufw_program_mfu) and roofline attribution.
                tel.perf.record_wall("pipeline_step", sm.step_time_s)
            return sm

        try:
            for i, (wait, batch) in enumerate(timed_batches(data)):
                if i >= remaining:
                    break
                tel.tracer.complete("data_fetch", wait)
                # Watchdog window: dispatch through host sync (same
                # contract as Trainer.run — see the comment there).
                tel.watchdog.arm()
                with tel.tracer.span("step_dispatch"):
                    prof.maybe_start(i)
                    if window_n == 0:
                        meter.start()
                    batch = globalize_batch(self.mesh, batch)
                    step_fn = self._compiled_step(batch)
                    # Cost harvest (first time per program only):
                    # abstract lower, so donation is untouched.
                    tel.perf.observe_jit(
                        "pipeline_step", step_fn, (self.state, batch)
                    )
                    with prof.step(i):
                        self.state, m = step_fn(self.state, batch)
                        window_n += 1
                        window_wait += wait
                        py_step = start_step + i + 1
                        # Step 1, multiples of sync_every, and the last.
                        sync = (
                            i == 0
                            or py_step % se == 0
                            or i + 1 == remaining
                        )
                        if sync:
                            loss = m["loss"]  # Meter.stop float()s it: the barrier
                    prof.maybe_stop(i)
                if not sync:
                    tel.watchdog.disarm()
                    continue
                sm = record_window(py_step, loss)
                tel.watchdog.disarm()
                window_n, window_wait = 0, 0.0
                history.append(sm)
                if on_metrics and (
                    se > 1 or i % self.cfg.log_every == 0
                ):
                    on_metrics(sm)
                with tel.tracer.span("eval"):
                    maybe_inloop_eval(self, py_step, eval_data, on_eval)
                if ckpt is not None:
                    with tel.tracer.span("checkpoint"):
                        ckpt.save(py_step, self.state)
                # Gang-consistent preemption stop (tpufw.train.preemption).
                with tel.tracer.span("preemption_sync"):
                    stop = checkpoint_stop(
                        shutdown, ckpt, py_step, self.state,
                        watchdog=tel.watchdog,
                    )
                if stop:
                    self.preempted = True
                    tel.events.emit(
                        "preemption_stop", level="warn", step=py_step
                    )
                    break
            # Iterator exhausted mid-window: flush the open window.
            if window_n:
                loss = m["loss"]  # Meter.stop float()s it: the barrier
                tel.watchdog.arm()
                sm = record_window(py_step, loss)
                tel.watchdog.disarm()
                history.append(sm)
                if on_metrics:
                    on_metrics(sm)
                if ckpt is not None:
                    with tel.tracer.span("checkpoint"):
                        ckpt.save(py_step, self.state)
        finally:
            prof.close()
            if ckpt is not None:
                ckpt.wait()
                ckpt.close()
            if owns_shutdown:
                shutdown.uninstall()
            tel.events.emit(
                "run_end",
                steps=len(history),
                last_step=history[-1].step if history else start_step,
                preempted=self.preempted,
            )
            tel.close()
        return history
