"""Input pipelines: synthetic LM batches and packed token streams.

The reference has no data path (nothing to feed ``nvidia-smi``); training
configs need one. Synthetic data is the benchmarking default (zero-IO,
deterministic); the packed stream handles real tokenized corpora with
sequence packing + segment ids so no FLOPs are spent on padding.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def synthetic_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    n_batches: Optional[int] = None,
) -> Iterator[dict]:
    """Deterministic random-token batches, generated host-side with numpy so
    device compute is purely the model (what a benchmark wants)."""
    rng = np.random.default_rng(seed)
    i = 0
    while n_batches is None or i < n_batches:
        yield {
            "tokens": rng.integers(
                0, vocab_size, (batch_size, seq_len), dtype=np.int32
            )
        }
        i += 1


def synthetic_packed_batches(
    batch_size: int,
    seq_len: int,
    vocab_size: int,
    seed: int = 0,
    mean_doc_len: int = 512,
    n_batches: Optional[int] = None,
) -> Iterator[dict]:
    """Synthetic PACKED batches: random docs of geometric length packed via
    ``pack_documents`` — the production data shape (segment_ids +
    loss_mask) without IO, so the bench can measure the packed/flash path
    (VERDICT r1 item 2: the measured number and the production path must
    not diverge)."""
    rng = np.random.default_rng(seed)

    def docs():
        while True:
            n = 1 + min(rng.geometric(1.0 / mean_doc_len), 4 * mean_doc_len)
            yield rng.integers(0, vocab_size, (n,), dtype=np.int32)

    it = pack_documents(docs(), batch_size, seq_len)
    for i, batch in enumerate(it):
        if n_batches is not None and i >= n_batches:
            break
        yield batch


def _emit(batch_toks: list, batch_segs: list, batch_train: list) -> dict:
    segs = np.array(batch_segs, np.int32)
    return {
        "tokens": np.array(batch_toks, np.int32),
        "segment_ids": segs,
        "loss_mask": (
            (segs > 0).astype(np.float32)
            * np.array(batch_train, np.float32)
        ),
    }


def pack_documents(
    docs: Iterator,
    batch_size: int,
    seq_len: int,
    pad_id: int = 0,
) -> Iterator[dict]:
    """Pack variable-length token docs into fixed [B, T] batches.

    Emits ``tokens``, ``segment_ids`` (per-doc ids so attention can't cross
    documents — wired to the model's segment masking), and ``loss_mask``
    (0 on padding). Documents longer than T are split; no tokens dropped.

    ``docs`` yields token arrays, or ``(tokens, train_mask)`` pairs for
    objectives that train on a SUBSET of each document's positions (SFT:
    assistant turns only — tpufw.train.sft); the per-token mask rides
    the packing with its tokens and lands in ``loss_mask``.
    """
    row_tokens: list[int] = []
    row_segs: list[int] = []
    row_train: list[float] = []
    seg = 1
    batch_toks, batch_segs, batch_train = [], [], []

    def flush_row():
        nonlocal row_tokens, row_segs, row_train, seg
        pad = seq_len - len(row_tokens)
        batch_toks.append(row_tokens + [pad_id] * pad)
        batch_segs.append(row_segs + [0] * pad)
        batch_train.append(row_train + [0.0] * pad)
        row_tokens, row_segs, row_train = [], [], []
        seg = 1

    for doc in docs:
        if isinstance(doc, tuple):
            doc, train = doc
            train = list(np.asarray(train, np.float32))
        else:
            train = None
        doc = list(np.asarray(doc, dtype=np.int32))
        if train is None:
            train = [1.0] * len(doc)
        elif len(train) != len(doc):
            raise ValueError(
                f"train_mask length {len(train)} != doc length {len(doc)}"
            )
        while doc:
            space = seq_len - len(row_tokens)
            take, doc = doc[:space], doc[space:]
            row_tokens.extend(take)
            row_train.extend(train[:space])
            train = train[space:]
            row_segs.extend([seg] * len(take))
            seg += 1
            if len(row_tokens) == seq_len:
                flush_row()
            if len(batch_toks) == batch_size:
                yield _emit(batch_toks, batch_segs, batch_train)
                batch_toks, batch_segs, batch_train = [], [], []
    if row_tokens:
        flush_row()
    if batch_toks:
        while len(batch_toks) < batch_size:
            batch_toks.append([pad_id] * seq_len)
            batch_segs.append([0] * seq_len)
            batch_train.append([0.0] * seq_len)
        yield _emit(batch_toks, batch_segs, batch_train)
