"""GRPO: Group Relative Policy Optimization (RL fine-tuning).

The reference ships no ML workloads at all (its "workload" is a
diagnostic CLI, reference README.md:314); GRPO is the on-policy RL
stage that completes the post-training suite (SFT -> DPO/distill ->
RL), using the critic-free group baseline of DeepSeekMath/R1: sample
``group_size`` completions per prompt, score them with a user reward
function, and normalize rewards WITHIN each prompt's group into
advantages — no value network, which on TPU means no second model to
shard or train.

TPU-first shape discipline:
- Rollout rows are RIGHT-padded [N, T] (prompt at position 0), so the
  scoring/training forward's default absolute positions match the RoPE
  positions the decode cache used at generation time exactly.
- Per-token log-probs come from ``chunked_token_logprob``
  (tpufw.ops.loss): the [B, C, V] chunk logits are never kept, the
  [N, T] fp32 ratio inputs are tiny.
- The generation itself is the existing jitted KV-cache scan
  (tpufw.infer.generate) — one compiled program per rollout shape.

Objective (clipped importance ratio, sequence-level group advantage,
optional k3 KL penalty to the frozen reference):

  ratio_t = exp(logpi(y_t) - logpi_old(y_t))
  obj_t   = min(ratio_t * A, clip(ratio_t, 1-eps, 1+eps) * A)
  kl_t    = exp(ref_t - pol_t) - (ref_t - pol_t) - 1        # k3, >= 0
  loss    = -mean_completion_tokens(obj_t - kl_beta * kl_t)

Anchor invariant (tests/test_grpo.py): immediately after a rollout the
policy equals the old policy, so every ratio is exactly 1 and the
clipped min() is inactive; and each group's advantages sum to ~0.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpufw.ops.loss import chunked_token_logprob
from tpufw.train.trainer import Trainer, frozen_copy, head_kernel


@dataclasses.dataclass(frozen=True)
class GRPOConfig:
    # Completions sampled per prompt; advantages normalize within the
    # group. 4-16 is the common range.
    group_size: int = 8
    # PPO-style ratio clip.
    clip_eps: float = 0.2
    # k3-KL penalty weight to the frozen reference; 0 disables the
    # reference forward entirely.
    kl_beta: float = 0.0
    # Rollout sampling temperature (0 would collapse the group).
    temperature: float = 1.0
    # Generated tokens per completion.
    max_new_tokens: int = 64
    # Storage dtype of the frozen reference weights (kl_beta > 0).
    ref_dtype: str = "bfloat16"
    # Stop-token for completions (mask ends at the first EOS,
    # inclusive); None = fixed-length completions.
    eos_id: Optional[int] = None


def group_advantages(
    rewards: np.ndarray, group_size: int, eps: float = 1e-6
) -> np.ndarray:
    """[N] rewards (rows grouped CONTIGUOUSLY: rows [i*K, (i+1)*K) are
    prompt i's K completions) -> [N] group-normalized advantages
    (r - mean_group) / (std_group + eps). A group with identical
    rewards gets advantage 0 — no learning signal, by design."""
    r = np.asarray(rewards, np.float32)
    if r.ndim != 1 or r.shape[0] % group_size:
        raise ValueError(
            f"rewards shape {r.shape} not divisible into groups of "
            f"{group_size}"
        )
    g = r.reshape(-1, group_size)
    adv = (g - g.mean(axis=1, keepdims=True)) / (
        g.std(axis=1, keepdims=True) + eps
    )
    return adv.reshape(-1)


def grpo_train_step(
    state,
    ref_params,
    batch: dict,
    clip_eps: float = 0.2,
    kl_beta: float = 0.0,
    temperature: float = 1.0,
    loss_chunk_size: int = 256,
    loss_chunk_dtype: str = "bfloat16",
    final_logit_soft_cap: Optional[float] = None,
):
    """One GRPO update on a rollout batch.

    batch: tokens [N, T] (right-padded prompt+completion),
    loss_mask [N, T] (1 on COMPLETION tokens), segment_ids [N, T],
    old_logp [N, T-1] (per-TARGET log-probs under the rollout policy),
    advantages [N]. ``ref_params`` may be None when kl_beta == 0.

    ``temperature`` must be the ROLLOUT sampling temperature: the
    behavior policy the tokens were drawn from is
    softmax(logits / temperature), so the importance ratios (and the
    KL) are computed on the SAME tempered distribution — untempered
    ratios would anchor at 1 but weight the objective by a
    distribution nobody sampled from.
    """
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    seg_in = batch["segment_ids"][:, :-1]
    # Target-position mask, the LM shift convention (trainer.py
    # shift_and_mask): a target position trains iff the PREDICTED token
    # is a completion token.
    mask = batch["loss_mask"][:, 1:].astype(jnp.float32)
    old_logp = batch["old_logp"]
    adv = batch["advantages"][:, None].astype(jnp.float32)
    dtype = jnp.dtype(loss_chunk_dtype)

    def token_logps(params):
        out = state.apply_fn(
            {"params": params}, inputs, segment_ids=seg_in,
            return_hidden=True,
        )
        aux = 0.0
        if isinstance(out, tuple):
            out, aux = out
        logp = chunked_token_logprob(
            out, head_kernel(params), targets,
            chunk_size=loss_chunk_size, compute_dtype=dtype,
            logits_soft_cap=final_logit_soft_cap,
            logits_scale=1.0 / temperature,
        )
        return logp, aux

    ref_logp = None
    if kl_beta > 0.0:
        ref_logp, _ = token_logps(ref_params)
        ref_logp = jax.lax.stop_gradient(ref_logp)

    n = jnp.maximum(mask.sum(), 1.0)

    def lf(params):
        logp, aux = token_logps(params)
        ratio = jnp.exp(logp - old_logp)
        clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps)
        obj = jnp.minimum(ratio * adv, clipped * adv)
        if ref_logp is not None:
            d = ref_logp - logp
            kl = jnp.exp(d) - d - 1.0  # k3 estimator, >= 0
            obj = obj - kl_beta * kl
            kl_mean = (kl * mask).sum() / n
        else:
            kl_mean = jnp.zeros((), jnp.float32)
        loss = -(obj * mask).sum() / n
        # Fraction of tokens where the clip BINDS (the clipped term is
        # the smaller one the min() picks).
        clip_frac = ((clipped * adv < ratio * adv) * mask).sum() / n
        return loss + aux, (ratio, kl_mean, clip_frac)

    (loss, (ratio, kl_mean, clip_frac)), grads = jax.value_and_grad(
        lf, has_aux=True
    )(state.params)
    new_state = state.apply_gradients(grads)
    return new_state, {
        "loss": loss,
        "grad_norm": optax.global_norm(grads),
        "mean_ratio": (ratio * mask).sum() / n,
        "clip_frac": clip_frac,
        "kl": kl_mean,
    }


class GRPOTrainer(Trainer):
    """Trainer specialized for GRPO rollouts + updates.

    ``TrainerConfig.batch_size`` must equal prompts_per_step *
    group_size (the rollout row count N); ``TrainerConfig.seq_len``
    bounds prompt + max_new_tokens. The RL loop is explicit
    (``rollout`` then the compiled step) because data depends on the
    current policy — see ``run_rl`` for the packaged loop.
    """

    def __init__(
        self,
        model,
        trainer_cfg,
        mesh_cfg=None,
        mesh=None,
        tx=None,
        grpo: GRPOConfig = GRPOConfig(),
    ):
        super().__init__(model, trainer_cfg, mesh_cfg, mesh, tx)
        if trainer_cfg.batch_size % grpo.group_size:
            raise ValueError(
                f"batch_size {trainer_cfg.batch_size} must be a "
                f"multiple of group_size {grpo.group_size}"
            )
        if trainer_cfg.grad_accum != 1:
            raise NotImplementedError(
                "GRPO does not implement grad_accum: microbatch "
                "slicing would split a prompt's group across updates"
            )
        self.grpo = grpo
        self.ref_params = None
        self._decode_model = None
        self._score_fn = None

    # -- reference ---------------------------------------------------------

    def _snapshot_reference(self):
        self.ref_params = frozen_copy(
            self.state.params, jnp.dtype(self.grpo.ref_dtype)
        )

    def init_state(self, seed: int = 0):
        out = super().init_state(seed)
        if self.grpo.kl_beta > 0.0:
            self._snapshot_reference()
        return out

    def init_from_params(self, path: str, seed: int = 0):
        out = super().init_from_params(path, seed)
        if self.grpo.kl_beta > 0.0:
            self._snapshot_reference()
        return out

    def maybe_restore(self) -> bool:
        """Mid-run resume: the restored POLICY must not become the KL
        reference (same contract as DPOTrainer.maybe_restore) — with
        kl_beta > 0 a reference snapshotted from the pre-restore init
        would anchor the penalty to random weights."""
        restored = super().maybe_restore()
        if (
            self.grpo.kl_beta > 0.0
            and restored
            and int(self.state.step) > 0
            and self.ref_params is None
        ):
            raise RuntimeError(
                "resumed a GRPO run mid-training with kl_beta > 0 and "
                "no KL reference: call init_from_params on the "
                "ORIGINAL base checkpoint BEFORE maybe_restore so the "
                "reference anchors to step-0 weights"
            )
        return restored

    # -- rollout -----------------------------------------------------------

    def _decode(self):
        if self._decode_model is None:
            cfg = dataclasses.replace(
                self.model.cfg.decode_config(),
                max_seq_len=self.cfg.seq_len,
            )
            self._decode_model = type(self.model)(cfg)
        return self._decode_model

    def _score(self, tokens, seg):
        """Per-target log-probs of ``tokens`` under CURRENT params —
        the rollout policy snapshot the ratios divide by."""
        if self._score_fn is None:
            from functools import partial

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            row = NamedSharding(self.mesh, P(("data", "fsdp")))

            def score(params, tokens, seg):
                out = self.model.apply(
                    {"params": params},
                    tokens[:, :-1],
                    segment_ids=seg[:, :-1],
                    return_hidden=True,
                )
                if isinstance(out, tuple):
                    out = out[0]
                # Tempered like the sampler: old_logp must be the
                # behavior policy's distribution (see grpo_train_step).
                return chunked_token_logprob(
                    out, head_kernel(params), tokens[:, 1:],
                    chunk_size=self.cfg.loss_chunk_size or 256,
                    compute_dtype=jnp.dtype(self.cfg.loss_chunk_dtype),
                    logits_soft_cap=self._final_soft_cap(),
                    logits_scale=1.0 / self.grpo.temperature,
                )

            self._score_fn = jax.jit(
                score,
                in_shardings=(self.state_sharding.params, row, row),
                out_shardings=None,
            )
        return self._score_fn(self.state.params, tokens, seg)

    def rollout(
        self,
        prompts: Sequence[Sequence[int]],
        reward_fn: Callable[[List[List[int]], List[List[int]]], np.ndarray],
        rng: jax.Array,
    ) -> tuple[dict, dict]:
        """Sample group_size completions per prompt, score rewards, and
        assemble one training batch.

        ``reward_fn(prompt_tokens, completion_tokens) -> [N] rewards``
        receives python token lists (N = len(prompts) * group_size,
        completions truncated at EOS when configured); decoding to text
        is the caller's concern.

        Returns (batch, info): batch feeds ``compiled_step``; info has
        host-side rollout metrics (mean/max reward, completion length).
        """
        from tpufw.infer import SamplingConfig, generate, pad_prompts

        if self.state is None:
            raise RuntimeError("rollout() before init_state()/restore")
        g = self.grpo
        n = len(prompts) * g.group_size
        if n != self.cfg.batch_size:
            raise ValueError(
                f"{len(prompts)} prompts x group {g.group_size} = {n} "
                f"rows != batch_size {self.cfg.batch_size}"
            )
        max_p = max(len(p) for p in prompts)
        if max_p + g.max_new_tokens > self.cfg.seq_len:
            raise ValueError(
                f"prompt ({max_p}) + max_new_tokens "
                f"({g.max_new_tokens}) exceeds seq_len {self.cfg.seq_len}"
            )
        tiled = [list(p) for p in prompts for _ in range(g.group_size)]
        ptoks, pads = pad_prompts(tiled)
        # Left-pad to the FIXED width seq_len - max_new: the decode scan
        # is jitted on prompt shape, and padding only to the batch max
        # would recompile for every distinct window of a ragged prompt
        # set (multi-minute server-side compiles on real chips).
        fixed_p = self.cfg.seq_len - g.max_new_tokens
        if ptoks.shape[1] < fixed_p:
            extra = fixed_p - ptoks.shape[1]
            ptoks = np.pad(ptoks, ((0, 0), (extra, 0)))
            pads = pads + extra
        completions = np.asarray(
            generate(
                self._decode(),
                self.state.params,
                jnp.asarray(ptoks),
                jnp.asarray(pads),
                rng,
                max_new_tokens=g.max_new_tokens,
                sampling=SamplingConfig(temperature=g.temperature),
                eos_id=g.eos_id,
            )
        )

        # Right-padded training rows: prompt at position 0 (absolute
        # positions then match the decode-time RoPE positions).
        t = self.cfg.seq_len
        tokens = np.zeros((n, t), np.int32)
        loss_mask = np.zeros((n, t), np.float32)
        seg = np.zeros((n, t), np.int32)
        comp_lists: List[List[int]] = []
        for i, p in enumerate(tiled):
            comp = completions[i].tolist()
            if g.eos_id is not None and g.eos_id in comp:
                comp = comp[: comp.index(g.eos_id) + 1]
            comp_lists.append(comp)
            row = p + comp
            tokens[i, : len(row)] = row
            seg[i, : len(row)] = 1
            loss_mask[i, len(p): len(row)] = 1.0

        rewards = np.asarray(reward_fn(tiled, comp_lists), np.float32)
        adv = group_advantages(rewards, g.group_size)
        old_logp = np.asarray(self._score(tokens, seg), np.float32)
        batch = {
            "tokens": tokens,
            "loss_mask": loss_mask,
            "segment_ids": seg,
            "old_logp": old_logp,
            "advantages": adv,
        }
        info = {
            "reward_mean": float(rewards.mean()),
            "reward_max": float(rewards.max()),
            "completion_len_mean": float(
                np.mean([len(c) for c in comp_lists])
            ),
        }
        return batch, info

    # -- step --------------------------------------------------------------

    def compiled_step(self, batch: dict | None = None):
        from functools import partial

        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if self.grpo.kl_beta > 0.0 and self.ref_params is None:
            raise RuntimeError(
                "GRPO step with kl_beta > 0 before the reference "
                "snapshot: call init_state()/init_from_params() first"
            )
        key = (
            (
                "grpo", "advantages", "loss_mask", "old_logp",
                "segment_ids", "tokens",
            )
            if batch is None
            else ("grpo", *sorted(batch.keys()))
        )
        if key not in self._compiled:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in key[1:]}
            step = partial(
                grpo_train_step,
                clip_eps=self.grpo.clip_eps,
                kl_beta=self.grpo.kl_beta,
                temperature=self.grpo.temperature,
                loss_chunk_size=self.cfg.loss_chunk_size or 256,
                loss_chunk_dtype=self.cfg.loss_chunk_dtype,
                final_logit_soft_cap=self._final_soft_cap(),
            )
            if self.grpo.kl_beta > 0.0:
                jitted = jax.jit(
                    step,
                    in_shardings=(
                        self.state_sharding,
                        self.state_sharding.params,
                        batch_sharding,
                    ),
                    out_shardings=(self.state_sharding, None),
                    donate_argnums=(0,),
                )
                self._compiled[key] = lambda state, b: jitted(
                    state, self.ref_params, b
                )
            else:
                # ref_params=None (an empty pytree): never pass the
                # donated state's own params as a dead argument — that
                # would be a use-after-donate at execution.
                jitted = jax.jit(
                    lambda state, b: step(state, None, b),
                    in_shardings=(self.state_sharding, batch_sharding),
                    out_shardings=(self.state_sharding, None),
                    donate_argnums=(0,),
                )
                self._compiled[key] = jitted
        return self._compiled[key]

    def run_rl(
        self,
        prompts,
        reward_fn,
        seed: int = 0,
        on_metrics: Callable[[dict], None] | None = None,
    ) -> list[dict]:
        """The packaged RL loop: total_steps x (rollout -> update).
        ``prompts`` is either a fixed prompt set (every step) or a
        callable ``step_index -> prompt set`` (rotation/curriculum).
        Returns per-step metric dicts (rollout info + step metrics).
        The policy the i-th rollout samples from is the
        (i-1)-times-updated one — on-policy by construction."""
        if self.state is None:
            self.init_state()
        from tpufw.parallel.context import use_mesh
        from tpufw.train.preemption import checkpoint_stop, owned_shutdown

        get_prompts = prompts if callable(prompts) else (lambda i: prompts)
        ckpt = None
        if self.cfg.checkpoint_dir:
            from tpufw.train.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                self.cfg.checkpoint_dir,
                save_interval_steps=self.cfg.checkpoint_every,
            )
        shutdown, owns_shutdown = owned_shutdown(
            None,
            self.cfg.handle_preemption,
            self.cfg.preemption_sync_every,
        )
        self.preempted = False
        # Same global-step-budget contract as Trainer.run: a restored
        # run finishes the remaining steps.
        start_step = int(self.state.step)
        remaining = max(0, self.cfg.total_steps - start_step)
        history = []
        rngs = jax.random.split(
            jax.random.key(seed), self.cfg.total_steps
        )
        try:
            with use_mesh(self.mesh):
                for i in range(remaining):
                    step_i = start_step + i
                    batch, info = self.rollout(
                        get_prompts(step_i), reward_fn, rngs[step_i]
                    )
                    batch = self.globalize_batch(batch)
                    step_fn = self.compiled_step(batch)
                    self.state, m = step_fn(self.state, batch)
                    py_step = step_i + 1
                    entry = {
                        **info,
                        **{k: float(v) for k, v in m.items()},
                        "step": py_step,
                    }
                    history.append(entry)
                    if on_metrics:
                        on_metrics(entry)
                    if ckpt is not None:
                        ckpt.save(py_step, self.state)
                    # SIGTERM (pod preemption): forced checkpoint, clean
                    # break — the JobSet restart resumes via
                    # maybe_restore (gang-consistent, preemption.py).
                    if checkpoint_stop(
                        shutdown, ckpt, py_step, self.state
                    ):
                        self.preempted = True
                        break
        finally:
            if ckpt is not None:
                ckpt.wait()
                ckpt.close()
            if owns_shutdown:
                shutdown.uninstall()
        return history
