"""Native token-corpus loader: ctypes over libtpufwdata.so (native/).

The C++ packer (native/dataloader) walks an mmap'd corpus and fills
preallocated numpy buffers — the per-doc packing loop never runs in
Python. Falls back to the pure-Python ``pack_documents`` pipeline when the
native library isn't built, so tests and dev boxes work either way. With
``shuffle=False`` the two paths are bit-identical (pinned by
tests/test_native_data.py); with ``shuffle=True`` the permutations differ
(splitmix64 vs numpy) — a warning is logged because data ORDER then
depends on which path loaded.

Corpus layout (<prefix>.bin / <prefix>.idx) is documented in
native/dataloader/dataloader.h; ``write_token_corpus`` produces it.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Sequence

import numpy as np

_DEFAULT_LIB_CANDIDATES = (
    os.path.join(
        os.path.dirname(__file__), "..", "..", "build-native",
        "libtpufwdata.so",
    ),
    "/opt/tpufw/libtpufwdata.so",
)


def write_token_corpus(
    prefix: str, docs: Sequence[Sequence[int]]
) -> tuple[str, str]:
    """Write docs as <prefix>.bin (uint32 tokens) + <prefix>.idx (uint64
    doc-start offsets, n_docs+1 entries). Returns the two paths."""
    bin_path, idx_path = prefix + ".bin", prefix + ".idx"
    offsets = [0]
    with open(bin_path, "wb") as f:
        for d in docs:
            arr = np.asarray(d, np.uint32)
            f.write(arr.tobytes())
            offsets.append(offsets[-1] + arr.size)
    np.asarray(offsets, np.uint64).tofile(idx_path)
    return bin_path, idx_path


def _load_lib(path: Optional[str] = None) -> Optional[ctypes.CDLL]:
    candidates = [path] if path else [
        os.environ.get("TPUFWDATA_LIB"), *_DEFAULT_LIB_CANDIDATES
    ]
    for c in candidates:
        if c and os.path.exists(c):
            lib = ctypes.CDLL(os.path.abspath(c))
            lib.tpufwdata_open.restype = ctypes.c_void_p
            lib.tpufwdata_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.tpufwdata_close.argtypes = [ctypes.c_void_p]
            lib.tpufwdata_error.restype = ctypes.c_char_p
            lib.tpufwdata_n_docs.restype = ctypes.c_uint64
            lib.tpufwdata_n_docs.argtypes = [ctypes.c_void_p]
            lib.tpufwdata_n_tokens.restype = ctypes.c_uint64
            lib.tpufwdata_n_tokens.argtypes = [ctypes.c_void_p]
            lib.tpufwdata_begin_epoch.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ]
            lib.tpufwdata_next_batch.restype = ctypes.c_int
            lib.tpufwdata_next_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_float),
            ]
            return lib
    return None


class TokenCorpus:
    """Iterator factory over a packed token corpus.

    ``epochs=None`` streams forever (reshuffling per epoch when ``shuffle``);
    an integer stops after that many passes — mirrors what the trainer's
    ``total_steps`` expects either way.
    """

    def __init__(
        self,
        prefix: str,
        batch_size: int,
        seq_len: int,
        shuffle: bool = False,
        seed: int = 0,
        epochs: Optional[int] = None,
        lib_path: Optional[str] = None,
        shard_id: int = 0,
        num_shards: int = 1,
    ):
        self.prefix = prefix
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.shuffle = shuffle
        self.seed = seed
        self.epochs = epochs
        if not (0 <= shard_id < num_shards):
            raise ValueError(
                f"shard_id {shard_id} out of range for {num_shards} shards"
            )
        if num_shards > 1 and epochs is not None:
            # Round-robin doc sharding gives shards unequal token counts,
            # so finite epochs would end at different batch counts per
            # process — the early-exhausted host stops iterating while the
            # rest block in make_array_from_process_local_data, hanging the
            # gang. Stream forever (epochs=None) and bound by total_steps.
            raise ValueError(
                "num_shards > 1 requires epochs=None (stream + stop by "
                "trainer total_steps): finite epochs yield unequal batch "
                "counts across shards and deadlock multi-host gangs"
            )
        # Data-parallel hosts pass (process_id, process_count): each packs
        # a disjoint round-robin subset of the (post-shuffle) doc order.
        self.shard_id = shard_id
        self.num_shards = num_shards
        self._lib = _load_lib(lib_path)

    @property
    def native(self) -> bool:
        return self._lib is not None

    def __iter__(self) -> Iterator[dict]:
        if self._lib is not None:
            yield from self._iter_native()
        else:
            yield from self._iter_python()

    def _iter_native(self) -> Iterator[dict]:
        lib = self._lib
        handle = lib.tpufwdata_open(
            (self.prefix + ".bin").encode(), (self.prefix + ".idx").encode()
        )
        if not handle:
            raise FileNotFoundError(
                f"tpufwdata_open({self.prefix}): "
                f"{lib.tpufwdata_error().decode()}"
            )
        try:
            epoch = 0
            while self.epochs is None or epoch < self.epochs:
                lib.tpufwdata_begin_epoch(
                    handle, int(self.shuffle), self.seed, epoch,
                    self.shard_id, self.num_shards,
                )
                while True:
                    toks = np.empty(
                        (self.batch_size, self.seq_len), np.int32
                    )
                    segs = np.empty_like(toks)
                    mask = np.empty(
                        (self.batch_size, self.seq_len), np.float32
                    )
                    ok = lib.tpufwdata_next_batch(
                        handle, self.batch_size, self.seq_len,
                        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                        segs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                        mask.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    )
                    if not ok:
                        break
                    yield {
                        "tokens": toks,
                        "segment_ids": segs,
                        "loss_mask": mask,
                    }
                epoch += 1
        finally:
            lib.tpufwdata_close(handle)

    def _docs(self, epoch: int) -> Iterator[np.ndarray]:
        tokens = np.memmap(self.prefix + ".bin", np.uint32, "r")
        offsets = np.fromfile(self.prefix + ".idx", np.uint64)
        order = np.arange(len(offsets) - 1)
        if self.shuffle:
            # Note: python fallback shuffle order differs from native's
            # splitmix64 permutation; only shuffle=False is bit-identical.
            order = np.random.default_rng(
                (self.seed, epoch)
            ).permutation(order)
        order = order[self.shard_id::self.num_shards]
        for d in order:
            yield np.asarray(
                tokens[int(offsets[d]):int(offsets[d + 1])], np.int32
            )

    def _iter_python(self) -> Iterator[dict]:
        from tpufw.train.data import pack_documents

        if self.shuffle:
            import logging

            logging.getLogger("tpufw.data").warning(
                "libtpufwdata.so not found: python fallback shuffles in a "
                "DIFFERENT order than the native loader — runs are not "
                "reproducible across the two (build native/ to pin order)"
            )
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            yield from pack_documents(
                self._docs(epoch), self.batch_size, self.seq_len
            )
            epoch += 1
