"""Checkpoint/resume via Orbax — the recovery half of elastic training.

The reference's only recovery primitive is ``restartPolicy: OnFailure`` on its
test pod (reference ``README.md:309``); SURVEY.md §5 mandates the real thing
for the TPU build: gang-restarted JobSets only make sense if workers resume
from a recent checkpoint. Async saves keep serialization off the step path.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin wrapper over ocp.CheckpointManager for TrainState pytrees.

    Saves are async (background thread does the device-to-host + write);
    ``restore`` reshards directly onto the current mesh via the abstract
    target — a checkpoint written on one topology restores onto another,
    which is what makes slice-size changes and elastic restarts cheap.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        events=None,
        tracer=None,
    ):
        self.directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=True,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)
        # tpufw.obs event log (or None): save/restore decisions become
        # checkpoint_save / checkpoint_restore events, so a post-mortem
        # can line the save cadence up against step times and stragglers.
        if events is None:
            from tpufw.obs import events as events_mod

            events = events_mod.NULL
        self.events = events
        # tpufw.obs tracer (or the shared null): restore and the
        # async-save drain get their own spans — they happen OUTSIDE
        # the loop's ``checkpoint`` span (restore precedes the loop,
        # wait() runs in its finally), so without these the goodput
        # ledger would book them as idle.
        if tracer is None:
            from tpufw.obs import trace as trace_mod

            tracer = trace_mod.NULL
        self.tracer = tracer

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        # force=True is the preemption path ("make sure THIS step is on
        # disk"); if the periodic schedule already saved it, that's
        # satisfied — not an error.
        if force and step in self._mgr.all_steps():
            self.events.emit(
                "checkpoint_save", step=step, forced=force, saved=False
            )
            return False
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved or force:
            # Periodic non-saves (off-interval steps) are not events;
            # they would be one line per sync window of pure noise.
            self.events.emit(
                "checkpoint_save", step=step, forced=force, saved=bool(saved)
            )
        return saved

    def restore(self, abstract_state: Any, step: Optional[int] = None) -> Any:
        """Restore ``step`` (default: latest) sharded per ``abstract_state``
        (a jax.eval_shape pytree whose leaves carry .sharding)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        with self.tracer.span("checkpoint_restore", step=step):
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        self.events.emit("checkpoint_restore", step=step)
        return restored

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        with self.tracer.span("checkpoint_wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
