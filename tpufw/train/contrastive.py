"""Contrastive embedding fine-tuning: decoder LM -> retrieval encoder.

The reference ships no ML workloads at all (its "workload" is a
diagnostic CLI, reference README.md:314); embeddings are the retrieval
half real users build next to generation, and the modern recipe turns
the SAME decoder checkpoints this framework trains/imports into
encoders — E5-Mistral style (causal trunk, last-token pooling) or
LLM2Vec style (``cfg.causal=False`` flips the trunk bidirectional,
mean pooling). Both ride the existing substrate: the trunk's
``return_hidden`` output is pooled, L2-normalized, and trained with a
symmetric in-batch-negative InfoNCE.

TPU-first shape discipline: batches are ``[2B, T]`` with pairs
INTERLEAVED (row 2i = query, row 2i+1 = its positive document — the
same multi-process-safe layout as tpufw.train.dpo), one forward covers
queries and documents, and the similarity matrix is a single [B, B]
matmul over the GLOBAL batch — under data parallelism every device's
queries see every device's documents as negatives for free, because
the batch axis is sharded but the program is global (no gather code).

Anchor invariant (tests/test_contrastive.py): at random init the
similarity matrix is ~uniform, so loss ~= ln(B); training on
distinguishable pairs drives the diagonal accuracy to 1.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Callable, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tpufw.train.trainer import Trainer


@dataclasses.dataclass(frozen=True)
class ContrastiveConfig:
    # Softmax temperature on cosine similarities (0.02-0.1 typical).
    temperature: float = 0.05
    # "mean" over real tokens (bidirectional/LLM2Vec convention) or
    # "last" real token (causal/E5-Mistral convention).
    pooling: str = "mean"


def pool_embeddings(
    hidden: jax.Array, segment_ids: jax.Array, mode: str = "mean"
) -> jax.Array:
    """[B, T, D] hidden + [B, T] segment ids (0 = padding) -> [B, D].

    "mean": masked mean over real tokens. "last": the last REAL
    token's hidden state (rows are right-padded, so that is index
    n_real - 1)."""
    real = (segment_ids > 0).astype(hidden.dtype)
    if mode == "mean":
        n = jnp.maximum(real.sum(axis=1, keepdims=True), 1.0)
        return (hidden * real[..., None]).sum(axis=1) / n
    if mode == "last":
        idx = jnp.maximum(real.sum(axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(
            hidden, idx[:, None, None], axis=1
        )[:, 0]
    raise ValueError(f"unknown pooling {mode!r}; 'mean' or 'last'")


def info_nce_loss(
    q: jax.Array, d: jax.Array, temperature: float = 0.05
) -> tuple[jax.Array, dict]:
    """Symmetric in-batch-negative InfoNCE over L2-normalized
    embeddings. q/d: [B, D]; pair i is (q[i], d[i]), every other row is
    a negative. Returns (loss, metrics)."""
    q = q / jnp.linalg.norm(q, axis=-1, keepdims=True).clip(1e-6)
    d = d / jnp.linalg.norm(d, axis=-1, keepdims=True).clip(1e-6)
    sim = (q @ d.T).astype(jnp.float32) / temperature  # [B, B]
    labels = jnp.arange(sim.shape[0])
    # Both directions (query->doc and doc->query), the standard CLIP/
    # retrieval symmetric objective.
    loss = 0.5 * (
        optax.softmax_cross_entropy_with_integer_labels(
            sim, labels
        ).mean()
        + optax.softmax_cross_entropy_with_integer_labels(
            sim.T, labels
        ).mean()
    )
    acc = (sim.argmax(axis=-1) == labels).astype(jnp.float32).mean()
    metrics = {
        "accuracy": acc,
        "sim_pos": jnp.diag(sim).mean() * temperature,
        "sim_neg": (
            (sim.sum() - jnp.diag(sim).sum())
            / jnp.maximum(sim.size - sim.shape[0], 1)
        )
        * temperature,
    }
    return loss, metrics


def read_pairs(path: str | pathlib.Path) -> Iterator[dict]:
    """JSONL retrieval pairs: {"query": <text>, "positive": <text>}."""
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if not (
                isinstance(obj, dict)
                and isinstance(obj.get("query"), str)
                and isinstance(obj.get("positive"), str)
            ):
                raise ValueError(
                    f"{path}:{ln}: expected "
                    '{"query": str, "positive": str}'
                )
            yield obj


def _fit(toks: List[int], seq_len: int):
    toks = toks[:seq_len]
    out = np.zeros(seq_len, np.int32)
    seg = np.zeros(seq_len, np.int32)
    out[: len(toks)], seg[: len(toks)] = toks, 1
    return out, seg


def pair_batches(
    path: str | pathlib.Path,
    batch_pairs: int,
    seq_len: int,
    encode: Callable[[str], List[int]],
    epochs: Optional[int] = None,
    seed: int = 0,
    shard_id: int = 0,
    num_shards: int = 1,
) -> Iterator[dict]:
    """[2B, T] batches: row 2i = query i, row 2i+1 = its positive
    (right-padded/truncated; interleaving keeps multi-process block
    concatenation pair-aligned, the tpufw.train.dpo argument)."""
    pairs = list(read_pairs(path))
    if not pairs:
        raise ValueError(f"{path}: no pairs")
    pairs = pairs[shard_id::num_shards]
    encoded = [
        (encode(p["query"]), encode(p["positive"])) for p in pairs
    ]
    if len(encoded) < batch_pairs:
        raise ValueError(
            f"{path}: shard {shard_id}/{num_shards} holds "
            f"{len(encoded)} pairs < batch_pairs={batch_pairs}"
        )
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(len(encoded))
        for start in range(0, len(order) - batch_pairs + 1, batch_pairs):
            toks = np.zeros((2 * batch_pairs, seq_len), np.int32)
            seg = np.zeros((2 * batch_pairs, seq_len), np.int32)
            for row, i in enumerate(order[start:start + batch_pairs]):
                qt, dt = encoded[i]
                toks[2 * row], seg[2 * row] = _fit(qt, seq_len)
                toks[2 * row + 1], seg[2 * row + 1] = _fit(dt, seq_len)
            yield {"tokens": toks, "segment_ids": seg}
        epoch += 1


def contrastive_train_step(
    state,
    batch: dict,
    temperature: float = 0.05,
    pooling: str = "mean",
):
    """One InfoNCE update on a [2B, T] interleaved query/doc batch."""
    tokens = batch["tokens"]
    seg = batch["segment_ids"]

    def lf(params):
        out = state.apply_fn(
            {"params": params}, tokens, segment_ids=seg,
            return_hidden=True,
        )
        aux = 0.0
        if isinstance(out, tuple):
            out, aux = out
        emb = pool_embeddings(out.astype(jnp.float32), seg, pooling)
        loss, metrics = info_nce_loss(
            emb[0::2], emb[1::2], temperature
        )
        return loss + aux, metrics

    (loss, metrics), grads = jax.value_and_grad(lf, has_aux=True)(
        state.params
    )
    new_state = state.apply_gradients(grads)
    return new_state, {
        "loss": loss,
        "grad_norm": optax.global_norm(grads),
        **metrics,
    }


class EmbeddingTrainer(Trainer):
    """Trainer specialized for contrastive embedding fine-tuning.
    run()/checkpointing/preemption/metering are inherited;
    ``TrainerConfig.batch_size`` is the ROW count 2B."""

    def __init__(
        self,
        model,
        trainer_cfg,
        mesh_cfg=None,
        mesh=None,
        tx=None,
        contrastive: ContrastiveConfig = ContrastiveConfig(),
    ):
        super().__init__(model, trainer_cfg, mesh_cfg, mesh, tx)
        if trainer_cfg.batch_size % 2:
            raise ValueError(
                f"embedding batch_size is the ROW count 2B; got odd "
                f"{trainer_cfg.batch_size}"
            )
        if trainer_cfg.grad_accum != 1:
            raise NotImplementedError(
                "contrastive training does not implement grad_accum: "
                "in-batch negatives are the objective — microbatching "
                "would shrink the negative pool, changing the loss"
            )
        if contrastive.pooling not in ("mean", "last"):
            raise ValueError(
                f"unknown pooling {contrastive.pooling!r}"
            )
        self.contrastive = contrastive

    def evaluate(self, data, n_batches=None):
        raise NotImplementedError(
            "EmbeddingTrainer.evaluate would run the LM cross-entropy "
            "on retrieval pairs — meaningless; use evaluate_retrieval "
            "(recall@k over held-out pairs) instead"
        )

    def compiled_eval_step(self, batch: dict):
        raise NotImplementedError(
            "no LM eval step for contrastive training "
            "(see evaluate_retrieval)"
        )

    def evaluate_retrieval(
        self,
        pairs,
        encode: Callable[[str], List[int]],
        seq_len: Optional[int] = None,
        ks: tuple = (1, 5, 10),
        batch_rows: int = 64,
    ) -> dict:
        """Held-out retrieval metrics: every query scored against EVERY
        document in ``pairs`` (the full candidate pool, not in-batch).

        ``pairs``: an iterable of {"query", "positive"} dicts or a
        JSONL path. Returns {"recall@k": ..., "mrr": ..., "n": N}.
        Embedding happens in ``batch_rows`` chunks so the pool size is
        bounded by host memory, not HBM.
        """
        if isinstance(pairs, (str, pathlib.Path)):
            pairs = list(read_pairs(pairs))
        else:
            pairs = list(pairs)
        if not pairs:
            raise ValueError("evaluate_retrieval: no pairs")
        t = seq_len or self.cfg.seq_len
        n = len(pairs)
        toks = np.zeros((2 * n, t), np.int32)
        seg = np.zeros_like(toks)
        for i, p in enumerate(pairs):
            toks[i], seg[i] = _fit(encode(p["query"]), t)
            toks[n + i], seg[n + i] = _fit(encode(p["positive"]), t)
        embs = np.concatenate([
            self.embed(toks[s: s + batch_rows], seg[s: s + batch_rows])
            for s in range(0, 2 * n, batch_rows)
        ])
        q, d = embs[:n], embs[n:]
        sim = q @ d.T  # [N, N]
        # Rank of the true document for each query (0 = top).
        order = np.argsort(-sim, axis=1)
        ranks = np.argmax(order == np.arange(n)[:, None], axis=1)
        out = {f"recall@{k}": float((ranks < k).mean()) for k in ks}
        out["mrr"] = float((1.0 / (ranks + 1)).mean())
        out["n"] = n
        return out

    def compiled_step(self, batch: dict | None = None):
        from functools import partial

        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        key = (
            ("contrastive", "segment_ids", "tokens")
            if batch is None
            else ("contrastive", *sorted(batch.keys()))
        )
        if key not in self._compiled:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in key[1:]}
            self._compiled[key] = jax.jit(
                partial(
                    contrastive_train_step,
                    temperature=self.contrastive.temperature,
                    pooling=self.contrastive.pooling,
                ),
                in_shardings=(self.state_sharding, batch_sharding),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            )
        return self._compiled[key]

    def embed(self, tokens: np.ndarray, segment_ids: np.ndarray):
        """[N, T] -> [N, D] L2-normalized embeddings with the trainer's
        pooling — the inference surface of the fine-tuned encoder."""
        if self.state is None:
            raise RuntimeError("embed() before init_state()/restore")
        from tpufw.parallel.context import use_mesh

        with use_mesh(self.mesh):
            out = self.model.apply(
                {"params": self.state.params},
                jnp.asarray(tokens),
                segment_ids=jnp.asarray(segment_ids),
                return_hidden=True,
            )
            if isinstance(out, tuple):
                out = out[0]
            emb = pool_embeddings(
                out.astype(jnp.float32),
                jnp.asarray(segment_ids),
                self.contrastive.pooling,
            )
            return np.asarray(
                emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
                .clip(1e-6)
            )
