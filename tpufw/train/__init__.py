from tpufw.train.trainer import (  # noqa: F401
    TrainState,
    Trainer,
    TrainerConfig,
    batch_loss,
    cross_entropy_loss,
    default_optimizer,
    eval_step,
    state_shardings,
    train_step,
)
from tpufw.train.metrics import Meter, StepMetrics  # noqa: F401
from tpufw.train.pipeline_trainer import (  # noqa: F401
    PipelineTrainer,
    PipeTrainState,
)
from tpufw.train.checkpoint import CheckpointManager  # noqa: F401
from tpufw.train.preemption import GracefulShutdown  # noqa: F401
from tpufw.train.data import (  # noqa: F401
    pack_documents,
    synthetic_batches,
    synthetic_packed_batches,
)
from tpufw.train.native_data import (  # noqa: F401
    TokenCorpus,
    write_token_corpus,
)
from tpufw.train.prefetch import prefetch_to_device  # noqa: F401
from tpufw.train.sft import (  # noqa: F401
    encode_conversation,
    render_conversation,
    sft_batches,
)
from tpufw.train.dpo import (  # noqa: F401
    DPOConfig,
    DPOTrainer,
    dpo_batches,
    dpo_train_step,
)
from tpufw.train.distill import (  # noqa: F401
    DistillConfig,
    DistillTrainer,
    distill_train_step,
)
from tpufw.train.grpo import (  # noqa: F401
    GRPOConfig,
    GRPOTrainer,
    group_advantages,
    grpo_train_step,
)
from tpufw.train.contrastive import (  # noqa: F401
    ContrastiveConfig,
    EmbeddingTrainer,
    contrastive_train_step,
    info_nce_loss,
)
from tpufw.train.vision import (  # noqa: F401
    VisionTrainer,
    VisionTrainerConfig,
    VisionTrainState,
    synthetic_images,
    vision_train_step,
)
