"""Supervised fine-tuning data path: chat conversations -> masked batches.

The reference ships no ML workloads at all (its "workload" is a
diagnostic CLI, reference README.md:314); SFT is the fine-tuning
workflow real users run after importing a base checkpoint
(tpufw.tools.import_hf), so it gets first-class support: render a chat
template, tokenize, and train ONLY on assistant-turn tokens — the
per-token train mask rides the standard packed-batch path
(tpufw.train.data.pack_documents) as ``loss_mask``, so every trainer,
schedule, and parallelism mode that consumes packed batches fine-tunes
correctly with zero changes.

Masking semantics: ``loss_mask`` marks TARGET positions
(tpufw.train.trainer.shift_and_mask applies ``mask[:, 1:]``), so
flagging assistant tokens trains exactly the positions whose predicted
token belongs to an assistant span — including the first response token
(predicted from the last prompt token) and the turn's end-of-turn
marker, and nothing else.
"""

from __future__ import annotations

import json
import pathlib
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from tpufw.train.data import pack_documents

#: template name -> (per-role header, turn footer, optional bos text).
#: Strings are rendered around each message's content; the assistant
#: header is part of the PROMPT (not trained), the assistant content +
#: footer are trained.
_TEMPLATES = {
    # Llama-3 instruct header/footer tokens, spelled as text so any
    # tokenizer (incl. the byte fallback) can render them.
    "llama3": {
        "bos": "<|begin_of_text|>",
        "header": "<|start_header_id|>{role}<|end_header_id|>\n\n",
        "footer": "<|eot_id|>",
    },
    "chatml": {
        "bos": "",
        "header": "<|im_start|>{role}\n",
        "footer": "<|im_end|>\n",
    },
    # Dependency-free plain-text template for smoke tests and byte-level
    # tokenizers.
    "plain": {
        "bos": "",
        "header": "### {role}\n",
        "footer": "\n",
    },
}


def render_conversation(
    messages: Sequence[dict], template: str = "plain"
) -> List[Tuple[str, bool]]:
    """Render chat ``messages`` ([{role, content}, ...]) into
    (text_span, train) pairs. Assistant content + its end-of-turn
    footer train; everything else (system/user turns, ALL headers) is
    context only."""
    if template not in _TEMPLATES:
        raise ValueError(
            f"unknown chat template {template!r}; "
            f"expected one of {sorted(_TEMPLATES)}"
        )
    t = _TEMPLATES[template]
    spans: List[Tuple[str, bool]] = []
    if t["bos"]:
        spans.append((t["bos"], False))
    for m in messages:
        role, content = m["role"], m["content"]
        train = role == "assistant"
        spans.append((t["header"].format(role=role), False))
        spans.append((content, train))
        spans.append((t["footer"], train))
    return [(s, tr) for s, tr in spans if s]


def encode_conversation(
    messages: Sequence[dict],
    encode: Callable[[str], List[int]],
    template: str = "plain",
) -> Tuple[np.ndarray, np.ndarray]:
    """(tokens, train_mask) for one conversation. ``encode`` must be
    context-free (no special-token injection) — each span is encoded
    independently so the mask boundary is exact."""
    toks: List[int] = []
    mask: List[float] = []
    for text, train in render_conversation(messages, template):
        ids = encode(text)
        toks.extend(ids)
        mask.extend([1.0 if train else 0.0] * len(ids))
    return np.asarray(toks, np.int32), np.asarray(mask, np.float32)


def read_conversations(path: str | pathlib.Path) -> Iterator[list]:
    """JSONL: one conversation per line, either a bare message list or
    {"messages": [...]} — the common export shapes."""
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            msgs = obj.get("messages") if isinstance(obj, dict) else obj
            if not isinstance(msgs, list) or not all(
                isinstance(m, dict) and "role" in m and "content" in m
                for m in msgs
            ):
                raise ValueError(
                    f"{path}:{ln}: expected a message list "
                    "[{role, content}, ...]"
                )
            yield msgs


def sft_batches(
    path: str | pathlib.Path,
    batch_size: int,
    seq_len: int,
    encode: Callable[[str], List[int]],
    template: str = "plain",
    epochs: Optional[int] = None,
    seed: int = 0,
    drop_untrainable: bool = True,
    shard_id: int = 0,
    num_shards: int = 1,
) -> Iterator[dict]:
    """Packed SFT batches from a JSONL conversation file: shuffled each
    epoch, assistant-masked, segment-separated. ``epochs=None`` cycles
    forever (the trainer's total_steps is the budget).

    ``drop_untrainable`` skips conversations with no assistant turn —
    they would contribute zero loss positions and only dilute batches.

    Multi-process: ``shard_id``/``num_shards`` give each process a
    DISJOINT strided slice of the conversations (same contract as
    TokenCorpus), sliced BEFORE shuffling so shards stay disjoint in
    every epoch regardless of seed.
    """
    convs = list(read_conversations(path))
    if not convs:
        raise ValueError(f"{path}: no conversations")
    convs = convs[shard_id::num_shards]
    if not convs:
        raise ValueError(
            f"{path}: shard {shard_id}/{num_shards} is empty "
            f"({len(list(read_conversations(path)))} conversations)"
        )
    encoded = [
        encode_conversation(m, encode, template) for m in convs
    ]
    if drop_untrainable:
        kept = [(t, m) for t, m in encoded if m.sum() > 0]
        if not kept:
            raise ValueError(
                f"{path}: no conversation has an assistant turn to "
                "train on"
            )
        encoded = kept
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(len(encoded))
        yield from pack_documents(
            (encoded[i] for i in order), batch_size, seq_len
        )
        epoch += 1


def byte_encode(text: str) -> List[int]:
    """Dependency-free byte tokenizer (id = utf-8 byte + 1; 0 = pad) —
    same convention as tpufw.tools.pack_corpus."""
    return [b + 1 for b in text.encode("utf-8")]
