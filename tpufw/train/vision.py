"""Image-classification training (ResNet-50, BASELINE config 2).

Separate from the LM trainer because vision models carry mutable batch-norm
statistics alongside params; everything else (mesh, logical shardings, MFU
metering) is shared machinery.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpufw.mesh import MeshConfig, build_mesh
from tpufw.parallel.context import use_mesh
from tpufw.train.metrics import Meter, StepMetrics, timed_batches
from tpufw.train.trainer import state_shardings


class VisionTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def vision_train_step(state: VisionTrainState, batch: dict):
    """One supervised step: images [B,H,W,C], labels [B]."""

    def loss_fn(params):
        logits, mutated = state.apply_fn(
            {"params": params, "batch_stats": state.batch_stats},
            batch["images"],
            train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()
        # Stat-free models (ViT) mutate nothing: keep the empty tree.
        return loss, (logits, mutated.get("batch_stats", state.batch_stats))

    (loss, (logits, new_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
    accuracy = jnp.mean(
        (jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32)
    )
    new_state = state.replace(
        step=state.step + 1,
        params=optax.apply_updates(state.params, updates),
        batch_stats=new_stats,
        opt_state=new_opt,
    )
    return new_state, {"loss": loss, "accuracy": accuracy}


@dataclasses.dataclass
class VisionTrainerConfig:
    batch_size: int = 256
    image_size: int = 224
    num_classes: int = 1000
    total_steps: int = 100
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    warmup_steps: int = 5
    # Orbax checkpoint/resume (None = off) — same elastic-recovery
    # contract as the LM TrainerConfig.
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 100
    # SIGTERM → gang-consistent stop → forced final checkpoint
    # (tpufw.train.preemption); same semantics as TrainerConfig.
    handle_preemption: bool = True
    preemption_sync_every: int = 1
    # Steps between host syncs (see TrainerConfig.sync_every): ResNet
    # steps are short (~100-300 ms), so per-step loss fetches serialize
    # against backend round trips; >1 dispatches a window per sync.
    sync_every: int = 1


class VisionTrainer:
    """SGD+momentum ResNet trainer over the tpufw mesh."""

    def __init__(
        self,
        model: nn.Module,
        cfg: VisionTrainerConfig,
        mesh_cfg: MeshConfig | None = None,
        mesh: Mesh | None = None,
    ):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else build_mesh(mesh_cfg)
        schedule = optax.warmup_cosine_decay_schedule(
            0.0,
            cfg.lr,
            cfg.warmup_steps,
            max(cfg.total_steps, cfg.warmup_steps + 1),
        )
        def decay_mask(params):
            # Standard ResNet recipe: no decay on BatchNorm scales/biases
            # (any rank-1 param).
            return jax.tree.map(lambda p: p.ndim > 1, params)

        self.tx = optax.chain(
            optax.add_decayed_weights(cfg.weight_decay, mask=decay_mask),
            optax.sgd(schedule, momentum=cfg.momentum, nesterov=True),
        )
        self.state = None
        self.state_sharding = None
        self._compiled = None
        self.preempted = False

    def _abstract_state(self, rng):
        imgs = jnp.zeros(
            (
                self.cfg.batch_size,
                self.cfg.image_size,
                self.cfg.image_size,
                3,
            ),
            jnp.float32,
        )

        def init_fn(rng):
            variables = self.model.init(rng, imgs, train=True)
            return VisionTrainState(
                step=jnp.zeros((), jnp.int32),
                params=variables["params"],
                # BN-free models (ViT) simply carry an empty tree here.
                batch_stats=variables.get("batch_stats", {}),
                opt_state=self.tx.init(variables["params"]),
                apply_fn=self.model.apply,
                tx=self.tx,
            )

        return init_fn, jax.eval_shape(init_fn, rng)

    def init_state(self, seed: int = 0) -> VisionTrainState:
        rng = jax.random.key(seed)
        init_fn, abstract = self._abstract_state(rng)
        self.state_sharding = state_shardings(abstract, self.mesh)
        with use_mesh(self.mesh):
            # tpulint: disable=TPU003 — _abstract_state only
            # eval_shape's rng (abstract, no randomness drawn); this
            # jitted init is the key's one real use.
            self.state = jax.jit(
                init_fn, out_shardings=self.state_sharding
            )(rng)
        self.state = meta.unbox(self.state)
        self.state_sharding = meta.unbox(self.state_sharding)
        return self.state

    def maybe_restore(self) -> bool:
        """Resume from the latest checkpoint in cfg.checkpoint_dir, if any
        — same pod-restart resume contract as the LM Trainer, without
        materializing a throwaway init."""
        if not self.cfg.checkpoint_dir:
            return False
        from tpufw.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(self.cfg.checkpoint_dir)
        try:
            if mgr.latest_step() is None:
                return False
            rng = jax.random.key(0)
            _, boxed = self._abstract_state(rng)
            self.state_sharding = meta.unbox(
                state_shardings(boxed, self.mesh)
            )
            abstract = jax.tree.map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
                meta.unbox(boxed),
                self.state_sharding,
            )
            self.state = mgr.restore(abstract)
            return True
        finally:
            mgr.close()

    def compiled_step(self):
        if self._compiled is None:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            self._compiled = jax.jit(
                vision_train_step,
                in_shardings=(
                    self.state_sharding,
                    {"images": row, "labels": row},
                ),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            )
        return self._compiled

    def run(
        self,
        data: Iterator[dict],
        flops_per_image: Optional[float] = None,
        on_metrics: Callable[[StepMetrics], None] | None = None,
        shutdown: "GracefulShutdown | None" = None,
    ) -> list[StepMetrics]:
        if self.state is None:
            self.init_state()
        step_fn = self.compiled_step()
        meter = Meter(
            tokens_per_step=self.cfg.batch_size,  # "tokens" = images here
            flops_per_token=flops_per_image or 0.0,
            n_chips=len(self.mesh.devices.flatten()),
        )
        owns_shutdown = False
        self.preempted = False
        ckpt = None
        if self.cfg.checkpoint_dir:
            from tpufw.train.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                self.cfg.checkpoint_dir,
                save_interval_steps=self.cfg.checkpoint_every,
            )
        from tpufw.train.preemption import checkpoint_stop, owned_shutdown

        shutdown, owns_shutdown = owned_shutdown(
            shutdown,
            self.cfg.handle_preemption,
            self.cfg.preemption_sync_every,
        )
        # Global step budget: a restored run finishes the remainder.
        start_step = int(self.state.step)
        remaining = max(0, self.cfg.total_steps - start_step)
        se = max(1, self.cfg.sync_every)
        window_n, window_wait = 0, 0.0
        from tpufw.train.trainer import globalize_batch

        history = []
        try:
            with use_mesh(self.mesh):
                for i, (wait, batch) in enumerate(timed_batches(data)):
                    if i >= remaining:
                        break
                    batch = globalize_batch(self.mesh, batch)
                    if window_n == 0:
                        meter.start()
                    self.state, m = step_fn(self.state, batch)
                    window_n += 1
                    window_wait += wait
                    py_step = start_step + i + 1
                    # Step 1 (compile boundary), MULTIPLES of
                    # sync_every (so aligned checkpoint_every fires),
                    # and the last step.
                    if not (
                        i == 0
                        or py_step % se == 0
                        or i + 1 == remaining
                    ):
                        continue
                    loss = m["loss"]  # Meter.stop float()s it: the barrier
                    sm = meter.stop(
                        py_step, loss,
                        data_wait_s=window_wait, n_steps=window_n,
                    )
                    window_n, window_wait = 0, 0.0
                    history.append(sm)
                    if on_metrics:
                        on_metrics(sm)
                    if ckpt is not None:
                        ckpt.save(py_step, self.state)
                    # Gang-consistent preemption stop (preemption.py).
                    if checkpoint_stop(
                        shutdown, ckpt, py_step, self.state
                    ):
                        self.preempted = True
                        break
                # Iterator exhausted mid-window: flush the open window.
                if window_n:
                    loss = m["loss"]  # Meter.stop float()s it: the barrier
                    sm = meter.stop(
                        py_step, loss,
                        data_wait_s=window_wait, n_steps=window_n,
                    )
                    history.append(sm)
                    if on_metrics:
                        on_metrics(sm)
                    if ckpt is not None:
                        ckpt.save(py_step, self.state)
        finally:
            if ckpt is not None:
                ckpt.wait()
                ckpt.close()
            if owns_shutdown:
                shutdown.uninstall()
        return history


def synthetic_images(
    batch_size: int,
    image_size: int = 224,
    num_classes: int = 1000,
    seed: int = 0,
    pool: int = 4,
    on_device: bool = False,
) -> Iterator[dict]:
    """Cycles a small pre-generated batch pool: generating 38 MB of fresh
    gaussians per step costs more host time than the TPU step itself
    (measured 139 ms vs 174 ms) and would corrupt throughput numbers.

    ``on_device`` stages the pool onto the default device ONCE and
    yields committed jax.Arrays, so the step's jit re-uses them instead
    of re-uploading ~150 MB per step — mandatory over a tunneled PJRT
    backend, where per-step host->device image transfer is ~1000x
    slower than the step itself (bench r3: 14.7 img/s transfer-bound
    vs compute at batch 256)."""
    rng = np.random.default_rng(seed)
    batches = [
        {
            "images": rng.standard_normal(
                (batch_size, image_size, image_size, 3)
            ).astype(np.float32),
            "labels": rng.integers(
                0, num_classes, (batch_size,), dtype=np.int64
            ),
        }
        for _ in range(pool)
    ]
    if on_device:
        import jax

        batches = [
            {k: jax.device_put(v) for k, v in b.items()} for b in batches
        ]
    i = 0
    while True:
        yield batches[i % pool]
        i += 1
