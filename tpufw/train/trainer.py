"""Sharded training: state, loss, jitted step, and the Trainer driver.

Everything runs through one ``jax.jit``-compiled train step whose in/out
shardings are derived from the model's logical partitioning metadata + the
mesh rules (tpufw.mesh). XLA inserts all collectives (grad psum over
data/fsdp, all-gathers for fsdp params, tensor-parallel reductions) — there
is no hand-written communication anywhere, per SURVEY.md §2c.
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct
from flax.core import meta
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpufw.mesh import MeshConfig, build_mesh, logical_axis_rules
from tpufw.parallel.context import use_mesh
from tpufw.train.metrics import Meter, StepMetrics, timed_batches


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    # Static fields (not traced).
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt = self.tx.update(grads, self.opt_state, self.params)
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt,
        )


def cross_entropy_loss(
    logits: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array] = None,
    z_loss_weight: float = 1e-4,
) -> tuple[jax.Array, jax.Array]:
    """Token CE with z-loss regularization (keeps the softmax normalizer
    bounded — standard for large-vocab LM training). Returns (loss, n_tokens).
    The per-token math lives in tpufw.ops.loss.token_cross_entropy, shared
    with the chunked-vocab path.
    """
    from tpufw.ops.loss import token_cross_entropy

    ce = token_cross_entropy(logits, targets, z_loss_weight)
    if mask is None:
        return ce.mean(), jnp.array(ce.size, jnp.float32)
    n = jnp.maximum(mask.sum(), 1.0)
    return (ce * mask).sum() / n, n


def default_optimizer(
    lr: float = 3e-4,
    warmup_steps: int = 100,
    total_steps: int = 10_000,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    b1: float = 0.9,
    b2: float = 0.95,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW + cosine schedule + global-norm clipping — the Llama recipe.

    ``mu_dtype="bfloat16"`` stores the first moment in bf16 (half the mu
    buffer; the momentum direction tolerates bf16 rounding). The second
    moment stays fp32 — it feeds a sqrt and small values underflow bf16.
    """
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1), lr * 0.1
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(
            schedule,
            b1=b1,
            b2=b2,
            weight_decay=weight_decay,
            mu_dtype=jnp.dtype(mu_dtype) if mu_dtype else None,
        ),
    )


def frozen_copy(tree, dtype, out_shardings=None) -> Any:
    """Cast every floating leaf of ``tree`` to ``dtype`` THROUGH jit, so
    each output leaf is a FRESH buffer even when the cast is a dtype
    no-op (fp32 -> fp32): frozen side-trees (DPO reference, distillation
    teacher) live next to a train step that donates state.params, and an
    aliased leaf would be a use-after-donate at the first step.
    ``out_shardings`` additionally lays the copy out on the mesh (a
    large frozen teacher must shard like any other param tree)."""

    def cast(t):
        return jax.tree.map(
            lambda p: p.astype(dtype)
            if jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            t,
        )

    if out_shardings is None:
        return jax.jit(cast)(tree)
    return jax.jit(cast, out_shardings=out_shardings)(tree)


def head_kernel(params) -> jax.Array:
    """The [D, V] LM-head matrix from a decoder_lm param tree — the
    dedicated ``lm_head`` kernel, or the transposed embedding when tied."""
    if "lm_head" in params:
        return params["lm_head"]["kernel"]
    return params["embed"]["embedding"].T


def shift_and_mask(batch: dict):
    """LM target shift + packed-batch masking, shared by every objective.

    Returns (inputs, targets, input_segment_ids, loss_mask). With
    segment_ids: never train boundary positions to predict the next
    document's first token — attention (correctly) can't see across
    segments — and never train on padding targets (segment 0).
    """
    tokens = batch["tokens"]
    inputs = tokens[:, :-1]
    targets = tokens[:, 1:]
    seg = batch.get("segment_ids")
    seg_in = None if seg is None else seg[:, :-1]
    mask = batch.get("loss_mask")
    mask = None if mask is None else mask[:, 1:].astype(jnp.float32)
    if seg is not None:
        same_seg = (seg[:, :-1] == seg[:, 1:]).astype(jnp.float32)
        nonpad = (seg[:, 1:] > 0).astype(jnp.float32)
        seg_mask = same_seg * nonpad
        mask = seg_mask if mask is None else mask * seg_mask
    return inputs, targets, seg_in, mask


def batch_loss(
    apply_fn: Callable,
    params,
    batch: dict,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype: str = "bfloat16",
    final_logit_soft_cap: Optional[float] = None,
) -> tuple[jax.Array, jax.Array]:
    """LM objective for one batch: (loss, n_target_tokens).

    batch: tokens [B,T] (+ optional loss_mask, segment_ids). Targets are
    tokens shifted left; the final position is masked out.
    ``loss_chunk_size`` switches to the chunked-vocab CE (tpufw.ops.loss):
    the model skips its head matmul and loss is computed from hidden
    states chunk-by-chunk, never materializing [B,T,V] logits. Shared by
    the train and eval steps so their objectives can't drift.
    """
    inputs, targets, seg_in, mask = shift_and_mask(batch)

    kwargs = {"segment_ids": seg_in}
    if loss_chunk_size:
        kwargs["return_hidden"] = True
    out = apply_fn({"params": params}, inputs, **kwargs)
    # MoE models return (logits, aux_loss) — router losses join the
    # objective here.
    aux = 0.0
    if isinstance(out, tuple):
        out, aux = out
    if loss_chunk_size:
        from tpufw.ops.loss import chunked_cross_entropy

        loss, n = chunked_cross_entropy(
            out, head_kernel(params), targets, mask,
            chunk_size=loss_chunk_size,
            compute_dtype=jnp.dtype(loss_chunk_dtype),
            # Gemma-style cap; the model skipped its head (and cap) via
            # return_hidden, so the chunked path applies it per chunk.
            logits_soft_cap=final_logit_soft_cap,
        )
    else:
        loss, n = cross_entropy_loss(out, targets, mask)
    return loss + aux, n


def train_step(
    state: TrainState,
    batch: dict,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype: str = "bfloat16",
    grad_accum: int = 1,
    final_logit_soft_cap: Optional[float] = None,
) -> tuple[TrainState, dict]:
    """One optimizer update (objective: ``batch_loss``).

    ``grad_accum`` > 1 splits the batch into that many microbatches and
    accumulates token-weighted gradients under ``lax.scan`` before the
    single update — same numbers as the one-shot step (modulo fp
    summation order), at 1/A the activation memory. Microbatch rows are
    taken strided (row m, m+A, ...) so each device's local shard
    contributes equally to every microbatch and no resharding is needed.
    """

    def loss_and_n(params, mb):
        def lf(p):
            return batch_loss(
                state.apply_fn, p, mb, loss_chunk_size, loss_chunk_dtype,
                final_logit_soft_cap,
            )

        (loss, n), grads = jax.value_and_grad(lf, has_aux=True)(params)
        return loss, n, grads

    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if grad_accum == 1:
        loss, _, grads = loss_and_n(state.params, batch)
    else:
        mbs = jax.tree.map(
            lambda x: x.reshape(
                x.shape[0] // grad_accum, grad_accum, *x.shape[1:]
            ).swapaxes(0, 1),
            batch,
        )

        def body(carry, mb):
            l_acc, n_acc, g_acc = carry
            loss, n, grads = loss_and_n(state.params, mb)
            return (
                l_acc + loss * n,
                n_acc + n,
                jax.tree.map(lambda a, g: a + g * n, g_acc, grads),
            ), None

        # Accumulate in fp32 regardless of param dtype: the body's
        # `g * n` promotes to fp32 (n is fp32), so a bf16-params carry
        # would be a scan dtype mismatch — and fp32 accumulation is the
        # numerically right call anyway. Cast back at the end.
        zero_g = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        (l_sum, n_sum, g_sum), _ = jax.lax.scan(
            body,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), zero_g),
            mbs,
        )
        n_safe = jnp.maximum(n_sum, 1.0)
        loss = l_sum / n_safe
        grads = jax.tree.map(
            lambda g, p: (g / n_safe).astype(p.dtype), g_sum, state.params
        )

    new_state = state.apply_gradients(grads)
    metrics = {
        "loss": loss,
        "grad_norm": optax.global_norm(grads),
    }
    return new_state, metrics


def eval_step(
    state: TrainState,
    batch: dict,
    loss_chunk_size: Optional[int] = None,
    loss_chunk_dtype: str = "bfloat16",
    final_logit_soft_cap: Optional[float] = None,
) -> dict:
    """Forward-only objective on one held-out batch: {loss, n_tokens}."""
    loss, n = batch_loss(
        state.apply_fn, state.params, batch, loss_chunk_size,
        loss_chunk_dtype, final_logit_soft_cap,
    )
    return {"loss": loss, "n_tokens": n}


def globalize_batch(mesh: Mesh, batch: dict) -> dict:
    """Multi-process: assemble each process's LOCAL batch shard into a
    global jax.Array (jit rejects raw numpy under a multi-host mesh).

    Contract: the configured batch size is the GLOBAL batch; each
    process's data iterator yields ``batch_size / process_count`` rows.
    In single-process runs this is the identity. Shared by the flax
    Trainer and the PipelineTrainer so the multi-host contract can't
    drift between them.
    """
    if jax.process_count() == 1:
        return batch
    row = NamedSharding(mesh, P(("data", "fsdp")))
    return {
        # Leaves that are already jax.Arrays (e.g. from
        # prefetch_to_device) are global already; only raw host
        # numpy needs assembling.
        k: v if isinstance(v, jax.Array)
        else jax.make_array_from_process_local_data(row, v)
        for k, v in batch.items()
    }


def _mesh_label(mesh: Mesh) -> str:
    """Compact mesh-shape label for the run_info gauge: ``data=8`` /
    ``data=4,fsdp=2`` (size-1 axes elided — they carry no sharding)."""
    parts = [
        f"{name}={size}"
        for name, size in mesh.shape.items()
        if size > 1
    ]
    return ",".join(parts) or "single"


def run_evaluation(
    data, n_batches, eval_batch_fn, globalize
) -> dict:
    """The ONE token-weighted held-out eval loop: accumulate
    {loss, n_tokens} outputs of ``eval_batch_fn(batch)`` over up to
    ``n_batches`` batches and report {eval_loss, eval_ppl, eval_tokens,
    eval_batches}. Shared by Trainer and PipelineTrainer so their eval
    reporting surfaces cannot drift."""
    total_loss = 0.0
    total_n = 0.0
    n_seen = 0
    for i, batch in enumerate(data):
        if n_batches is not None and i >= n_batches:
            break
        if not isinstance(batch, dict):
            batch = {"tokens": batch}
        batch = globalize(batch)
        out = eval_batch_fn(batch)
        n = float(out["n_tokens"])
        total_loss += float(out["loss"]) * n
        total_n += n
        n_seen += 1
    if n_seen == 0:
        raise ValueError("evaluate(): empty eval iterator")
    import math

    loss = total_loss / max(total_n, 1.0)
    return {
        "eval_loss": loss,
        "eval_ppl": math.exp(min(loss, 50.0)),
        "eval_tokens": int(total_n),
        "eval_batches": n_seen,
    }


def maybe_inloop_eval(trainer, step: int, eval_data, on_eval) -> None:
    """The ONE in-loop eval trigger (cadence + reporting), shared by the
    flax and pipeline trainers so eval cadence cannot drift."""
    cfg = trainer.cfg
    if not (cfg.eval_every and eval_data is not None):
        return
    if step % cfg.eval_every:
        return
    ev = trainer.evaluate(eval_data(), cfg.eval_batches)
    ev["step"] = step
    tel = getattr(trainer, "telemetry", None)
    if tel is not None:
        tel.events.emit(
            "eval",
            **{
                k: v if isinstance(v, int) else round(float(v), 6)
                for k, v in ev.items()
                if isinstance(v, (int, float))
            },
        )
    if on_eval:
        on_eval(ev)


def state_shardings(
    abstract_state: TrainState, mesh: Mesh, rules=None
) -> TrainState:
    """Derive NamedShardings for a TrainState pytree from logical metadata.

    Params carry flax ``Partitioned`` metadata; optimizer moments mirror the
    param they track (optax keeps the tree structure), so
    ``nn.logical_to_mesh_sharding`` resolves both. Scalars replicate.
    """
    rules = rules or logical_axis_rules()
    specs = nn.get_partition_spec(abstract_state)
    return nn.logical_to_mesh_sharding(specs, mesh, rules)


@dataclasses.dataclass
class TrainerConfig:
    batch_size: int = 8
    seq_len: int = 2048
    total_steps: int = 100
    lr: float = 3e-4
    warmup_steps: int = 10
    log_every: int = 10
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 1000
    # Sequence positions per chunked-CE scan step; None = full logits.
    loss_chunk_size: Optional[int] = None
    # Head-matmul input dtype for the chunked path. "bfloat16" is the MXU
    # fast path (fp32 accumulation either way); "float32" restores bitwise
    # full-logits numerics at ~2x head-matmul cost.
    loss_chunk_dtype: str = "bfloat16"
    # XProf capture: trace steps [profile_start, profile_stop) into
    # profile_dir (None disables). Step 0 is excluded by default so the
    # window holds steady-state steps, not the XLA compile.
    profile_dir: Optional[str] = None
    profile_start: int = 3
    profile_stop: int = 6
    # Held-out evaluation: every eval_every steps (0 = off) run
    # eval_batches forward-only batches from the eval iterator passed to
    # ``Trainer.run(eval_data=...)``.
    eval_every: int = 0
    eval_batches: int = 8
    # Gradient accumulation: microbatches per optimizer step (1 = off).
    # Batch rows per microbatch must still divide over data x fsdp.
    grad_accum: int = 1
    # Adam first-moment storage dtype (None = fp32). "bfloat16" halves
    # the mu buffer — see default_optimizer.
    adam_mu_dtype: Optional[str] = None
    # Preemption handling: latch SIGTERM (k8s pod termination) and exit
    # the step loop cleanly with a forced final checkpoint, so a JobSet
    # gang restart resumes from the current step (tpufw.train.preemption).
    # Default ON — one default for library and deployed use; the handler
    # chains to any prior one and is uninstalled when run() returns.
    handle_preemption: bool = True
    # Steps between gang-consistency syncs of the stop flag (the
    # cross-host allgather in GracefulShutdown.should_stop); a stop is
    # acted on within this many steps of the signal. 1 = every step.
    preemption_sync_every: int = 1
    # Steps between host syncs of the loss (block_until_ready). 1 = the
    # classic per-step sync. >1 dispatches a WINDOW of steps and syncs
    # once: on a remote/tunneled backend every sync costs a host<->device
    # round trip, which serializes against short steps. The loop always
    # syncs after the first step (compile boundary / first-step latency)
    # and the last; metrics entries then carry window averages
    # (StepMetrics.window_steps), and checkpoint saves, in-loop eval,
    # and preemption checks run at sync points only — align
    # checkpoint_every/eval_every to multiples of sync_every.
    sync_every: int = 1
    # MFU autotuning (tpufw.tune): "off" = fully inert; "cached" = apply
    # a persisted winner if one exists, never search; "search" = cache
    # hit or run the budgeted compile-and-measure search before the
    # first step and persist the winner. Resolved once at the top of
    # run(); the winner overwrites grad_accum / loss_chunk_size /
    # sync_every / remat policy / flash blocks on this trainer.
    autotune: str = "off"
    # Wall-clock budget for the "search" mode's measurement loop.
    autotune_budget_s: float = 120.0
    # Timed steps per candidate (median is the score).
    autotune_steps: int = 3
    # Unified telemetry (tpufw.obs). telemetry_dir: write the schema'd
    # events.jsonl + Chrome-trace trace.json (Perfetto-loadable) per
    # host under this dir, plus a final metrics.prom snapshot (None
    # disables the files). metrics_port: serve the Prometheus registry
    # at /metrics on this port from a daemon thread (None disables;
    # 0 binds an ephemeral port — tests read Trainer.telemetry
    # .bound_port). Set BOTH knobs uniformly across hosts: the skew
    # monitor's per-window allgather is a collective. With both off
    # the instrumentation degrades to shared no-ops (<1% per-step,
    # asserted in tests/test_obs.py).
    telemetry_dir: Optional[str] = None
    metrics_port: Optional[int] = None
    # A host is flagged (straggler_detected event, warn) when its sync
    # window's wall time exceeds the fleet median by this factor.
    straggler_factor: float = 2.0
    # Pipeline schedule override (PipelineTrainer only; the flax
    # Trainer ignores both). None keeps the PipelineConfig's own
    # schedule; "gpipe" | "1f1b" | "interleaved" | "zb1" replaces it.
    # pipeline_vstages is the interleaved schedule's virtual-stage
    # count v (bubble (S-1)/(v*M+S-1)); it must satisfy
    # PipelineConfig.validate's divisibility rules.
    pipeline_schedule: Optional[str] = None
    pipeline_vstages: int = 1


class Trainer:
    """Builds mesh + sharded state and runs the step loop with MFU metrics."""

    def __init__(
        self,
        model: nn.Module,
        trainer_cfg: TrainerConfig,
        mesh_cfg: MeshConfig | None = None,
        mesh: Mesh | None = None,
        tx: optax.GradientTransformation | None = None,
    ):
        self.model = model
        self.cfg = trainer_cfg
        self.mesh = mesh if mesh is not None else build_mesh(mesh_cfg)
        self.tx = tx or default_optimizer(
            lr=trainer_cfg.lr,
            warmup_steps=trainer_cfg.warmup_steps,
            total_steps=trainer_cfg.total_steps,
            mu_dtype=trainer_cfg.adam_mu_dtype,
        )
        if getattr(getattr(model, "cfg", None), "lora_rank", 0) > 0:
            # LoRA fine-tune: update ONLY adapter params; the frozen
            # base gets set_to_zero (optax.masked would PASS ITS RAW
            # GRADIENTS THROUGH, silently training the base). Moments
            # are allocated only for the adapter partition.
            from tpufw.models.lora import lora_mask

            def labels(params):
                return jax.tree.map(
                    lambda m: "lora" if m else "frozen", lora_mask(params)
                )

            self.tx = optax.multi_transform(
                {"lora": self.tx, "frozen": optax.set_to_zero()}, labels
            )
        self._compiled: dict = {}
        self.state = None
        self.state_sharding = None
        self.preempted = False
        # TuneResult of the last apply_autotune (tpufw.tune.runner);
        # None until cfg.autotune resolves in run().
        self.last_tune = None
        # tpufw.obs.Telemetry, built per run() from the cfg knobs;
        # the disabled singleton between runs so probes never branch.
        from tpufw.obs import Telemetry

        self.telemetry = Telemetry.disabled()

    def _abstract_state(self, rng):
        tokens = jnp.zeros(
            (self.cfg.batch_size, self.cfg.seq_len), jnp.int32
        )

        def init_fn(rng):
            variables = self.model.init(rng, tokens[:, :-1])
            params = variables["params"]
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.tx.init(params),
                apply_fn=self.model.apply,
                tx=self.tx,
            )

        # Trace under the mesh context: mesh-aware ops (ring attention)
        # resolve the current mesh during eval_shape too.
        with use_mesh(self.mesh):
            abstract = jax.eval_shape(init_fn, rng)
        return init_fn, abstract

    def init_state(self, seed: int = 0) -> TrainState:
        rng = jax.random.key(seed)
        init_fn, abstract = self._abstract_state(rng)
        self.state_sharding = state_shardings(abstract, self.mesh)
        with use_mesh(self.mesh):
            jit_init = jax.jit(init_fn, out_shardings=self.state_sharding)
            # _abstract_state only eval_shape's rng (abstract, no
            # randomness drawn); this jitted init is the key's one
            # real use.
            self.state = jit_init(rng)  # tpulint: disable=TPU003
        # Same jit object kept for the perf observatory (run() harvests
        # its cost_analysis once telemetry exists): the AOT lower hits
        # the executable this call just built.
        self._init_harvest = (jit_init, rng)
        # Unbox flax Partitioned wrappers: downstream code wants raw arrays.
        self.state = meta.unbox(self.state)
        self.state_sharding = meta.unbox(self.state_sharding)
        return self.state

    def restore_params(self, path: str):
        """Restore a bare-params Orbax checkpoint (the
        ``tpufw.tools.import_hf`` CLI's output) sharded onto this
        trainer's mesh, WITHOUT materializing any state — the abstract
        tree comes from eval_shape, same no-throwaway-init discipline as
        ``maybe_restore``. Returns (params, full_state_sharding)."""
        import orbax.checkpoint as ocp

        _, boxed = self._abstract_state(jax.random.key(0))
        shardings = meta.unbox(state_shardings(boxed, self.mesh))
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            meta.unbox(boxed).params,
            shardings.params,
        )
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(os.path.abspath(path), abstract)
        return params, shardings

    def init_from_params(self, path: str, seed: int = 0) -> TrainState:
        """Start training FROM a bare-params Orbax checkpoint: step 0,
        FRESH optimizer state, params restored sharded — the
        fine-tune-from-imported-weights entry point, distinct from
        ``maybe_restore`` (which resumes a full TrainState mid-run).
        Must be called on a fresh trainer: silently mixing restored
        params with an existing step/optimizer would corrupt the run.

        With LoRA enabled on the model (cfg.lora_rank > 0) the
        checkpoint holds only the BASE tree: base kernels restore from
        disk, adapters initialize fresh (B = 0, so step 0 equals the
        checkpointed model) — the import -> LoRA-fine-tune on-ramp."""
        if self.state is not None:
            raise RuntimeError(
                "init_from_params on an already-initialized trainer; "
                "construct a fresh Trainer (or use maybe_restore to "
                "resume a full TrainState)"
            )
        if getattr(getattr(self.model, "cfg", None), "lora_rank", 0) > 0:
            return self._init_lora_from_params(path, seed)
        del seed  # params come from the checkpoint, nothing is sampled
        params, self.state_sharding = self.restore_params(path)

        def make_state(p):
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=p,
                opt_state=self.tx.init(p),
                apply_fn=self.model.apply,
                tx=self.tx,
            )

        with use_mesh(self.mesh):
            self.state = jax.jit(
                make_state,
                out_shardings=self.state_sharding,
                donate_argnums=(0,),
            )(params)
        return self.state

    def _init_lora_from_params(self, path: str, seed: int) -> TrainState:
        """Base kernels from the checkpoint + fresh adapters (see
        init_from_params). The checkpoint tree is exactly what a rank-0
        twin of this model initializes, so its abstract/restore target
        comes from that twin; the restored leaves then overwrite the
        matching leaves of a fresh full init (adapters keep theirs)."""
        base_model = type(self.model)(
            dataclasses.replace(self.model.cfg, lora_rank=0)
        )
        base = Trainer(base_model, self.cfg, mesh=self.mesh, tx=self.tx)
        base_params, _ = base.restore_params(path)

        rng = jax.random.key(seed)
        init_fn, abstract = self._abstract_state(rng)
        self.state_sharding = meta.unbox(
            state_shardings(abstract, self.mesh)
        )

        def graft(full, restored):
            if isinstance(restored, dict):
                return {
                    k: graft(full[k], restored[k]) if k in restored else v
                    for k, v in full.items()
                }
            return restored

        def make_state(restored):
            # Full init traced, then base leaves replaced by the donated
            # checkpoint: XLA dead-code-eliminates the unused base random
            # init, so peak memory is ~one param tree + adapters (the
            # no-throwaway-init discipline, LoRA edition).
            state = meta.unbox(init_fn(rng))
            return state.replace(
                params=graft(state.params, restored)
            )

        with use_mesh(self.mesh):
            self.state = jax.jit(
                make_state,
                out_shardings=self.state_sharding,
                donate_argnums=(0,),
            )(base_params)
        return self.state

    def maybe_restore(self) -> bool:
        """Resume from the latest checkpoint in cfg.checkpoint_dir, if any —
        the JobSet gang-restart resume path (SURVEY.md §5)."""
        if not self.cfg.checkpoint_dir:
            return False
        from tpufw.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(self.cfg.checkpoint_dir)
        try:
            if mgr.latest_step() is None:
                return False
            if self.state is not None:
                abstract = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        x.shape, x.dtype, sharding=x.sharding
                    ),
                    self.state,
                )
            else:
                # Shapes + shardings WITHOUT materializing a throwaway init
                # (an 8B init would allocate full params+Adam just to be
                # overwritten by the restore).
                rng = jax.random.key(0)
                _, boxed = self._abstract_state(rng)
                self.state_sharding = meta.unbox(
                    state_shardings(boxed, self.mesh)
                )
                abstract = jax.tree.map(
                    lambda x, s: jax.ShapeDtypeStruct(
                        x.shape, x.dtype, sharding=s
                    ),
                    meta.unbox(boxed),
                    self.state_sharding,
                )
            self.state = mgr.restore(abstract)
            return True
        finally:
            mgr.close()

    def _final_soft_cap(self) -> Optional[float]:
        """The model's final-logit soft-cap (Gemma), applied inside the
        chunked-CE path since return_hidden skips the model's own cap."""
        cfg = getattr(self.model, "cfg", None)
        return getattr(cfg, "final_logit_soft_cap", None)

    def globalize_batch(self, batch: dict) -> dict:
        return globalize_batch(self.mesh, batch)

    def compiled_step(self, batch: dict | None = None):
        """Jitted train step; batch shardings derived from the batch's own
        structure (every leaf is batch-major: shard dim 0 on data+fsdp)."""
        key = (
            ("tokens",)
            if batch is None
            else tuple(sorted(batch.keys()))
        )
        if key not in self._compiled:
            accum = self.cfg.grad_accum
            if accum < 1:
                raise ValueError(f"grad_accum must be >= 1, got {accum}")
            if accum > 1:
                dp = (
                    self.mesh.shape["data"] * self.mesh.shape["fsdp"]
                )
                if self.cfg.batch_size % accum or (
                    self.cfg.batch_size // accum
                ) % dp:
                    raise ValueError(
                        f"grad_accum={accum}: batch {self.cfg.batch_size} "
                        f"must split into {accum} microbatches whose rows "
                        f"divide over data x fsdp = {dp}"
                    )
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in key}
            self._compiled[key] = jax.jit(
                partial(
                    train_step,
                    loss_chunk_size=self.cfg.loss_chunk_size,
                    loss_chunk_dtype=self.cfg.loss_chunk_dtype,
                    grad_accum=self.cfg.grad_accum,
                    final_logit_soft_cap=self._final_soft_cap(),
                ),
                in_shardings=(self.state_sharding, batch_sharding),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            )
        return self._compiled[key]

    def compiled_eval_step(self, batch: dict):
        """Jitted forward-only step (no donation: state survives)."""
        key = ("eval", *sorted(batch.keys()))
        if key not in self._compiled:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in sorted(batch.keys())}
            self._compiled[key] = jax.jit(
                partial(
                    eval_step,
                    loss_chunk_size=self.cfg.loss_chunk_size,
                    loss_chunk_dtype=self.cfg.loss_chunk_dtype,
                    final_logit_soft_cap=self._final_soft_cap(),
                ),
                in_shardings=(self.state_sharding, batch_sharding),
                out_shardings=None,
            )
        return self._compiled[key]

    def evaluate(
        self, data: Iterator[dict], n_batches: Optional[int] = None
    ) -> dict:
        """Token-weighted held-out loss + perplexity over ``n_batches``
        (None = until the iterator ends). The objective matches training
        (``batch_loss``, incl. z-loss / MoE aux), so eval_loss is directly
        comparable to the train curve; ppl = exp(eval_loss)."""
        if self.state is None:
            raise RuntimeError("evaluate() before init_state()/restore")

        def eval_one(b):
            fn = self.compiled_eval_step(b)
            self.telemetry.perf.observe_jit(
                "eval_step", fn, (self.state, b)
            )
            return fn(self.state, b)

        with use_mesh(self.mesh):
            return run_evaluation(
                data, n_batches, eval_one, self.globalize_batch
            )

    def run(
        self,
        data: Iterator[dict],
        model_flops_per_token: float,
        on_metrics: Callable[[StepMetrics], None] | None = None,
        eval_data: Callable[[], Iterator[dict]] | None = None,
        on_eval: Callable[[dict], None] | None = None,
        shutdown: "GracefulShutdown | None" = None,
    ) -> list[StepMetrics]:
        from tpufw.obs import Telemetry

        # Telemetry FIRST: autotune trials and checkpoint restores in
        # init_state are themselves events worth having.
        tel = self.telemetry = Telemetry.create(
            telemetry_dir=self.cfg.telemetry_dir,
            metrics_port=self.cfg.metrics_port,
            straggler_factor=self.cfg.straggler_factor,
        )
        tel.set_run_info(
            backend=jax.default_backend(),
            mesh=_mesh_label(self.mesh),
            model=type(self.model).__name__,
        )
        tel.record_config({"trainer": dataclasses.asdict(self.cfg)})
        if self.cfg.autotune != "off":
            # Resolve BEFORE state init: a remat-policy winner rebuilds
            # the model, and the jitted step bakes every tuned knob in.
            from tpufw.tune.runner import apply_autotune

            with tel.tracer.span("tune"):
                apply_autotune(self, events=tel.events, perf=tel.perf)
        if self.state is None:
            self.init_state()
        if tel.perf.enabled:
            # programs.json keyed like the tune winner cache, so a
            # cost table and a tune winner for the same (model, batch,
            # seq, mesh) point line up by construction.
            from tpufw.tune.runner import _trainer_cache_key

            tel.perf.set_key(_trainer_cache_key(self))
            init_harvest = getattr(self, "_init_harvest", None)
            if init_harvest is not None:
                with use_mesh(self.mesh):
                    tel.perf.observe_jit(
                        "state_init", init_harvest[0], (init_harvest[1],)
                    )
        owns_shutdown = False
        self.preempted = False
        meter = Meter(
            tokens_per_step=self.cfg.batch_size * (self.cfg.seq_len - 1),
            flops_per_token=model_flops_per_token,
            n_chips=len(self.mesh.devices.flatten()),
            registry=tel.registry,
        )
        ckpt = None
        if self.cfg.checkpoint_dir:
            from tpufw.train.checkpoint import CheckpointManager

            ckpt = CheckpointManager(
                self.cfg.checkpoint_dir,
                save_interval_steps=self.cfg.checkpoint_every,
                events=tel.events,
                tracer=tel.tracer,
            )
        from tpufw.obs.perf import resolve_profile_window
        from tpufw.utils.profiling import StepProfiler

        # TPUFW_PROFILE_STEPS=a:b overrides the config window; without
        # a configured profile dir the capture lands under the
        # telemetry dir so the trace is linkable from the run artifact.
        prof = StepProfiler(
            *resolve_profile_window(
                self.cfg.profile_dir,
                self.cfg.profile_start,
                self.cfg.profile_stop,
                telemetry_dir=self.cfg.telemetry_dir,
            )
        )
        from tpufw.train.preemption import checkpoint_stop, owned_shutdown

        shutdown, owns_shutdown = owned_shutdown(
            shutdown,
            self.cfg.handle_preemption,
            self.cfg.preemption_sync_every,
            events=tel.events,
        )
        # total_steps is the GLOBAL optimizer-step budget (it sized the LR
        # schedule): a restored run finishes the remaining steps, it does
        # not train total_steps more.
        start_step = int(self.state.step)
        remaining = max(0, self.cfg.total_steps - start_step)
        se = max(1, self.cfg.sync_every)
        window_n, window_wait = 0, 0.0
        history: list[StepMetrics] = []
        tel.events.emit(
            "run_start",
            workload="train",
            start_step=start_step,
            total_steps=self.cfg.total_steps,
            batch_size=self.cfg.batch_size,
            seq_len=self.cfg.seq_len,
            sync_every=se,
            n_chips=len(self.mesh.devices.flatten()),
        )

        def record_window(py_step, loss):
            # One host sync: meter.stop's float(loss) is the barrier.
            # Everything published here describes the window just
            # closed — StepMetrics to the caller, a step event to the
            # log, per-host gauges + straggler check to the skew
            # monitor (its allgather rides the sync the loop already
            # pays for).
            with tel.tracer.span("host_sync"):
                sm = meter.stop(
                    py_step, loss,
                    data_wait_s=window_wait, n_steps=window_n,
                )
                tel.events.emit(
                    "step",
                    step=sm.step,
                    loss=round(sm.loss, 6),
                    step_time_s=round(sm.step_time_s, 6),
                    data_wait_s=round(sm.data_wait_s, 6),
                    mfu=round(sm.mfu, 5),
                    tokens_per_sec_per_chip=round(
                        sm.tokens_per_sec_per_chip, 1
                    ),
                    window_steps=sm.window_steps,
                )
                if tel.skew is not None:
                    tel.skew.record(
                        sm.step,
                        sm.step_time_s * sm.window_steps,
                        sm.data_wait_s,
                    )
                # Static FLOPs x measured wall -> per-program MFU
                # (tpufw_program_mfu) and roofline attribution.
                tel.perf.record_wall("train_step", sm.step_time_s)
            return sm

        try:
            with use_mesh(self.mesh):
                for i, (wait, batch) in enumerate(timed_batches(data)):
                    if i >= remaining:
                        break
                    tel.tracer.complete("data_fetch", wait)
                    # Watchdog window: dispatch through host sync.
                    # Data fetch / eval / checkpoint are excluded —
                    # they have no progress guarantee, and the point
                    # is catching wedged collectives, not slow I/O.
                    tel.watchdog.arm()
                    with tel.tracer.span("step_dispatch"):
                        batch = self.globalize_batch(batch)
                        step_fn = self.compiled_step(batch)
                        # Cost harvest (first time per program only):
                        # abstract lower, so donation is untouched.
                        tel.perf.observe_jit(
                            "train_step", step_fn, (self.state, batch)
                        )
                        prof.maybe_start(i)
                        if window_n == 0:
                            meter.start()
                        with prof.step(i):
                            self.state, m = step_fn(self.state, batch)
                            window_n += 1
                            window_wait += wait
                            # state.step advances by exactly 1 per
                            # step_fn: tracking it host-side avoids a
                            # device fetch (= a round trip on tunneled
                            # backends) per step.
                            py_step = start_step + i + 1
                            # Sync at step 1 (compile boundary), then
                            # at steps that are MULTIPLES of sync_every
                            # — so checkpoint_every/eval_every aligned
                            # to sync_every actually fire — and at the
                            # last.
                            sync = (
                                i == 0
                                or py_step % se == 0
                                or i + 1 == remaining
                            )
                            if sync:
                                loss = m["loss"]  # Meter.stop float()s it: the barrier
                        prof.maybe_stop(i)
                    if not sync:
                        tel.watchdog.disarm()
                        continue
                    sm = record_window(py_step, loss)
                    tel.watchdog.disarm()
                    window_n, window_wait = 0, 0.0
                    history.append(sm)
                    if on_metrics and (
                        se > 1 or i % self.cfg.log_every == 0
                    ):
                        on_metrics(sm)
                    with tel.tracer.span("eval"):
                        maybe_inloop_eval(self, py_step, eval_data, on_eval)
                    if ckpt is not None:
                        with tel.tracer.span("checkpoint"):
                            ckpt.save(py_step, self.state)
                    # Collective decision (see preemption.py): the whole
                    # gang breaks at the same step or not at all.
                    with tel.tracer.span("preemption_sync"):
                        stop = checkpoint_stop(
                            shutdown, ckpt, py_step, self.state,
                            watchdog=tel.watchdog,
                        )
                    if stop:
                        self.preempted = True
                        tel.events.emit(
                            "preemption_stop", level="warn", step=py_step
                        )
                        break
                # Iterator exhausted mid-window: flush the open window
                # so every executed step is metered and checkpointable.
                if window_n:
                    loss = m["loss"]  # Meter.stop float()s it: the barrier
                    tel.watchdog.arm()
                    sm = record_window(py_step, loss)
                    tel.watchdog.disarm()
                    history.append(sm)
                    if on_metrics:
                        on_metrics(sm)
                    if ckpt is not None:
                        with tel.tracer.span("checkpoint"):
                            ckpt.save(py_step, self.state)
        finally:
            # Flush even on a mid-loop crash: the trace and the last
            # checkpoint are exactly what post-mortems need.
            prof.close()
            if ckpt is not None:
                ckpt.wait()
                ckpt.close()
            if owns_shutdown:
                shutdown.uninstall()
            tel.events.emit(
                "run_end",
                steps=len(history),
                last_step=history[-1].step if history else start_step,
                preempted=self.preempted,
            )
            tel.close()
        return history
