"""Knowledge distillation: student training against a frozen teacher.

The reference ships no ML workloads at all (its "workload" is a
diagnostic CLI, reference README.md:314); distillation is the third
post-training workflow next to SFT (tpufw.train.sft) and DPO
(tpufw.train.dpo), and rides the same substrate: packed LM batches, the
Trainer's mesh/sharding/checkpoint/preemption loop, and a chunked-vocab
objective that never materializes [B, T, V] logits for EITHER model —
student and teacher logits are computed chunk-by-chunk inside one
``lax.scan`` (tpufw.ops.loss._chunk_seq layout) and reduced to a scalar
immediately.

Objective (Hinton et al. 2015 softened-softmax form):

  loss = alpha * T^2 * KL(softmax(teacher/T) || softmax(student/T))
       + (1 - alpha) * CE(student, hard labels)

The T^2 factor keeps gradient magnitude comparable across temperatures.
The teacher may be a DIFFERENT architecture (bigger d_model/layers) —
only the vocab must match; its forward runs OUTSIDE the grad closure
(no activations kept, bf16 weights by default).

Anchor invariant (tests/test_distill.py): teacher == student makes the
KL term exactly 0, so with alpha=1 the loss is 0 at step 0.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from tpufw.ops.loss import _chunk_seq
from tpufw.train.trainer import (
    Trainer,
    frozen_copy,
    head_kernel,
    shift_and_mask,
)


def chunked_distill_loss(
    student_hidden: jax.Array,
    student_kernel: jax.Array,
    teacher_hidden: jax.Array,
    teacher_kernel: jax.Array,
    targets: jax.Array,
    mask: jax.Array,
    temperature: float = 1.0,
    alpha: float = 0.5,
    chunk_size: int = 256,
    compute_dtype=jnp.bfloat16,
    student_soft_cap: Optional[float] = None,
    teacher_soft_cap: Optional[float] = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(total, kl, ce) masked means, chunked over the sequence axis.

    kl is the temperature-softened KL(teacher || student) * T^2; ce is
    the hard-label cross entropy (no z-loss — distillation already
    regularizes the student's distribution toward the teacher's).
    Student and teacher vocab sizes must match. The soft caps are each
    model's final-logit tanh cap (Gemma) — return_hidden skipped the
    models' own cap application, so it must be re-applied here BEFORE
    temperature scaling or a capped model distills the wrong
    distribution; the two can differ (different architectures).
    """
    if student_kernel.shape[-1] != teacher_kernel.shape[-1]:
        raise ValueError(
            f"student vocab {student_kernel.shape[-1]} != teacher vocab "
            f"{teacher_kernel.shape[-1]}: distillation KL needs one vocab"
        )
    mask = mask.astype(jnp.float32)
    hs, ts, ms = _chunk_seq(chunk_size, student_hidden, targets, mask)
    # Teacher hidden may have a different feature dim; _chunk_seq only
    # needs [B, T, D*]. targets/mask re-chunked identically (discarded).
    ht, _, _ = _chunk_seq(chunk_size, teacher_hidden, targets, mask)

    inv_t = 1.0 / temperature

    @jax.checkpoint
    def body(carry, xs):
        from tpufw.ops.attention import tanh_soft_cap

        h_s, h_t, t_c, m_c = xs
        s_logits = jnp.einsum(
            "bcd,dv->bcv",
            h_s.astype(compute_dtype),
            student_kernel.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        if student_soft_cap is not None:
            s_logits = tanh_soft_cap(s_logits, student_soft_cap)
        t_logits = jnp.einsum(
            "bcd,dv->bcv",
            h_t.astype(compute_dtype),
            teacher_kernel.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        if teacher_soft_cap is not None:
            t_logits = tanh_soft_cap(t_logits, teacher_soft_cap)
        s_logp = jax.nn.log_softmax(s_logits * inv_t, axis=-1)
        t_logp = jax.nn.log_softmax(t_logits * inv_t, axis=-1)
        t_p = jnp.exp(t_logp)
        # KL(t||s) per position; teacher term is constant in the student
        # but kept so the metric reads as a true KL (0 at equality).
        kl_tok = (t_p * (t_logp - s_logp)).sum(-1)
        ce_tok = -jnp.take_along_axis(
            jax.nn.log_softmax(s_logits, axis=-1), t_c[..., None], -1
        )[..., 0]
        kl_sum, ce_sum, n_sum = carry
        return (
            kl_sum + (kl_tok * m_c).sum(),
            ce_sum + (ce_tok * m_c).sum(),
            n_sum + m_c.sum(),
        ), None

    (kl_sum, ce_sum, n), _ = jax.lax.scan(
        body,
        (
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        ),
        (hs, ht, ts, ms),
    )
    n_safe = jnp.maximum(n, 1.0)
    kl = (temperature**2) * kl_sum / n_safe
    ce = ce_sum / n_safe
    return alpha * kl + (1.0 - alpha) * ce, kl, ce


@dataclasses.dataclass(frozen=True)
class DistillConfig:
    # Softmax temperature for both distributions (the KL term).
    temperature: float = 2.0
    # KL weight; (1 - alpha) goes to hard-label CE. 1.0 = pure KL.
    alpha: float = 0.5
    # Storage dtype of the frozen teacher weights.
    teacher_dtype: str = "bfloat16"


def distill_train_step(
    state,
    teacher_params,
    batch: dict,
    teacher_apply_fn=None,
    temperature: float = 2.0,
    alpha: float = 0.5,
    loss_chunk_size: int = 256,
    loss_chunk_dtype: str = "bfloat16",
    student_soft_cap: Optional[float] = None,
    teacher_soft_cap: Optional[float] = None,
):
    """One distillation update on a packed LM batch.

    The teacher forward (possibly a different architecture) runs outside
    the grad closure. MoE student aux loss joins the objective as in
    tpufw.train.trainer.batch_loss.
    """
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    dtype = jnp.dtype(loss_chunk_dtype)

    def hidden_of(apply_fn, params):
        out = apply_fn(
            {"params": params}, inputs, segment_ids=seg_in,
            return_hidden=True,
        )
        aux = 0.0
        if isinstance(out, tuple):
            out, aux = out
        return out, aux

    t_hidden, _ = hidden_of(teacher_apply_fn, teacher_params)
    t_hidden = jax.lax.stop_gradient(t_hidden)
    t_kernel = jax.lax.stop_gradient(head_kernel(teacher_params))

    def lf(params):
        s_hidden, aux = hidden_of(state.apply_fn, params)
        total, kl, ce = chunked_distill_loss(
            s_hidden, head_kernel(params), t_hidden, t_kernel,
            targets, mask if mask is not None else jnp.ones_like(
                targets, jnp.float32
            ),
            temperature=temperature, alpha=alpha,
            chunk_size=loss_chunk_size, compute_dtype=dtype,
            student_soft_cap=student_soft_cap,
            teacher_soft_cap=teacher_soft_cap,
        )
        return total + aux, (kl, ce)

    (loss, (kl, ce)), grads = jax.value_and_grad(lf, has_aux=True)(
        state.params
    )
    new_state = state.apply_gradients(grads)
    return new_state, {
        "loss": loss,
        "kl_loss": kl,
        "ce_loss": ce,
        "grad_norm": optax.global_norm(grads),
    }


class DistillTrainer(Trainer):
    """Trainer whose objective distills a frozen teacher into the
    (smaller) student ``model``. run()/checkpointing/preemption/metering
    are inherited; ``set_teacher`` must be called before the first step.

    The teacher's FLOPs are not charged in MFU — pass an adjusted
    ``model_flops_per_token`` to ``run`` if comparing against plain LM
    training (student 6N + teacher forward 2N_t per token).
    """

    def __init__(
        self,
        model,
        trainer_cfg,
        mesh_cfg=None,
        mesh=None,
        tx=None,
        distill: DistillConfig = DistillConfig(),
    ):
        super().__init__(model, trainer_cfg, mesh_cfg, mesh, tx)
        if trainer_cfg.grad_accum != 1:
            raise NotImplementedError(
                "DistillTrainer does not implement grad_accum; "
                "silently ignoring it would change optimization "
                "semantics vs the base Trainer"
            )
        self.distill = distill
        self.teacher_model = None
        self.teacher_params = None

    def _check_vocab(self, teacher_model):
        s_vocab = getattr(getattr(self.model, "cfg", None), "vocab_size", None)
        t_vocab = getattr(
            getattr(teacher_model, "cfg", None), "vocab_size", None
        )
        if s_vocab is not None and t_vocab is not None and s_vocab != t_vocab:
            raise ValueError(
                f"teacher vocab {t_vocab} != student vocab {s_vocab}"
            )

    def _teacher_layout(self, teacher_model):
        """(abstract param tree, mesh shardings) for the teacher: lay it
        out with the same logical rules as any param tree — a
        multi-B-param teacher held unsharded would OOM exactly the
        configurations chunked logits exist to fit. eval_shape under
        the mesh recovers the flax Partitioned metadata an unboxed tree
        no longer carries."""
        from flax import linen as nn
        from flax.core import meta

        from tpufw.mesh import logical_axis_rules
        from tpufw.parallel.context import use_mesh

        tokens = jnp.zeros((1, 8), jnp.int32)
        with use_mesh(self.mesh):
            abstract = jax.eval_shape(
                lambda r: teacher_model.init(r, tokens)["params"],
                jax.random.key(0),
            )
        specs = nn.get_partition_spec(abstract)
        shardings = meta.unbox(
            nn.logical_to_mesh_sharding(
                specs, self.mesh, logical_axis_rules()
            )
        )
        return meta.unbox(abstract), shardings

    def set_teacher(self, teacher_model, teacher_params):
        """Install the frozen teacher (any decoder with the student's
        vocab). Params are cast to ``teacher_dtype`` through jit so the
        stored tree never aliases donated buffers, and laid out on the
        mesh (see ``_teacher_layout``)."""
        self._check_vocab(teacher_model)
        _, self._teacher_sharding = self._teacher_layout(teacher_model)
        self.teacher_model = teacher_model
        self.teacher_params = frozen_copy(
            teacher_params,
            jnp.dtype(self.distill.teacher_dtype),
            out_shardings=self._teacher_sharding,
        )

    def set_teacher_from(self, teacher_model, path: str):
        """Install the teacher from a bare-params Orbax checkpoint (the
        ``tpufw.tools.import_hf`` output shape), restored SHARDED onto
        this trainer's mesh — never materialized on one host."""
        import os

        import orbax.checkpoint as ocp

        self._check_vocab(teacher_model)
        abstract, shardings = self._teacher_layout(teacher_model)
        restore_tree = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            abstract,
            shardings,
        )
        with ocp.StandardCheckpointer() as ckptr:
            params = ckptr.restore(os.path.abspath(path), restore_tree)
        self._teacher_sharding = shardings
        self.teacher_model = teacher_model
        self.teacher_params = frozen_copy(
            params,
            jnp.dtype(self.distill.teacher_dtype),
            out_shardings=shardings,
        )

    def compiled_step(self, batch: dict | None = None):
        from functools import partial

        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if self.teacher_params is None:
            raise RuntimeError(
                "distillation step before set_teacher(): install the "
                "frozen teacher first"
            )
        key = (
            ("distill", "tokens")
            if batch is None
            else ("distill", *sorted(batch.keys()))
        )
        if key not in self._compiled:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in key[1:]}
            t_cap = getattr(
                getattr(self.teacher_model, "cfg", None),
                "final_logit_soft_cap", None,
            )
            jitted = jax.jit(
                partial(
                    distill_train_step,
                    teacher_apply_fn=self.teacher_model.apply,
                    temperature=self.distill.temperature,
                    alpha=self.distill.alpha,
                    loss_chunk_size=self.cfg.loss_chunk_size or 256,
                    loss_chunk_dtype=self.cfg.loss_chunk_dtype,
                    student_soft_cap=self._final_soft_cap(),
                    teacher_soft_cap=t_cap,
                ),
                in_shardings=(
                    self.state_sharding,
                    self._teacher_sharding,
                    batch_sharding,
                ),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            )
            self._compiled[key] = lambda state, b: jitted(
                state, self.teacher_params, b
            )
        return self._compiled[key]
