"""Device prefetch: overlap host->device batch transfer with the step.

Without this the H2D copy of each batch sits on the critical path of
``Trainer.run``'s dispatch. A one-deep background thread keeps the next
batch already resident (sharded row-wise over data+fsdp, matching the
trainer's batch sharding) while the current step computes — the input-
pipeline overlap a GPU stack gets from dataloader workers + pinned-memory
copies, done the JAX way with ``jax.device_put`` onto a NamedSharding.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_END = object()


def prefetch_to_device(
    batches: Iterator[dict],
    mesh: Mesh,
    spec: Optional[P] = None,
    buffer_size: Optional[int] = None,
) -> Iterator[dict]:
    """Yield device-resident batches one transfer ahead of consumption.

    ``spec`` defaults to row-sharding over ("data", "fsdp") — the trainer's
    batch layout. ``buffer_size`` defaults to ``TPUFW_PREFETCH_DEPTH``
    (2): depth 1 can stall the step on a slow host read, deeper buffers
    pin more batches in HBM. Exceptions in the source iterator propagate
    to the consumer at the point of the failed batch.
    """
    if buffer_size is None:
        from tpufw.workloads.env import env_int

        buffer_size = max(1, env_int("prefetch_depth", 2))
    sharding = NamedSharding(
        mesh, spec if spec is not None else P(("data", "fsdp"))
    )
    if jax.process_count() > 1:
        # Each process's iterator yields its LOCAL rows; assemble into a
        # global array (device_put with a multi-host sharding is invalid).
        transfer = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
            sharding, x
        )
    else:
        transfer = lambda x: jax.device_put(x, sharding)  # noqa: E731
    q: queue.Queue = queue.Queue(maxsize=buffer_size)
    abandoned = threading.Event()

    def put(item) -> bool:
        # Bounded put that gives up once the consumer is gone — a plain
        # q.put would block forever when the consumer stops early (the
        # normal case: Trainer.run breaks at total_steps on an infinite
        # corpus stream), leaking the thread, HBM batches, and the
        # source's native handle.
        while not abandoned.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            try:
                for batch in batches:
                    device_batch = jax.tree.map(transfer, batch)
                    if not put(device_batch):
                        return
            finally:
                close = getattr(batches, "close", None)
                if close:
                    close()  # runs the source's finally (native handles)
        except BaseException as e:  # re-raised on the consumer side
            put((_END, e))
            return
        put((_END, None))

    # Named so hang-watchdog stack dumps identify it (an unnamed
    # "Thread-3" wedged in device_put is unattributable).
    t = threading.Thread(
        target=worker, daemon=True, name="tpufw-prefetch"
    )
    t.start()
    try:
        while True:
            item = q.get()
            if (
                isinstance(item, tuple)
                and len(item) == 2
                and item[0] is _END
            ):
                if item[1] is not None:
                    raise item[1]
                return
            yield item
    finally:
        abandoned.set()
