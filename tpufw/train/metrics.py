"""Training metrics: tokens/sec/chip and MFU as first-class measured outputs.

BASELINE's headline metric is tokens/sec/chip for Llama-3-8B and >=35% MFU on
v5e-16 (SURVEY.md §6); the reference has no metrics at all (its verification
channel is ``kubectl logs`` of ``nvidia-smi``, reference ``README.md:331-335``).
MFU here is *model* FLOPs utilization: analytic model FLOPs per token (from
the model config) — not XLA's executed-FLOPs counter, which would reward
rematerialization for doing extra work.
"""

from __future__ import annotations

import dataclasses
import time

from tpufw.utils.hardware import ChipSpec, detect_chip


@dataclasses.dataclass
class StepMetrics:
    step: int
    loss: float
    step_time_s: float
    tokens_per_sec_per_chip: float
    mfu: float
    # Host time spent waiting on the data iterator BEFORE this step —
    # input-boundness is invisible in step_time (the fetch happens
    # between steps), so it gets its own number.
    data_wait_s: float = 0.0
    # Steps averaged into this entry (sync_every > 1 measures a WINDOW
    # of asynchronously-dispatched steps per host sync; step/loss are
    # the window's last step's).
    window_steps: int = 1

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Meter:
    """Accumulates step timings and converts to tokens/sec/chip + MFU.

    ``flops_per_token`` comes from ``config.flops_per_token(seq_len)``;
    ``n_chips`` is the global device count (the denominator that makes
    tokens/sec/chip comparable across slice sizes).
    """

    def __init__(
        self,
        tokens_per_step: int,
        flops_per_token: float,
        n_chips: int,
        chip: ChipSpec | None = None,
        registry=None,
    ):
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.n_chips = max(n_chips, 1)
        self.chip = chip or detect_chip()
        self._t0: float | None = None
        # Optional tpufw.obs.Registry: every stop() publishes the
        # window into the shared scrape surface (histograms for the
        # time distributions, gauges for the point-in-time headline).
        self.registry = registry
        if registry is not None:
            self._c_steps = registry.counter(
                "tpufw_train_steps_total", "optimizer steps completed"
            )
            self._c_tokens = registry.counter(
                "tpufw_train_tokens_total", "target tokens trained on"
            )
            self._h_step = registry.histogram(
                "tpufw_train_step_time_seconds",
                "per-step wall time (window average when sync_every > 1)",
            )
            self._h_wait = registry.histogram(
                "tpufw_train_data_wait_seconds",
                "per-step host wait on the input pipeline",
            )
            self._g_step = registry.gauge(
                "tpufw_train_step", "last synced optimizer step"
            )
            self._g_loss = registry.gauge(
                "tpufw_train_loss", "loss at the last synced step"
            )
            self._g_mfu = registry.gauge(
                "tpufw_train_mfu", "model FLOPs utilization (0..1)"
            )
            self._g_tps = registry.gauge(
                "tpufw_train_tokens_per_sec_per_chip",
                "throughput per chip",
            )

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(
        self,
        step: int,
        loss: float,
        data_wait_s: float = 0.0,
        n_steps: int = 1,
    ) -> StepMetrics:
        """``n_steps`` > 1: the elapsed time covers a window of that
        many dispatched steps (one host sync per window); throughput,
        step time, AND data_wait_s (pass the window's summed wait) are
        all attributed per step, so their units stay consistent."""
        if self._t0 is None:
            raise RuntimeError("Meter.stop() without start()")
        # The loss FETCH is the window barrier and must happen before
        # the clock is read: jax.block_until_ready can return while the
        # step is still executing on a tunneled PJRT backend (measured
        # in r3 — 1.4 ms/step "synced" vs 253 ms real), so a caller's
        # pre-sync cannot be trusted. float() forces a device->host
        # value read, which is the only sync that can't lie.
        loss = float(loss)
        n = max(n_steps, 1)
        dt = (time.perf_counter() - self._t0) / n
        data_wait_s = data_wait_s / n
        self._t0 = None
        tps_chip = self.tokens_per_step / dt / self.n_chips
        mfu = tps_chip * self.flops_per_token / self.chip.peak_bf16_flops
        if self.registry is not None:
            self._c_steps.inc(n)
            self._c_tokens.inc(self.tokens_per_step * n)
            # Per-step averages observed n times: _sum/_count aggregate
            # to the window's exact totals (see Histogram.observe).
            self._h_step.observe(dt, n=n)
            self._h_wait.observe(data_wait_s, n=n)
            self._g_step.set(step)
            self._g_loss.set(loss)
            self._g_mfu.set(mfu)
            self._g_tps.set(tps_chip)
        return StepMetrics(
            step=step,
            loss=loss,
            step_time_s=dt,
            tokens_per_sec_per_chip=tps_chip,
            mfu=mfu,
            data_wait_s=data_wait_s,
            window_steps=n_steps,
        )


def timed_batches(data):
    """Wrap an iterator, yielding (data_wait_s, batch) — the ONE place
    host blocking on the input pipeline is measured (all three trainer
    loops use it)."""
    it = iter(data)
    while True:
        t0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            return
        yield time.perf_counter() - t0, batch
