"""Direct Preference Optimization: preference-pair fine-tuning.

The reference ships no ML workloads at all (its "workload" is a
diagnostic CLI, reference README.md:314); DPO is the alignment step real
users run after SFT (tpufw.train.sft), so it rides the same substrate:
chat templates render prompts, responses are the trained spans, and the
trainer is a thin subclass of tpufw.train.trainer.Trainer — same mesh,
sharding, checkpointing, preemption, and metering.

TPU-first shape discipline: each batch is ``[2B, T]`` with pairs
INTERLEAVED — row 2i is pair i's chosen, row 2i+1 its rejected — so ONE
model forward covers both halves and the pairwise split is a strided
[2B] vector slice after the per-row reduction; no ragged shapes, no
second program. Interleaving (not chosen-first/rejected-last) is what
makes multi-process data loading correct: the global batch is a
concatenation of per-process blocks, and a stride-2 split stays
pair-aligned under ANY concatenation of even-sized interleaved blocks,
where a half-split would pair rows across unrelated processes. Both
the policy and the frozen reference score sequences through
``chunked_sequence_logprob`` (tpufw.ops.loss), so [B, T, V] logits are
never materialized; the reference forward runs OUTSIDE the grad closure
(no activations kept) with bf16-cast weights.

Objective (Rafailov et al. 2023, plus conservative-DPO label smoothing):

  r_c = beta * (log pi(y_c|x) - log ref(y_c|x))     # "rewards"
  r_r = beta * (log pi(y_r|x) - log ref(y_r|x))
  loss = -(1 - ls) * log sigmoid(r_c - r_r) - ls * log sigmoid(r_r - r_c)

At step 0 with ref == policy every reward is exactly 0, so
loss == log 2 and accuracy == 0.5 — pinned by tests/test_dpo.py.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Callable, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpufw.train.sft import _TEMPLATES, render_conversation
from tpufw.train.trainer import (
    Trainer,
    frozen_copy,
    head_kernel,
    shift_and_mask,
)

# ----------------------------------------------------------------------
# Data: preference pairs -> [2B, T] batches
# ----------------------------------------------------------------------


def read_pairs(path: str | pathlib.Path) -> Iterator[dict]:
    """JSONL preference pairs: {"prompt": <str | message list>,
    "chosen": <str>, "rejected": <str>} per line (the common export
    shape of preference datasets)."""
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            if not (
                isinstance(obj, dict)
                and "prompt" in obj
                and isinstance(obj.get("chosen"), str)
                and isinstance(obj.get("rejected"), str)
            ):
                raise ValueError(
                    f"{path}:{ln}: expected "
                    '{"prompt": ..., "chosen": str, "rejected": str}'
                )
            yield obj


def encode_pair(
    pair: dict,
    encode: Callable[[str], List[int]],
    template: str = "plain",
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """One pair -> (tokens_c, mask_c, tokens_r, mask_r).

    The prompt (string = a single user turn, or a full message list) is
    rendered through the SFT chat template INCLUDING the assistant
    header, so both responses continue from the identical context; the
    response content + end-of-turn footer are the trained span — the
    same mask convention as tpufw.train.sft.encode_conversation.
    """
    prompt = pair["prompt"]
    if isinstance(prompt, str):
        prompt = [{"role": "user", "content": prompt}]
    ctx: List[int] = []
    # render_conversation validates the template name (the one
    # canonical check); the direct lookup below can then only succeed.
    for text, _ in render_conversation(prompt, template):
        ctx.extend(encode(text))
    t = _TEMPLATES[template]
    ctx.extend(encode(t["header"].format(role="assistant")))

    rows = []
    for resp in (pair["chosen"], pair["rejected"]):
        resp_ids = encode(resp) + encode(t["footer"])
        toks = np.asarray(ctx + resp_ids, np.int32)
        mask = np.zeros(len(toks), np.float32)
        mask[len(ctx):] = 1.0
        rows.append((toks, mask))
    (tc, mc), (tr, mr) = rows
    return tc, mc, tr, mr


def _pad_row(
    toks: np.ndarray, mask: np.ndarray, seq_len: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Right-pad one fitted row to ``seq_len`` (padding is segment 0)."""
    n = len(toks)
    out_t = np.zeros(seq_len, np.int32)
    out_m = np.zeros(seq_len, np.float32)
    seg = np.zeros(seq_len, np.int32)
    out_t[:n], out_m[:n], seg[:n] = toks, mask, 1
    return out_t, out_m, seg


def _fit_pair(
    tc: np.ndarray,
    mc: np.ndarray,
    tr: np.ndarray,
    mr: np.ndarray,
    seq_len: int,
):
    """Fit BOTH rows of a pair to ``seq_len`` with one shared left
    truncation: both rows drop the same count of OLDEST prompt tokens
    (the pair's worst-case overflow), so chosen and rejected keep the
    IDENTICAL prompt suffix. Truncating each row independently would
    score the two responses against different contexts — a systematic
    length-correlated reward bias (DPO conditions both on the same x).
    """
    drop = max(len(tc), len(tr)) - seq_len
    if drop > 0:
        resp = max(int(mc.sum()), int(mr.sum()))
        if resp >= seq_len:
            raise ValueError(
                f"response ({resp} tokens) does not fit in "
                f"seq_len={seq_len}; raise seq_len or filter the pair"
            )
        # drop <= prompt length: both rows share the prompt, and the
        # longer row is prompt + its response < prompt + seq_len.
        tc, mc = tc[drop:], mc[drop:]
        tr, mr = tr[drop:], mr[drop:]
    return _pad_row(tc, mc, seq_len), _pad_row(tr, mr, seq_len)


def dpo_batches(
    path: str | pathlib.Path,
    batch_pairs: int,
    seq_len: int,
    encode: Callable[[str], List[int]],
    template: str = "plain",
    epochs: Optional[int] = None,
    seed: int = 0,
    shard_id: int = 0,
    num_shards: int = 1,
) -> Iterator[dict]:
    """Yield [2B, T] DPO batches (B = ``batch_pairs``): row 2i is pair
    i's chosen, row 2i+1 its rejected (the interleaved layout
    ``dpo_loss_from_logps`` splits with a stride-2 slice — see the
    module docstring for why interleaving is the multi-process-safe
    choice). Pairs are sharded disjointly across processes BEFORE
    shuffling (same contract as tpufw.train.sft.sft_batches) and
    reshuffled each epoch; ``epochs=None`` cycles forever."""
    pairs = list(read_pairs(path))
    if not pairs:
        raise ValueError(f"{path}: no preference pairs")
    pairs = pairs[shard_id::num_shards]
    encoded = [encode_pair(p, encode, template) for p in pairs]
    if len(encoded) < batch_pairs:
        # An undersized shard would yield ZERO batches — with
        # epochs=None that is an infinite permute-nothing spin, so fail
        # loudly instead (sft_batches raises on its empty-shard analog).
        raise ValueError(
            f"{path}: shard {shard_id}/{num_shards} holds "
            f"{len(encoded)} pairs < batch_pairs={batch_pairs}"
        )
    rng = np.random.default_rng(seed)
    epoch = 0
    while epochs is None or epoch < epochs:
        order = rng.permutation(len(encoded))
        for start in range(0, len(order) - batch_pairs + 1, batch_pairs):
            idx = order[start:start + batch_pairs]
            toks = np.zeros((2 * batch_pairs, seq_len), np.int32)
            mask = np.zeros((2 * batch_pairs, seq_len), np.float32)
            seg = np.zeros((2 * batch_pairs, seq_len), np.int32)
            for row, i in enumerate(idx):
                tc, mc, tr, mr = encoded[i]
                (
                    (toks[2 * row], mask[2 * row], seg[2 * row]),
                    (
                        toks[2 * row + 1],
                        mask[2 * row + 1],
                        seg[2 * row + 1],
                    ),
                ) = _fit_pair(tc, mc, tr, mr, seq_len)
            yield {
                "tokens": toks,
                "loss_mask": mask,
                "segment_ids": seg,
            }
        epoch += 1


# ----------------------------------------------------------------------
# Objective
# ----------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DPOConfig:
    # Reward scale: how hard the policy is pushed away from the
    # reference. The standard operating range is 0.1-0.5.
    beta: float = 0.1
    # Conservative DPO (label noise robustness): 0 = the pure objective.
    label_smoothing: float = 0.0
    # Storage dtype of the frozen reference weights (its forward is
    # score-only, so serving precision is enough; halves the extra HBM).
    ref_dtype: str = "bfloat16"


def _sequence_logps(
    apply_fn,
    params,
    inputs,
    targets,
    seg_in,
    mask,
    chunk_size: int,
    compute_dtype,
    soft_cap,
):
    """[2B] per-row response logprob sums (+ MoE aux loss, 0.0 for
    dense models) through the chunked head path."""
    from tpufw.ops.loss import chunked_sequence_logprob

    out = apply_fn(
        {"params": params}, inputs, segment_ids=seg_in, return_hidden=True
    )
    aux = 0.0
    if isinstance(out, tuple):
        out, aux = out
    logps = chunked_sequence_logprob(
        out, head_kernel(params), targets, mask,
        chunk_size=chunk_size, compute_dtype=compute_dtype,
        logits_soft_cap=soft_cap,
    )
    return logps, aux


def dpo_loss_from_logps(
    policy_logps: jax.Array,
    ref_logps: jax.Array,
    beta: float,
    label_smoothing: float = 0.0,
) -> tuple[jax.Array, dict]:
    """[2B] INTERLEAVED (even = chosen, odd = rejected) policy /
    reference logprob sums -> (scalar loss, metrics)."""
    rewards = beta * (policy_logps - ref_logps)
    r_c, r_r = rewards[0::2], rewards[1::2]
    margin = r_c - r_r
    ls = label_smoothing
    loss = (
        -(1.0 - ls) * jax.nn.log_sigmoid(margin)
        - ls * jax.nn.log_sigmoid(-margin)
    ).mean()
    metrics = {
        # Exact ties count 0.5 ("coin flip"), so the step-0 anchor
        # (ref == policy, margin identically 0) reads 0.5, not 0.
        "accuracy": (
            (margin > 0).astype(jnp.float32)
            + 0.5 * (margin == 0).astype(jnp.float32)
        ).mean(),
        "margin": margin.mean(),
        "reward_chosen": r_c.mean(),
        "reward_rejected": r_r.mean(),
    }
    return loss, metrics


def dpo_train_step(
    state,
    ref_params,
    batch: dict,
    beta: float = 0.1,
    label_smoothing: float = 0.0,
    loss_chunk_size: int = 256,
    loss_chunk_dtype: str = "bfloat16",
    final_logit_soft_cap: Optional[float] = None,
):
    """One DPO optimizer update on a [2B, T] chosen/rejected batch.

    The reference forward runs outside the grad closure — no gradient,
    no saved activations; the policy forward + per-row chunked logprob
    reduction is the only differentiated region. MoE router aux loss
    (load balancing) joins the objective from the POLICY forward, as in
    tpufw.train.trainer.batch_loss.
    """
    inputs, targets, seg_in, mask = shift_and_mask(batch)
    if mask is None:
        raise ValueError(
            "DPO batch has neither loss_mask nor segment_ids: without a "
            "response mask the pairwise logprob sums would score entire "
            "rows (prompt included) — use tpufw.train.dpo.dpo_batches"
        )
    dtype = jnp.dtype(loss_chunk_dtype)

    ref_logps, _ = _sequence_logps(
        state.apply_fn, ref_params, inputs, targets, seg_in, mask,
        loss_chunk_size, dtype, final_logit_soft_cap,
    )
    ref_logps = jax.lax.stop_gradient(ref_logps)

    def lf(params):
        logps, aux = _sequence_logps(
            state.apply_fn, params, inputs, targets, seg_in, mask,
            loss_chunk_size, dtype, final_logit_soft_cap,
        )
        loss, metrics = dpo_loss_from_logps(
            logps, ref_logps, beta, label_smoothing
        )
        return loss + aux, metrics

    (loss, metrics), grads = jax.value_and_grad(lf, has_aux=True)(
        state.params
    )
    import optax

    new_state = state.apply_gradients(grads)
    return new_state, {
        "loss": loss,
        "grad_norm": optax.global_norm(grads),
        **metrics,
    }


# ----------------------------------------------------------------------
# Trainer
# ----------------------------------------------------------------------


class DPOTrainer(Trainer):
    """tpufw.train.trainer.Trainer specialized for preference pairs:
    run()/checkpointing/preemption/metering are inherited verbatim; only
    the compiled step (and the frozen reference tree it closes over)
    differs.

    ``TrainerConfig.batch_size`` must be the ROW count 2B (what
    ``dpo_batches(batch_pairs=B)`` emits) — rows are what shard over
    data x fsdp. MFU/tokens metrics count all 2B rows; the reference
    forward's FLOPs are not charged by default. flops_per_token is the
    6N train convention (fwd 2N + bwd 4N); DPO adds one ref forward
    (2N) per row, so pass ``model_flops_per_token * 4 / 3`` to ``run``
    for exact accounting when comparing MFU against plain LM training.
    """

    def __init__(
        self,
        model,
        trainer_cfg,
        mesh_cfg=None,
        mesh=None,
        tx=None,
        dpo: DPOConfig = DPOConfig(),
    ):
        super().__init__(model, trainer_cfg, mesh_cfg, mesh, tx)
        if trainer_cfg.batch_size % 2:
            raise ValueError(
                f"DPO batch_size is the ROW count 2B; got odd "
                f"{trainer_cfg.batch_size}"
            )
        if trainer_cfg.grad_accum != 1:
            raise NotImplementedError(
                "DPO does not implement grad_accum: microbatch slicing "
                "would split chosen rows from their rejected partners"
            )
        self.dpo = dpo
        self.ref_params = None

    # -- reference snapshot ------------------------------------------------

    def _snapshot_reference(self):
        """Freeze the CURRENT policy params as the reference (cast to
        ref_dtype). Correct at step 0 — after SFT import or fresh init —
        which is exactly when DPO starts."""
        self.ref_params = frozen_copy(
            self.state.params, jnp.dtype(self.dpo.ref_dtype)
        )

    def init_state(self, seed: int = 0):
        out = super().init_state(seed)
        self._snapshot_reference()
        return out

    def init_from_params(self, path: str, seed: int = 0):
        out = super().init_from_params(path, seed)
        self._snapshot_reference()
        return out

    def maybe_restore(self) -> bool:
        """Mid-run resume: the restored POLICY must not become the
        reference — re-snapshot only when no reference exists yet (a
        resumed run keeps the one captured at step 0 only if the caller
        restores it; without a checkpointed copy we refuse rather than
        silently anchor to the moved policy)."""
        restored = super().maybe_restore()
        if restored and int(self.state.step) > 0 and self.ref_params is None:
            raise RuntimeError(
                "resumed a DPO run mid-training without a reference "
                "snapshot: call init_from_params on the ORIGINAL base "
                "checkpoint first (the reference must anchor to step-0 "
                "weights, not the resumed policy)"
            )
        if self.ref_params is None and self.state is not None:
            self._snapshot_reference()
        return restored

    # -- compiled step -----------------------------------------------------

    def compiled_step(self, batch: dict | None = None):
        from functools import partial

        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if self.ref_params is None:
            raise RuntimeError(
                "DPO step before reference snapshot: call init_state() "
                "or init_from_params() first"
            )
        key = (
            ("dpo", "tokens")
            if batch is None
            else ("dpo", *sorted(batch.keys()))
        )
        if key not in self._compiled:
            row = NamedSharding(self.mesh, P(("data", "fsdp")))
            batch_sharding = {k: row for k in key[1:]}
            jitted = jax.jit(
                partial(
                    dpo_train_step,
                    beta=self.dpo.beta,
                    label_smoothing=self.dpo.label_smoothing,
                    loss_chunk_size=self.cfg.loss_chunk_size or 256,
                    loss_chunk_dtype=self.cfg.loss_chunk_dtype,
                    final_logit_soft_cap=self._final_soft_cap(),
                ),
                in_shardings=(
                    self.state_sharding,
                    self.state_sharding.params,
                    batch_sharding,
                ),
                out_shardings=(self.state_sharding, None),
                donate_argnums=(0,),
            )
            self._compiled[key] = lambda state, b: jitted(
                state, self.ref_params, b
            )
        return self._compiled[key]
