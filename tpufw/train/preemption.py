"""Preemption-aware graceful shutdown: SIGTERM → checkpoint → clean exit.

Kubernetes terminates pods by sending SIGTERM and waiting
``terminationGracePeriodSeconds`` before SIGKILL — that window is the whole
elastic-recovery budget. The reference's only recovery primitive is
``restartPolicy: OnFailure`` (reference README.md:309), i.e. die and redo;
SURVEY.md §5 mandates the real thing: a preempted trainer should save a
final checkpoint inside the grace window so the JobSet gang restart resumes
from the *current* step, not the last periodic save.

The subtlety is multi-host: every pod in the gang receives SIGTERM, but not
between the same two steps — clocks and signal delivery skew. If process A
decides "stop after step N" while process B decides "stop after step N+1",
B blocks forever in step N+1's collectives. The stop decision must
therefore itself be collective: each step, processes agree on
``any(local_flag)`` via a tiny all-gather, so the gang always stops — and
checkpoints — at the same step. (Single-process runs skip the collective.)
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class GracefulShutdown:
    """Latches termination signals and turns them into a gang-consistent
    per-step stop decision.

    Usage::

        shutdown = GracefulShutdown()          # installs SIGTERM handler
        for step, batch in enumerate(data):
            train(batch)
            if shutdown.should_stop():         # collective across processes
                ckpt.save(step, state, force=True)
                break

    Handlers chain: a previously-installed Python-level handler still runs
    after the flag is latched. Installation is skipped (flag-only mode) off
    the main thread, where CPython forbids ``signal.signal``.
    """

    def __init__(
        self,
        signals: tuple = (signal.SIGTERM,),
        sync_every: int = 1,
        events=None,
    ):
        self._flag = threading.Event()
        self._prev: dict = {}
        self._signals = tuple(signals)
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        self._sync_every = sync_every
        self._calls = 0
        self._stop_latched = False
        # tpufw.obs event log (or None): the signal itself is logged,
        # so the gap between SIGTERM and the gang's agreed stop step is
        # measurable from the event stream.
        self.events = events
        for sig in self._signals:
            try:
                self._prev[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # Not the main thread — signals can't be installed here;
                # request() still works (tests, embedded use).
                self._prev.pop(sig, None)

    def _handle(self, signum, frame):
        self._flag.set()
        if self.events is not None:
            try:
                self.events.emit(
                    "preemption_signal", level="warn", signum=int(signum)
                )
            except Exception:  # noqa: BLE001 — never die in a handler
                pass
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def request(self) -> None:
        """Set the local stop flag programmatically (what the signal does)."""
        self._flag.set()

    @property
    def requested(self) -> bool:
        """This process's local flag — NOT gang-safe; use should_stop()."""
        return self._flag.is_set()

    def should_stop(self) -> bool:
        """Gang-consistent stop decision: True iff ANY process has latched
        a signal. Every process must call this the same number of times
        (it is a collective when process_count > 1) — call it exactly once
        per training step. Once True, stays True without further
        collectives. ``sync_every`` amortizes the all-gather: non-sync
        calls return the last agreed value, so a stop is acted on within
        ``sync_every`` steps of the signal."""
        if self._stop_latched:
            return True
        self._calls += 1
        if (self._calls - 1) % self._sync_every:
            return False
        import jax

        if jax.process_count() == 1:
            self._stop_latched = self._flag.is_set()
            return self._stop_latched

        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._flag.is_set()], dtype=np.int32)
        )
        self._stop_latched = bool(np.asarray(flags).sum() > 0)
        return self._stop_latched

    def uninstall(self) -> None:
        """Restore the previous signal handlers (tests / nested use).

        ``prev`` is None when the prior disposition was installed at the
        C level (signal.signal couldn't report it) — irrestorable from
        Python, so our (now inert: chains to nothing, sets a flag nobody
        reads) handler stays rather than guessing SIG_DFL.
        """
        for sig, prev in self._prev.items():
            if prev is None:
                continue
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()

    def __enter__(self) -> "GracefulShutdown":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.uninstall()
        return None


def owned_shutdown(
    shutdown: Optional[GracefulShutdown],
    enabled: bool,
    sync_every: int,
    events=None,
) -> tuple[Optional[GracefulShutdown], bool]:
    """Trainer-side ownership helper: construct a GracefulShutdown iff the
    caller passed none and the config enables handling. Returns
    (shutdown, owns); the caller must ``uninstall()`` in its run-loop
    ``finally`` when ``owns`` — call this LAST in run() setup, right
    before that try, so a setup failure can't leak the signal handler.
    """
    if shutdown is not None or not enabled:
        return shutdown, False
    return GracefulShutdown(sync_every=sync_every, events=events), True


def checkpoint_stop(
    shutdown: Optional[GracefulShutdown], ckpt, step: int, state,
    watchdog=None,
) -> bool:
    """The per-step stop block shared by every trainer loop: gang-consistent
    stop check (call exactly once per step — it is a collective), and on
    stop a forced checkpoint of ``step`` so the restart resumes here.
    Returns True when the loop should break. ``watchdog`` (a
    ``tpufw.obs.health.HangWatchdog``) is disarmed before the forced
    save: the final checkpoint races the SIGKILL grace window and has
    no bounded duration, so it must not read as a hang (let alone
    trigger an abort that forfeits the save)."""
    if shutdown is None or not shutdown.should_stop():
        return False
    if watchdog is not None:
        watchdog.disarm()
    if ckpt is not None:
        ckpt.save(step, state, force=True)
    return True
