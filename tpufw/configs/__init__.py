from tpufw.configs.presets import BENCH_CONFIG_NAME, bench_model_config  # noqa: F401
