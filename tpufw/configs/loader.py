"""YAML-of-record run configs (SURVEY.md §5 "Config/flag system").

The reference configures everything as literal values inside commands
(driver version at README.md:67, pod CIDR at README.md:198, GPU count at
README.md:317); tpufw's equivalent is **one YAML file of record per
BASELINE config** under ``deploy/configs/``, loaded here into the plain
dataclasses the code already uses — no bespoke flag DSL.

Resolution order (lowest to highest precedence):

  YAML file (``TPUFW_CONFIG=<path>`` or an explicit ``load_run_config``)
    < ``TPUFW_*`` env vars (what the deploy manifests set)

so a manifest can point at the YAML of record and override only what is
deployment-specific (checkpoint dir, step count).  ``to_env`` renders a
RunConfig back to the ``TPUFW_*`` dict, which is how the tests prove the
deploy manifests and the YAML of record agree instead of drifting.

Schema (all sections optional except ``model``)::

    name: llama3-8b-v5e16
    hardware: {slice: v5e-16, topology: 4x4, hosts: 4, chips_per_host: 4}
    model:
      preset: llama3_8b          # LLAMA_CONFIGS / MIXTRAL_CONFIGS /
                                 # llama3_600m_bench / resnet50
      overrides: {attention_backend: flash}   # dataclasses.replace fields
    trainer:  {batch_size: 32, seq_len: 2048, ...}   # TrainerConfig fields
    mesh:     {fsdp: 16}                             # MeshConfig fields
    pipeline: {n_stages: 2, n_microbatches: 4,       # PipelineConfig
               schedule: gpipe}  # or 1f1b (O(stages) activation memory)
                                 # (sizes mesh.pipe; train_pipeline runs)

Unknown keys anywhere are hard errors — config drift should fail loudly at
load time, not silently at step 1000.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
from dataclasses import dataclass
from typing import Any, Optional

import yaml

from tpufw.mesh import MeshConfig
from tpufw.train.trainer import TrainerConfig
from tpufw.train.vision import VisionTrainerConfig

#: Fields whose YAML spelling maps to a dtype object on the model config.
_DTYPE_FIELDS = ("dtype", "param_dtype")


@dataclass(frozen=True)
class HardwareConfig:
    """Slice shape of record — what the manifest's nodeSelector must match."""

    slice: str = "v5e-1"
    topology: Optional[str] = None
    hosts: int = 1
    chips_per_host: int = 1

    @property
    def n_chips(self) -> int:
        return self.hosts * self.chips_per_host


@dataclass(frozen=True)
class RunConfig:
    name: str
    hardware: HardwareConfig
    model_preset: str
    model_cfg: Any  # LlamaConfig | MixtralConfig | ResNetConfig
    trainer: Any  # TrainerConfig (LM) | VisionTrainerConfig (resnet)
    mesh: MeshConfig
    pipeline: Any = None  # Optional[PipelineConfig] (train_pipeline runs)

    @property
    def family(self) -> str:
        return type(self.model_cfg).__name__.removesuffix("Config").lower()


def _reject_unknown(section: str, given: dict, allowed: set[str]) -> None:
    unknown = set(given) - allowed
    if unknown:
        raise ValueError(
            f"{section}: unknown keys {sorted(unknown)}; "
            f"allowed: {sorted(allowed)}"
        )


def _build_dataclass(cls, section: str, given: dict):
    fields = {f.name for f in dataclasses.fields(cls)}
    _reject_unknown(section, given, fields)
    return cls(**given)


def resolve_model_preset(preset: str):
    """Public preset-name -> model config resolution (the ONE registry;
    the import/export CLI uses it too)."""
    return _resolve_preset(preset)


def _resolve_preset(preset: str):
    from tpufw.configs.presets import BENCH_CONFIG_NAME, bench_model_config
    from tpufw.models import (
        DEEPSEEK_CONFIGS,
        GEMMA_CONFIGS,
        LLAMA_CONFIGS,
        MIXTRAL_CONFIGS,
    )
    from tpufw.models.resnet import ResNetConfig

    if preset == BENCH_CONFIG_NAME:
        return bench_model_config()
    if preset in LLAMA_CONFIGS:
        return LLAMA_CONFIGS[preset]
    if preset in MIXTRAL_CONFIGS:
        return MIXTRAL_CONFIGS[preset]
    if preset in GEMMA_CONFIGS:
        return GEMMA_CONFIGS[preset]
    if preset in DEEPSEEK_CONFIGS:
        return DEEPSEEK_CONFIGS[preset]
    if preset == "resnet50":
        return ResNetConfig()
    raise ValueError(
        f"unknown model preset {preset!r}; choose from "
        f"[{BENCH_CONFIG_NAME!r}, 'resnet50', "
        f"*{list(LLAMA_CONFIGS)}, *{list(MIXTRAL_CONFIGS)}, "
        f"*{list(GEMMA_CONFIGS)}, *{list(DEEPSEEK_CONFIGS)}]"
    )


def _apply_model_overrides(cfg, overrides: dict):
    import jax.numpy as jnp

    fields = {f.name for f in dataclasses.fields(cfg)}
    _reject_unknown(f"model.overrides ({type(cfg).__name__})",
                    overrides, fields)
    coerced = dict(overrides)
    for k in _DTYPE_FIELDS:
        if isinstance(coerced.get(k), str):
            coerced[k] = jnp.dtype(coerced[k]).type
    if isinstance(coerced.get("rope_scaling"), dict):
        # YAML spells the Llama-3.1 rope transform as a mapping; the
        # config stores the frozen dataclass (unknown keys are hard
        # errors like everywhere else in this loader).
        from tpufw.models.llama import RopeScaling

        _reject_unknown(
            "model.overrides.rope_scaling",
            coerced["rope_scaling"],
            {f.name for f in dataclasses.fields(RopeScaling)},
        )
        coerced["rope_scaling"] = RopeScaling(**coerced["rope_scaling"])
    return dataclasses.replace(cfg, **coerced)


def load_run_config(path: str | os.PathLike) -> RunConfig:
    """Parse one YAML of record into the framework's own dataclasses."""
    raw = yaml.safe_load(pathlib.Path(path).read_text())
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: top level must be a mapping")
    _reject_unknown(
        str(path),
        raw,
        {"name", "hardware", "model", "trainer", "mesh", "pipeline"},
    )
    model_sec = raw.get("model")
    if not isinstance(model_sec, dict) or "preset" not in model_sec:
        raise ValueError(f"{path}: required section model.preset missing")
    _reject_unknown("model", model_sec, {"preset", "overrides"})

    model_cfg = _apply_model_overrides(
        _resolve_preset(model_sec["preset"]),
        model_sec.get("overrides") or {},
    )
    hardware = _build_dataclass(
        HardwareConfig, "hardware", raw.get("hardware") or {}
    )
    trainer_cls = (
        VisionTrainerConfig if model_sec["preset"] == "resnet50"
        else TrainerConfig
    )
    trainer = _build_dataclass(
        trainer_cls, "trainer", raw.get("trainer") or {}
    )
    mesh = _build_dataclass(MeshConfig, "mesh", raw.get("mesh") or {})
    pipeline = None
    if raw.get("pipeline"):
        from tpufw.parallel.pipeline import PipelineConfig

        pipeline = _build_dataclass(
            PipelineConfig, "pipeline", raw["pipeline"]
        )
        if mesh.pipe == 1:
            mesh = dataclasses.replace(mesh, pipe=pipeline.n_stages)
        elif mesh.pipe != pipeline.n_stages:
            raise ValueError(
                f"{path}: mesh.pipe={mesh.pipe} != "
                f"pipeline.n_stages={pipeline.n_stages}"
            )
        pipeline.validate(model_cfg, trainer.batch_size)

    # Cross-checks that catch the silent-gang-split class of drift early.
    per_slice = dict(
        mesh.sizes(max(1, hardware.n_chips // max(1, mesh.dcn_data)))
    )
    mesh_chips = mesh.dcn_data
    for v in per_slice.values():
        mesh_chips *= v
    if hardware.n_chips != mesh_chips:
        raise ValueError(
            f"{path}: mesh covers {mesh_chips} chips but hardware "
            f"declares {hardware.n_chips} ({hardware.slice})"
        )
    return RunConfig(
        name=raw.get("name") or pathlib.Path(path).stem,
        hardware=hardware,
        model_preset=model_sec["preset"],
        model_cfg=model_cfg,
        trainer=trainer,
        mesh=mesh,
        pipeline=pipeline,
    )


#: TrainerConfig/MeshConfig fields -> the TPUFW_* env names the deploy
#: manifests use (tpufw/workloads/env.py strips the prefix + lowercases).
_TRAINER_ENV = {
    "batch_size": "BATCH_SIZE",
    "seq_len": "SEQ_LEN",
    "total_steps": "TOTAL_STEPS",
    "lr": "LR",
    "warmup_steps": "WARMUP_STEPS",
    "log_every": "LOG_EVERY",
    "checkpoint_dir": "CHECKPOINT_DIR",
    "checkpoint_every": "CHECKPOINT_EVERY",
    "loss_chunk_size": "LOSS_CHUNK_SIZE",
    "loss_chunk_dtype": "LOSS_CHUNK_DTYPE",
    "eval_every": "EVAL_EVERY",
    "eval_batches": "EVAL_BATCHES",
    "grad_accum": "GRAD_ACCUM",
    "adam_mu_dtype": "ADAM_MU_DTYPE",
    "handle_preemption": "HANDLE_PREEMPTION",
    "preemption_sync_every": "PREEMPTION_SYNC_EVERY",
}
_VISION_ENV = {
    "batch_size": "BATCH_SIZE",
    "image_size": "IMAGE_SIZE",
    "num_classes": "NUM_CLASSES",
    "total_steps": "TOTAL_STEPS",
    "checkpoint_dir": "CHECKPOINT_DIR",
    "checkpoint_every": "CHECKPOINT_EVERY",
    "handle_preemption": "HANDLE_PREEMPTION",
    "preemption_sync_every": "PREEMPTION_SYNC_EVERY",
}
_MESH_ENV = {
    "data": "MESH_DATA",
    "pipe": "MESH_PIPE",
    "fsdp": "MESH_FSDP",
    "expert": "MESH_EXPERT",
    "sequence": "MESH_SEQUENCE",
    "tensor": "MESH_TENSOR",
    "dcn_data": "MESH_DCN_DATA",
}


def to_env(run: RunConfig, *, defaults_too: bool = False) -> dict[str, str]:
    """Render a RunConfig as the TPUFW_* env dict a manifest would set.

    With ``defaults_too=False`` only non-default values are emitted —
    exactly the keys a minimal manifest must carry to reproduce the YAML
    of record (the drift test's contract).
    """
    env = {} if run.family == "resnet" else {"TPUFW_MODEL": run.model_preset}
    trainer_map = (
        (run.trainer, _VISION_ENV, VisionTrainerConfig())
        if run.family == "resnet"
        else (run.trainer, _TRAINER_ENV, TrainerConfig())
    )
    for cfg, mapping, defaults in (
        trainer_map,
        (run.mesh, _MESH_ENV, MeshConfig()),
    ):
        for field, suffix in mapping.items():
            if field == "pipe" and run.pipeline is not None:
                # Pipeline manifests size the pipe axis via
                # TPUFW_PIPE_STAGES (one source of truth).
                continue
            val = getattr(cfg, field)
            if not defaults_too and val == getattr(defaults, field):
                continue
            if val is None:
                continue
            env[f"TPUFW_{suffix}"] = str(val)
    if run.pipeline is not None:
        env["TPUFW_PIPE_STAGES"] = str(run.pipeline.n_stages)
        env["TPUFW_PIPE_MICROBATCHES"] = str(run.pipeline.n_microbatches)
        if run.pipeline.schedule != "gpipe":
            env["TPUFW_PIPE_SCHEDULE"] = run.pipeline.schedule
    return env
