"""Benchmark/workload presets — the YAML-of-record side lives in deploy/.

The single-chip bench model is the Llama-3 architecture sized for one v5e
chip (16 GiB HBM, ``tpufw.utils.hardware``): fp32 params + Adam moments for
~600M params is ~7 GiB, leaving headroom for remat'd activations at
batch 8 x 2048. Scaling the *architecture* down (not the math) keeps the MFU
measurement representative of the 8B target.
"""

from __future__ import annotations

import jax.numpy as jnp

from tpufw.models.llama import LlamaConfig

BENCH_CONFIG_NAME = "llama3_600m_bench"


def bench_model_config() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=32_768,
        d_model=1536,
        n_layers=14,
        n_heads=12,
        n_kv_heads=6,
        head_dim=128,
        d_ff=6144,
        max_seq_len=2048,
        dtype=jnp.bfloat16,
        param_dtype=jnp.float32,
        attention_backend="flash",
        remat=True,
        scan_layers=True,
    )
