"""Llama-3 model family, TPU-first (Flax linen + logical partitioning).

BASELINE configs 3-4 name Llama-3-8B as the flagship training workload; the
reference itself ships no models (its workload is ``nvidia-smi``, reference
``README.md:314``), so this implementation is additive per SURVEY.md §0.

TPU-first choices:
- bfloat16 activations, fp32 RMSNorm/softmax accumulation — keeps the MXU on
  its fast path without fp16-style loss-scale machinery.
- ``nn.scan`` over the layer stack — one compiled block body instead of
  L inlined copies; XLA compile time stays flat as L grows.
- every parameter carries *logical* axis names (``embed``, ``mlp``,
  ``q_heads``...); the (logical -> mesh) mapping lives in
  ``tpufw.mesh.logical_axis_rules`` so tp/fsdp/sp/ep layout changes never
  touch this file.
- attention is dispatched through ``tpufw.ops.multi_head_attention`` so the
  Pallas flash kernel and ring (sequence-parallel) backends drop in by
  config string.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import ad_checkpoint
from flax import linen as nn

from tpufw.ops import multi_head_attention, rms_norm
from tpufw.ops.quant import dequantize_kv, quantize_kv

Dtype = Any

# Remat (rematerialization) policies: what survives the forward pass for
# backward, vs recomputed. jax names the "no batch dims" policy after
# dot_general batch dims, which plain x@W projections don't have — so
# "dots" saves EVERY projection output, not "almost nothing".
_REMAT_POLICIES = {
    "dots": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing": jax.checkpoint_policies.nothing_saveable,
    "everything": jax.checkpoint_policies.everything_saveable,
    # Save ONLY each block's attention output ([B, T, D] per layer — the
    # small tensor), recomputing everything else like "nothing" does.
    # Backward then skips re-running the flash kernel (the one fwd op
    # XLA can't fuse into its neighbours) at a memory cost of
    # n_layers * B*T*D*2 bytes, while the [B, T, d_ff] MLP
    # intermediates that make "dots" OOM still rematerialize.
    "attn_out": jax.checkpoint_policies.save_only_these_names("attn_out"),
}


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Rotary frequency transform, by ``rope_type``:

    - ``"llama3"`` (HF ``_compute_llama3_parameters``, Llama-3.1/3.3):
      low-frequency components are slowed by ``factor`` (extending the
      usable context), high-frequency components are kept, and a smooth
      ramp interpolates between the two wavelength bands.
    - ``"linear"`` (HF ``_compute_linear_scaling_parameters``, common
      on long-context Llama-2 fine-tunes): every frequency divided by
      ``factor`` — position interpolation; only ``factor`` is read.

    yarn lives on the DeepSeek family (tpufw.models.deepseek
    YarnScaling); dynamic/longrope are rejected at import
    (tools/import_hf.py) rather than silently approximated.
    """

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192
    rope_type: str = "llama3"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    d_ff: int = 14_336
    rope_theta: float = 500_000.0
    # Llama-3.1+ long-context rope transform (None = plain RoPE).
    rope_scaling: Optional[RopeScaling] = None
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    attention_backend: str = "xla"
    remat: bool = True
    # What the block remat saves for backward (tpufw.models.llama
    # _REMAT_POLICIES): "dots" saves every projection-matmul output
    # (fast bwd, memory-heavy: the [B,T,d_ff] MLP intermediates dominate
    # HBM); "nothing" recomputes the whole block from its input (full
    # remat: smallest footprint, ~1 extra fwd of FLOPs) — the standard
    # memory/compute trade, selectable per run.
    remat_policy: str = "dots"
    # False = BIDIRECTIONAL attention (LLM2Vec-style embedding
    # fine-tuning, tpufw.train.contrastive); incompatible with decode
    # (a KV cache is a causal construct).
    causal: bool = True
    scan_layers: bool = True
    # Autoregressive KV-cache mode (tpufw.infer): attention reads/writes a
    # [B, max_seq_len] cache ("cache" flax collection) instead of attending
    # within the call's own tokens. Build with cfg.decode_config().
    decode: bool = False
    # LoRA (parameter-efficient fine-tuning): rank > 0 adds frozen-base
    # low-rank adapters to every attention/MLP projection (B zero-init,
    # so step 0 equals the base model); the Trainer then updates ONLY
    # adapter params (tpufw.train.trainer lora masking), and
    # tpufw.models.lora.merge_lora folds trained adapters back into the
    # base kernels for serving/export.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Mistral-style local attention: ONE window on EVERY layer (unlike
    # Gemma-2's alternation). None = global attention.
    sliding_window: Optional[int] = None
    # Qwen-2 style attention: biases on the q/k/v projections only
    # (o and the MLP stay bias-free). The one architectural delta
    # between Llama and the Qwen-2/2.5 family.
    attention_qkv_bias: bool = False
    # Weight-only int8 serving (tpufw.ops.quant): projection kernels are
    # stored int8 + per-output-channel scales, halving decode's HBM
    # weight traffic. Params come from quantize_params on a trained
    # tree; this flag makes the modules DECLARE the quantized form.
    # Serving-only — there is no gradient through the rounded weights.
    quantized_weights: bool = False
    # Paged KV cache (tpufw.infer.pages): kv_page > 0 replaces the
    # contiguous per-row [B, max_seq_len] KV cache with a global page
    # arena of ``kv_pages`` fixed-size pages (``kv_page`` slots each)
    # plus a per-row page table, so HBM holds pages proportional to
    # TOKENS IN FLIGHT rather than rows x max_seq_len, and matching
    # prompt prefixes share pages across rows. Decode-only (t == 1);
    # page 0 is reserved as a causally-masked junk sink. kv_quant
    # "int8" stores the paged K/V as int8 + per-token fp32 scales
    # (quantized at append, dequantized on read), halving KV bytes.
    kv_page: int = 0
    kv_pages: int = 0
    kv_quant: str = ""

    def decode_config(self) -> "LlamaConfig":
        """This architecture re-dressed for inference: KV-cache on, remat
        off (no backward pass), xla attention (flash/ring are trainers')."""
        return dataclasses.replace(
            self, decode=True, remat=False, attention_backend="xla"
        )

    def n_params(self, include_embed: bool = True) -> int:
        """Analytic parameter count (exact for this architecture)."""
        d, l = self.d_model, self.n_layers
        attn = l * (
            d * self.n_heads * self.head_dim
            + 2 * d * self.n_kv_heads * self.head_dim
            + self.n_heads * self.head_dim * d
        )
        if self.attention_qkv_bias:
            attn += l * (
                self.n_heads * self.head_dim
                + 2 * self.n_kv_heads * self.head_dim
            )
        mlp = l * 3 * d * self.d_ff
        norms = (2 * l + 1) * d
        embed = self.vocab_size * d
        head = 0 if self.tie_embeddings else d * self.vocab_size
        total = attn + mlp + norms
        if include_embed:
            total += embed + head
        return total

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs per token: 6*N_matmul + 6*L*d_model*T (causal).

        6*N covers fwd (2N) + bwd (4N) for all matmul params incl. the LM
        head but not the embedding gather; the attention term is the
        QK^T/AV score FLOPs, causal-halved, x3 for fwd+bwd.
        """
        d, l = self.d_model, self.n_layers
        n_matmul = (
            l
            * (
                d * self.n_heads * self.head_dim
                + 2 * d * self.n_kv_heads * self.head_dim
                + self.n_heads * self.head_dim * d
                + 3 * d * self.d_ff
            )
            + d * self.vocab_size
        )
        return 6.0 * n_matmul + self._attn_score_flops(seq_len)

    def _attn_score_flops(self, seq_len: int) -> float:
        """QK^T/AV score FLOPs per token, fwd+bwd (x3), both matmuls
        (x2). Per-query key count: seq/2 for the causal triangle, capped
        at the sliding window (Mistral/Mixtral) — mirrors GemmaConfig's
        local layers; without the cap, windowed runs at long seq_len
        report inflated model FLOPs and overstate MFU. Shared by the
        Llama and Mixtral flops_per_token (only their matmul term
        differs)."""
        keys = seq_len / 2
        if self.sliding_window is not None:
            keys = min(float(self.sliding_window), keys)
        return (
            6.0 * self.n_layers * self.n_heads * self.head_dim
            * 2.0 * keys
        )


# Presets. 8B matches Meta's Llama-3-8B shape; the proxies are the same
# architecture scaled to fit one v5e chip (16 GiB HBM) for bench/smoke runs.
#
# Backend policy: production-size presets (here and in the mixtral/
# gemma/deepseek families) train through attention_backend="flash" —
# the naive xla path materializes f32 [H, T, T] scores, which at
# seq 8192 / 32 heads is 8 GB PER TENSOR (measured compile-OOM, r5;
# docs/PERF.md block8b section) and cost 11 MFU points even where it
# fit. Tiny test presets stay on "xla": the suite runs them on CPU,
# where flash means the Pallas interpreter (slow), and the xla path is
# the reference the flash kernel is parity-tested against.
# decode_config() resets the backend for the KV-cache path.
LLAMA_CONFIGS: dict[str, LlamaConfig] = {
    "llama3_8b": LlamaConfig(attention_backend="flash"),
    # Llama-3.1-8B: same shape as 3.0, llama3 rope transform (Meta's
    # published scaling params are RopeScaling's defaults), 128k
    # context window.
    "llama31_8b": LlamaConfig(
        max_seq_len=131_072,
        rope_scaling=RopeScaling(),
        attention_backend="flash",
    ),
    "llama3_1b_proxy": LlamaConfig(
        vocab_size=32_768,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        max_seq_len=4096,
        attention_backend="flash",
    ),
    "llama3_tiny": LlamaConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        max_seq_len=128,
        remat=False,
    ),
    # Mistral-7B (v0.1): Llama architecture + a 4096-token sliding
    # window on every layer.
    "mistral_7b": LlamaConfig(
        vocab_size=32_000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=14_336,
        rope_theta=10_000.0,
        max_seq_len=32_768,
        sliding_window=4096,
        attention_backend="flash",
    ),
    "mistral_tiny": LlamaConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        max_seq_len=128,
        sliding_window=32,
        remat=False,
    ),
    # Qwen-2.5: the Llama architecture + qkv biases. 7B matches the HF
    # Qwen/Qwen2.5-7B shape (untied); the tiny is the test proxy.
    "qwen25_7b": LlamaConfig(
        vocab_size=152_064,
        d_model=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        head_dim=128,
        d_ff=18_944,
        rope_theta=1_000_000.0,
        rms_eps=1e-6,
        max_seq_len=32_768,
        attention_qkv_bias=True,
        attention_backend="flash",
    ),
    "qwen25_tiny": LlamaConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        max_seq_len=128,
        remat=False,
        attention_qkv_bias=True,
    ),
}


def _scale_rope_freqs(
    freqs: jax.Array, s: RopeScaling
) -> jax.Array:
    """Frequency transforms matching HF's executed math so imported
    checkpoints are bit-comparable. "linear": every frequency divided
    by ``factor`` (position interpolation). "llama3"
    (``_compute_llama3_parameters``): components with wavelength beyond
    ``original_max/low_freq_factor`` are slowed by ``factor``, those
    below ``original_max/high_freq_factor`` are kept, and the band
    between is linearly interpolated in smooth-factor space."""
    if s.rope_type == "linear":
        return freqs / s.factor
    if s.rope_type != "llama3":
        raise NotImplementedError(
            f"rope_type={s.rope_type!r}: RopeScaling implements "
            "'llama3' and 'linear'"
        )
    old_len = float(s.original_max_position_embeddings)
    wavelen = 2.0 * math.pi / freqs
    scaled = jnp.where(
        wavelen > old_len / s.low_freq_factor, freqs / s.factor, freqs
    )
    smooth = (old_len / wavelen - s.low_freq_factor) / (
        s.high_freq_factor - s.low_freq_factor
    )
    smoothed = (1.0 - smooth) * freqs / s.factor + smooth * freqs
    is_medium = (wavelen <= old_len / s.low_freq_factor) & (
        wavelen >= old_len / s.high_freq_factor
    )
    return jnp.where(is_medium, smoothed, scaled)


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float,
    scaling: Optional[RopeScaling] = None,
) -> jax.Array:
    """Rotary embeddings. x: [B, T, H, D], positions: [B, T] -> same shape."""
    d = x.shape[-1]
    freqs = 1.0 / (
        theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )  # [D/2]
    if scaling is not None:
        freqs = _scale_rope_freqs(freqs, scaling)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    # Gemma parameterization: weight stored as an offset from 1 (zeros
    # init, applied as 1 + w) — matches HF so checkpoints interchange.
    offset: bool = False

    @nn.compact
    def __call__(self, x):
        init = (
            nn.initializers.zeros_init()
            if self.offset
            else nn.initializers.ones_init()
        )
        w = self.param(
            "scale",
            nn.with_logical_partitioning(init, ("norm",)),
            (x.shape[-1],),
            jnp.float32,
        )
        return rms_norm(x, w + 1.0 if self.offset else w, self.eps)


def lora_delta(cfg, x, features, axis, in_names, out_names, name):
    """Low-rank adapter delta for the projection ``name``: x @ A @ B
    scaled by alpha/rank; 0.0 when LoRA is off. A uses the projection's
    fan-in init, B starts at ZERO — step 0 output equals the base model,
    the standard LoRA init. Params land as ``{name}_lora_a/b`` siblings
    of the base module, so a base-only checkpoint stays a strict subtree
    (import/export and bare-params restore are unaffected)."""
    r = getattr(cfg, "lora_rank", 0)
    if not r:
        return 0.0
    a = nn.DenseGeneral(
        features=r,
        axis=axis,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), (*in_names, "lora")
        ),
        name=f"{name}_lora_a",
    )(x)
    b = nn.DenseGeneral(
        features=features,
        axis=-1,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), ("lora", *out_names)
        ),
        name=f"{name}_lora_b",
    )(a)
    return b * (getattr(cfg, "lora_alpha", 16.0) / r)


def reject_quant_lora(cfg) -> None:
    """The one statement of the serving invariant: int8 weights carry no
    gradient path, so adapters must be merged (tools/merge_lora) before
    quantizing. Shared by every quantized module (llama.projection,
    mixtral MoEMLP)."""
    if getattr(cfg, "lora_rank", 0):
        raise ValueError(
            "quantized_weights with lora_rank > 0: merge the "
            "adapters (tools/merge_lora) before quantizing"
        )


class QuantDenseGeneral(nn.Module):
    """DenseGeneral over int8 weights + per-output-channel scales —
    the serving twin of the fp projection (tpufw.ops.quant). Param
    shapes match ``quantize_params`` output; logical axes mirror the fp
    kernel's so sharded serving lays out identically."""

    features: Any
    axis: Any
    dtype: Any
    in_names: tuple
    out_names: tuple
    use_bias: bool = False

    @nn.compact
    def __call__(self, x):
        from tpufw.ops.quant import quant_contract

        axes = (
            (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        )
        n_in = len(axes)
        in_dims = tuple(x.shape[a] for a in axes)
        out_dims = (
            (self.features,)
            if isinstance(self.features, int)
            else tuple(self.features)
        )
        q = self.param(
            "q_kernel",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(),
                (*self.in_names, *self.out_names),
            ),
            (*in_dims, *out_dims),
            jnp.int8,
        )
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(
                nn.initializers.ones_init(), self.out_names
            ),
            out_dims,
            jnp.float32,
        )
        y = quant_contract(x.astype(self.dtype), q, scale, n_in)
        if self.use_bias:
            b = self.param(
                "bias",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), self.out_names
                ),
                out_dims,
                jnp.float32,
            )
            y = y + b.astype(y.dtype)
        return y


def projection(
    cfg, x, features, axis, in_names, out_names, name, use_bias=False
):
    """Dense projection + optional LoRA delta — the ONE composition every
    adapted matmul (attention q/k/v/o, MLP gate/up/down) goes through.
    Must be called from inside a compact ``__call__``. With
    ``cfg.quantized_weights`` the int8 serving twin is declared instead
    (mutually exclusive with LoRA — merge adapters first); biased
    projections (Qwen qkv) keep a full-precision bias vector either way
    (it is tiny — the kernel carries the bandwidth)."""
    if getattr(cfg, "quantized_weights", False):
        reject_quant_lora(cfg)
        return QuantDenseGeneral(
            features=features,
            axis=axis,
            dtype=cfg.dtype,
            in_names=tuple(in_names),
            out_names=tuple(out_names),
            use_bias=use_bias,
            name=name,
        )(x)
    base = nn.DenseGeneral(
        features=features,
        axis=axis,
        use_bias=use_bias,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), (*in_names, *out_names)
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), tuple(out_names)
        ),
        name=name,
    )(x)
    return base + lora_delta(
        cfg, x, features, axis, in_names, out_names, name
    )


class Attention(nn.Module):
    cfg: LlamaConfig
    # Sliding-window size for this layer (None = global attention).
    # Gemma-2 alternates local/global layers, so this is per-block.
    window: Optional[int] = None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        qkv_bias = getattr(cfg, "attention_qkv_bias", False)
        q = projection(
            cfg, x, (cfg.n_heads, cfg.head_dim), -1,
            ("embed",), ("q_heads", "head_dim"), "q", use_bias=qkv_bias,
        )
        k = projection(
            cfg, x, (cfg.n_kv_heads, cfg.head_dim), -1,
            ("embed",), ("kv_heads", "head_dim"), "k", use_bias=qkv_bias,
        )
        v = projection(
            cfg, x, (cfg.n_kv_heads, cfg.head_dim), -1,
            ("embed",), ("kv_heads", "head_dim"), "v", use_bias=qkv_bias,
        )
        rope_scaling = getattr(cfg, "rope_scaling", None)
        q = apply_rope(q, positions, cfg.rope_theta, rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, rope_scaling)
        # Non-default query scaling (Gemma's query_pre_attn_scalar):
        # backends scale by head_dim**-0.5 internally, so pre-multiply q
        # by the ratio to the desired qpas**-0.5.
        qpas = getattr(cfg, "query_pre_attn_scalar", None)
        if qpas is not None and float(qpas) != float(cfg.head_dim):
            q = q * (math.sqrt(cfg.head_dim) / math.sqrt(float(qpas)))
        q = nn.with_logical_constraint(
            q, ("batch", "act_seq", "act_heads", "head_dim")
        )
        k = nn.with_logical_constraint(
            k, ("batch", "act_seq", "act_heads", "head_dim")
        )
        v = nn.with_logical_constraint(
            v, ("batch", "act_seq", "act_heads", "head_dim")
        )
        causal = getattr(cfg, "causal", True)
        if not causal and self.window is not None:
            # The window mask is causal-relative (last-N PAST keys);
            # under causal=False it would pass every FUTURE key while
            # capping the past — an incoherent asymmetric mask, not
            # bidirectional attention. LLM2Vec-on-Mistral must disable
            # the window (sliding_window=None) explicitly.
            raise ValueError(
                "causal=False with sliding_window set: the window mask "
                "is causal-relative; set sliding_window=None for "
                "bidirectional embedding fine-tuning"
            )
        if cfg.decode:
            if not causal:
                raise ValueError(
                    "causal=False with decode=True: a KV cache is a "
                    "causal construct — bidirectional models embed, "
                    "they don't autoregress"
                )
            out = self._cached_attention(q, k, v, segment_ids, positions)
        else:
            out = multi_head_attention(
                q,
                k,
                v,
                causal=causal,
                segment_ids=segment_ids,
                logits_soft_cap=getattr(cfg, "attn_logit_soft_cap", None),
                sliding_window=self.window,
                backend=cfg.attention_backend,
            )
        return projection(
            cfg, out, cfg.d_model, (-2, -1),
            ("heads", "head_dim"), ("embed",), "o",
        )

    def _cached_attention(self, q, k, v, segment_ids, positions):
        """KV-cache step: append this call's k/v at the cache cursor, then
        attend q (at ``positions``) over the whole cache. Static shapes —
        the cache is always [B, max_seq_len] and masking does the rest:
        never-written slots keep segment 0, so the segment mask hides them
        (prompt pad slots stay 0 too, handled by the same mechanism).
        """
        cfg = self.cfg
        if getattr(cfg, "kv_page", 0):
            return self._paged_cached_attention(q, k, v, segment_ids)
        b, t = q.shape[:2]
        shape = (b, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)
        ck = self.variable("cache", "cached_key", jnp.zeros, shape, cfg.dtype)
        cv = self.variable(
            "cache", "cached_value", jnp.zeros, shape, cfg.dtype
        )
        cseg = self.variable(
            "cache", "cached_segment_ids",
            jnp.zeros, (b, cfg.max_seq_len), jnp.int32,
        )
        cursor = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        cur = cursor.value
        seg = (
            jnp.ones((b, t), jnp.int32) if segment_ids is None
            else segment_ids.astype(jnp.int32)
        )
        if cur.ndim == 0:
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(cfg.dtype), (0, cur, 0, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(cfg.dtype), (0, cur, 0, 0)
            )
            cseg.value = jax.lax.dynamic_update_slice(
                cseg.value, seg, (0, cur)
            )
            # Causality is over cache SLOTS, not RoPE positions — under
            # left-padding a token's RoPE position lags its slot by pad_len
            # and would wrongly mask valid recent slots.
            slot_positions = jnp.broadcast_to(cur + jnp.arange(t), (b, t))
        else:
            # Per-row cursors [B] (tpufw.infer.slots pool decode): each
            # slot writes at its own offset. Clamp the write window so a
            # retired-but-still-stepped row scatters in bounds; its output
            # is masked host-side, and the clamped slot is overwritten by
            # the next insert's full-cache copy.
            cur_w = jnp.minimum(cur, cfg.max_seq_len - t)
            rows = jnp.arange(b)[:, None]
            cols = cur_w[:, None] + jnp.arange(t)[None, :]
            ck.value = ck.value.at[rows, cols].set(k.astype(cfg.dtype))
            cv.value = cv.value.at[rows, cols].set(v.astype(cfg.dtype))
            cseg.value = cseg.value.at[rows, cols].set(seg)
            slot_positions = cur_w[:, None] + jnp.arange(t)[None, :]
        cursor.value = cur + t
        return multi_head_attention(
            q,
            ck.value,
            cv.value,
            causal=True,
            segment_ids=seg,
            kv_segment_ids=cseg.value,
            q_positions=slot_positions,
            logits_soft_cap=getattr(cfg, "attn_logit_soft_cap", None),
            sliding_window=self.window,
            backend="xla",
        )

    def _paged_cached_attention(self, q, k, v, segment_ids):
        """Paged KV-cache decode step (cfg.kv_page > 0).

        Storage is a global arena of ``kv_pages`` pages x ``kv_page``
        slots shared by every row; ``page_table`` [B, S/page] maps each
        row's logical slot j to physical page table[j // page], offset
        j % page. The gather read reconstructs the logical [B, S] row
        IN LOGICAL SLOT ORDER, so attention sees exactly what the
        contiguous branch sees at every written slot and the output is
        bit-equal at matching precision: unmapped table entries point at
        reserved page 0, whose junk only ever surfaces at logical slots
        strictly beyond the row's cursor, where the causal mask fills
        the logit before softmax (exp underflows to exact 0.0, and
        0.0 * finite-junk-V == 0.0). Occupancy, table churn, and cursor
        motion are all DATA — one jitted program forever.

        t == 1 is the plain decode step; t > 1 is the speculative
        verify block (tpufw.infer.speculative chunked path): all t
        tokens scatter into consecutive logical slots first, then the
        gather reconstructs the row INCLUDING the block, so intra-block
        causality falls out of the same slot-ordered mask. Prefill
        still runs through a contiguous row cache and is scattered into
        pages at insert (tpufw.infer.pages).
        """
        cfg = self.cfg
        b, t = q.shape[:2]
        page, n_pages = cfg.kv_page, cfg.kv_pages
        if cfg.max_seq_len % page:
            raise ValueError(
                f"kv_page={page} must divide max_seq_len={cfg.max_seq_len}"
            )
        per_row = cfg.max_seq_len // page
        quant = cfg.kv_quant == "int8"
        kv_dtype = jnp.int8 if quant else cfg.dtype
        shape = (n_pages, page, cfg.n_kv_heads, cfg.head_dim)
        ck = self.variable("cache", "cached_key", jnp.zeros, shape, kv_dtype)
        cv = self.variable(
            "cache", "cached_value", jnp.zeros, shape, kv_dtype
        )
        cseg = self.variable(
            "cache", "cached_segment_ids",
            jnp.zeros, (n_pages, page), jnp.int32,
        )
        table = self.variable(
            "cache", "page_table", jnp.zeros, (b, per_row), jnp.int32
        )
        # Per-row cursor from birth (the paged pool always decodes with
        # one token per row) — no scalar branch to diverge on.
        cursor = self.variable(
            "cache", "cache_index", jnp.zeros, (b,), jnp.int32
        )
        if quant:
            cks = self.variable(
                "cache", "cached_key_scale",
                jnp.zeros, (n_pages, page), jnp.float32,
            )
            cvs = self.variable(
                "cache", "cached_value_scale",
                jnp.zeros, (n_pages, page), jnp.float32,
            )
        cur = cursor.value
        seg = (
            jnp.ones((b, t), jnp.int32) if segment_ids is None
            else segment_ids.astype(jnp.int32)
        )
        # Same write-window clamp as the contiguous per-row branch: a
        # done-but-still-stepped row keeps scattering in bounds. Its
        # writes land either in its own private last page (the
        # allocator never shares a row's final page; speculative
        # callers keep t <= page so the clamped window never leaves
        # it) or, once retired (table zeroed), in reserved page 0.
        wslot = (
            jnp.minimum(cur, cfg.max_seq_len - t)[:, None]
            + jnp.arange(t)[None, :]
        )  # [B, t] logical write slots
        phys = table.value[jnp.arange(b)[:, None], wslot // page]
        off = wslot % page
        if quant:
            qk, sk = quantize_kv(k, n_feat=2)
            qv, sv = quantize_kv(v, n_feat=2)
            ck.value = ck.value.at[phys, off].set(qk)
            cv.value = cv.value.at[phys, off].set(qv)
            cks.value = cks.value.at[phys, off].set(sk)
            cvs.value = cvs.value.at[phys, off].set(sv)
        else:
            ck.value = ck.value.at[phys, off].set(k.astype(cfg.dtype))
            cv.value = cv.value.at[phys, off].set(v.astype(cfg.dtype))
        cseg.value = cseg.value.at[phys, off].set(seg)
        cursor.value = cur + t
        # Gather the logical view: [B, per_row] table -> [B, S, ...].
        idx = table.value
        s = cfg.max_seq_len
        feat = (cfg.n_kv_heads, cfg.head_dim)
        if quant:
            k_all = dequantize_kv(
                ck.value[idx], cks.value[idx], cfg.dtype
            ).reshape(b, s, *feat)
            v_all = dequantize_kv(
                cv.value[idx], cvs.value[idx], cfg.dtype
            ).reshape(b, s, *feat)
        else:
            k_all = ck.value[idx].reshape(b, s, *feat)
            v_all = cv.value[idx].reshape(b, s, *feat)
        return multi_head_attention(
            q,
            k_all,
            v_all,
            causal=True,
            segment_ids=seg,
            kv_segment_ids=cseg.value[idx].reshape(b, s),
            q_positions=wslot,
            logits_soft_cap=getattr(cfg, "attn_logit_soft_cap", None),
            sliding_window=self.window,
            backend="xla",
        )


class MLP(nn.Module):
    """SwiGLU feed-forward. ``d_ff`` overrides the config width
    (DeepSeek shared experts size theirs as a multiple of the expert
    width, not cfg.d_ff)."""

    cfg: LlamaConfig
    d_ff: Optional[int] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        d_ff = self.d_ff if self.d_ff is not None else cfg.d_ff
        gate = projection(
            cfg, x, d_ff, -1, ("embed",), ("mlp",), "gate"
        )
        up = projection(cfg, x, d_ff, -1, ("embed",), ("mlp",), "up")
        act_name = getattr(cfg, "mlp_activation", "silu")
        if act_name == "silu":
            act = nn.silu(gate)
        elif act_name == "gelu_tanh":  # Gemma GeGLU
            act = nn.gelu(gate, approximate=True)
        else:
            raise ValueError(f"unknown mlp_activation {act_name!r}")
        h = act * up
        h = nn.with_logical_constraint(h, ("batch", "act_seq", "act_mlp"))
        return projection(
            cfg, h, cfg.d_model, -1, ("mlp",), ("embed",), "down"
        )


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        attn_out = Attention(
            cfg, window=getattr(cfg, "sliding_window", None), name="attn"
        )(
            RMSNorm(cfg.rms_eps, name="attn_norm")(x), positions, segment_ids
        )
        # Tag for remat_policy="attn_out" (no-op under other policies).
        x = x + ad_checkpoint.checkpoint_name(attn_out, "attn_out")
        x = x + MLP(cfg, name="mlp")(RMSNorm(cfg.rms_eps, name="mlp_norm")(x))
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))


def unstack_layer_params(params: dict, donate: bool = False) -> dict:
    """Scanned-trunk param tree -> the unscanned twin's tree.

    ``decoder_lm`` with ``scan_layers=True`` stores the block stack as
    ONE submodule named "layers" whose leaves carry a leading [L] axis
    (nn.scan variable_axes); with ``scan_layers=False`` the same
    weights live under ``layer_0 .. layer_{L-1}``. This converts the
    former to the latter — the serving "unroll" lever: a checkpoint
    trained scanned can be decoded by the unscanned twin
    (``dataclasses.replace(cfg, scan_layers=False)``), which skips the
    per-step per-layer weight slicing of the decode scan. Works for
    every decoder_lm family (Llama/Qwen/Mistral/Mixtral/Deepseek and
    Gemma, whose scanned unit is a PAIR). A tree with no "layers" key
    (already unscanned) is returned unchanged.

    With ``donate=True`` each stacked leaf is explicitly DELETED once
    its per-layer slices exist, so peak device memory is the weights
    plus one stacked leaf — not 2x the weights, which would OOM
    serving startup for any model past half of HBM. (Explicit delete,
    not jit donation: the stacked buffer can never alias the smaller
    tuple-of-slices outputs, so donation would just warn and free —
    this frees without the warning, on every backend.) Consequence:
    the input tree's "layers" leaves are INVALID afterwards — only
    enable when the caller drops the old tree immediately (the serve
    paths do); the default keeps the input usable."""
    if "layers" not in params:
        return params
    leaves, treedef = jax.tree_util.tree_flatten(params["layers"])
    n = leaves[0].shape[0]
    split = jax.jit(lambda a: tuple(a[i] for i in range(n)))
    per_leaf = []
    for leaf in leaves:
        out = split(leaf)
        if donate and isinstance(leaf, jax.Array):
            # The slices must exist on device before the source dies.
            jax.block_until_ready(out)
            leaf.delete()
        per_leaf.append(out)
    out = {k: v for k, v in params.items() if k != "layers"}
    for i in range(n):
        out[f"layer_{i}"] = jax.tree_util.tree_unflatten(
            treedef, [pl[i] for pl in per_leaf]
        )
    return out


def decoder_lm(
    cfg, block_base, tokens, positions, segment_ids, with_aux,
    return_hidden=False,
):
    """Shared decoder trunk: embed -> remat/scan block stack -> norm -> head.

    Used by both Llama and Mixtral (the only difference is the block class
    and whether blocks thread an aux-loss carry) so the two families can't
    drift. Must be called from inside a compact ``__call__``.

    Returns ``logits`` or ``(logits, aux)`` when ``with_aux``. With
    ``return_hidden`` the head matmul is skipped and the post-final-norm
    hidden states [B, T, D] take the place of logits — the chunked-vocab
    loss path (tpufw.ops.loss) computes CE straight from these plus the
    head kernel, never materializing [B, T, V].
    """
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    # Scaled-embedding models (Gemma) store embeddings ~1/sqrt(d) and
    # multiply by sqrt(d) at lookup, keeping the TIED head's logits O(1);
    # initializing at stddev 1.0 there would saturate the final soft-cap
    # from step 0 (observed: init loss 29 vs ln(V)~5.5).
    embed_std = (
        cfg.d_model ** -0.5 if getattr(cfg, "embed_scale", False) else 1.0
    )
    embed = nn.Embed(
        cfg.vocab_size,
        cfg.d_model,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        embedding_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=embed_std), ("vocab", "embed")
        ),
        name="embed",
    )
    x = embed(tokens)
    if getattr(cfg, "embed_scale", False):
        # Gemma scales embeddings by sqrt(d_model), cast through the
        # activation dtype exactly as HF does (bf16 rounding included).
        x = x * jnp.asarray(
            math.sqrt(cfg.d_model), cfg.dtype
        ).astype(x.dtype)
    x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))

    block_cls = block_base
    if cfg.remat:
        policy_name = getattr(cfg, "remat_policy", "dots")
        if policy_name not in _REMAT_POLICIES:
            raise ValueError(
                f"unknown remat_policy {policy_name!r}; choose from "
                f"{sorted(_REMAT_POLICIES)}"
            )
        block_cls = nn.remat(
            block_base,
            policy=_REMAT_POLICIES[policy_name],
            prevent_cse=not cfg.scan_layers,
        )
    aux = jnp.zeros((), jnp.float32)
    if cfg.scan_layers:

        def body(mdl, carry, _):
            h, aux_acc = carry
            out = mdl(h, positions, segment_ids)
            if with_aux:
                h, a = out
                return (h, aux_acc + a), None
            return (out, aux_acc), None

        (x, aux), _ = nn.scan(
            body,
            variable_axes={"params": 0, "cache": 0},
            split_rngs={"params": True},
            length=cfg.n_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(block_cls(cfg, name="layers"), (x, aux), None)
    else:
        for i in range(cfg.n_layers):
            out = block_cls(cfg, name=f"layer_{i}")(x, positions, segment_ids)
            if with_aux:
                x, a = out
                aux = aux + a
            else:
                x = out

    x = RMSNorm(
        cfg.rms_eps,
        offset=getattr(cfg, "rms_offset", False),
        name="final_norm",
    )(x)
    if return_hidden:
        return (x, aux) if with_aux else x
    if cfg.tie_embeddings:
        logits = embed.attend(x.astype(jnp.float32))
    elif getattr(cfg, "quantized_weights", False):
        logits = QuantDenseGeneral(
            features=cfg.vocab_size,
            axis=-1,
            dtype=jnp.float32,
            in_names=("embed",),
            out_names=("vocab",),
            name="lm_head",
        )(x)
    else:
        logits = nn.DenseGeneral(
            features=cfg.vocab_size,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )(x)
    logits = nn.with_logical_constraint(
        logits, ("batch", "act_seq", "act_vocab")
    )
    return (logits, aux) if with_aux else logits


class Llama(nn.Module):
    """Decoder-only Llama-3 LM. Returns logits [B, T, vocab]."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, tokens, positions=None, segment_ids=None, return_hidden=False
    ):
        return decoder_lm(
            self.cfg, LlamaBlock, tokens, positions, segment_ids, False,
            return_hidden=return_hidden,
        )
