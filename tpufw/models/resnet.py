"""ResNet-50 (Flax) — BASELINE config 2: single-TPU-pod image training.

The reference's only workload is ``nvidia-smi`` (reference ``README.md:314``);
ResNet-50 is the first *real* accelerator workload in the TPU build plan
(SURVEY.md §7.3 C5, the end of the minimum slice). TPU-first notes: NHWC
layout (XLA's native conv layout on TPU), bf16 activations with fp32
batch-norm statistics, and logical axes on conv kernels so fsdp sharding
works without model edits.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    # BatchNorm compute dtype. Statistics (mean/var) are always reduced
    # in float32 inside flax regardless of this, and running stats live
    # in param_dtype; this only sets the dtype of the normalize/scale
    # arithmetic applied to the activation tensor. float32 doubles the
    # HBM traffic of every BN in the bandwidth-bound early stages.
    norm_dtype: Dtype = jnp.float32

    def flops_per_image(self, image_size: int = 224) -> float:
        """~4.1 GFLOP forward for 224x224 ResNet-50; x3 for fwd+bwd."""
        # Scale quadratically with resolution from the canonical 224 number.
        fwd = 4.1e9 * (image_size / 224) ** 2
        return 3.0 * fwd


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool):
        cfg = self.cfg
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
                ("conv_h", "conv_w", "conv_in", "conv_out"),
            ),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=cfg.norm_dtype,
            param_dtype=cfg.param_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            name="conv2",
        )(y)
        y = norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        # Zero-init the last BN scale: residual branches start as identity,
        # the standard trick for stable large-batch training.
        y = norm(name="bn3", scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4,
                (1, 1),
                strides=(self.strides, self.strides),
                name="proj",
            )(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    """ResNet-v1.5 bottleneck network. Input NHWC, returns [B, num_classes]."""

    cfg: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        cfg = self.cfg
        x = images.astype(cfg.dtype)
        x = nn.Conv(
            cfg.width,
            (7, 7),
            strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
                ("conv_h", "conv_w", "conv_in", "conv_out"),
            ),
            name="conv_init",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=cfg.norm_dtype,
            param_dtype=cfg.param_dtype,
            name="bn_init",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for block in range(n_blocks):
                x = BottleneckBlock(
                    filters=cfg.width * 2**stage,
                    strides=2 if block == 0 and stage > 0 else 1,
                    cfg=cfg,
                    name=f"stage{stage}_block{block}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            cfg.num_classes,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="head",
        )(x)
        return x


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(ResNetConfig(num_classes=num_classes, **kw))
