"""LoRA utilities: adapter detection, optimizer masking, kernel merge.

The adapters themselves live where the projections live
(``tpufw.models.llama.lora_delta``, shared by Llama/Gemma blocks and
Mixtral's attention; ``tpufw.models.mixtral.MoEMLP._expert_matmul``
adapts the expert stacks as raw [E, in, r]/[E, r, out] arrays). This
module is the everything-else: picking adapter leaves out of a param
tree (the Trainer freezes the rest), and folding trained adapters back
into the base kernels so serving/export see a plain dense model —
handling both the module layout ({name}_lora_a/kernel) and the
raw-array layout ({name}_lora_a beside the stack).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_A, _B = "_lora_a", "_lora_b"


def is_lora_path(path) -> bool:
    """True for a jax.tree_util key path inside a LoRA adapter module."""
    for k in path:
        name = getattr(k, "key", None)
        if isinstance(name, str) and (name.endswith(_A) or name.endswith(_B)):
            return True
    return False


def lora_mask(params: Any) -> Any:
    """Bool pytree: True on adapter leaves — feed to ``optax.masked`` so
    the optimizer updates ONLY the adapters (and allocates moments only
    for them: an 8B base at rank 16 keeps ~0.2% of Adam state)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: is_lora_path(path), params
    )


def has_lora(params: Any) -> bool:
    return any(jax.tree_util.tree_leaves(lora_mask(params)))


def merge_lora(
    params: Any, rank: int | None = None, *, alpha: float
) -> Any:
    """Fold adapters into base kernels: kernel += (A ⊗ B) * alpha/rank,
    then drop the adapter params. Returns a plain base-model tree (the
    shape a rank-0 config initializes / ``to_hf`` exports / the serving
    path restores). ``tensordot`` over the rank axis handles every
    projection shape: A is [*in_dims, r], B is [r, *out_dims], kernel is
    [*in_dims, *out_dims].

    ``rank`` is recoverable from the adapters themselves (A's trailing
    dim), so passing it is optional — but if passed it is VALIDATED:
    a stale --rank would otherwise silently mis-scale every kernel.
    ``alpha`` is NOT recoverable from shapes, so it is a required
    keyword: a defaulted alpha would silently mis-scale every merged
    kernel for models trained with a non-default lora_alpha (pass
    ``cfg.lora_alpha``).
    """
    ranks = set()
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        # Module-shaped adapters: .../{name}_lora_a/kernel.
        if any(
            getattr(k, "key", None) == "kernel"
            and isinstance(getattr(prev, "key", None), str)
            and prev.key.endswith(_A)
            for prev, k in zip(path, path[1:])
        ):
            ranks.add(leaf.shape[-1])
        # Raw-array adapters (Mixtral expert stacks): the leaf ITSELF is
        # named {name}_lora_a; rank is its trailing dim.
        last = getattr(path[-1], "key", None) if path else None
        if isinstance(last, str) and last.endswith(_A):
            ranks.add(leaf.shape[-1])
    if len(ranks) == 1:
        actual = ranks.pop()
        if rank is not None and rank != actual:
            raise ValueError(
                f"merge_lora: rank={rank} but the adapters were trained "
                f"at rank {actual} — merging would mis-scale every kernel"
            )
        rank = actual
    if rank is None or rank <= 0:
        raise ValueError(
            f"merge_lora: could not infer a single adapter rank "
            f"(found {sorted(ranks) if ranks else 'none'}) and no valid "
            f"rank was given"
        )
    scale = alpha / rank
    merged_any = []

    def _delta(a, b, kernel_ndim):
        if (a.ndim - 1) + (b.ndim - 1) == kernel_ndim:
            return jnp.tensordot(a, b, axes=([-1], [0]))
        # Leading batch axes shared by a, b, and the kernel — the
        # nn.scan layer stack, the Mixtral expert axis, or both
        # ([L, E, in, r]): strip one per vmap level.
        return jax.vmap(
            lambda aa, bb: _delta(aa, bb, kernel_ndim - 1)
        )(a, b)

    def delta(a, b, kernel):
        return _delta(
            a.astype(jnp.float32), b.astype(jnp.float32), kernel.ndim
        )

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for key, val in node.items():
            if key.endswith(_A) or key.endswith(_B):
                continue  # consumed below / dropped
            a_mod = node.get(key + _A)
            b_mod = node.get(key + _B)
            if a_mod is not None and b_mod is not None:
                if isinstance(val, dict):
                    # Module layout: {name}/{kernel}, adapters are
                    # sibling modules with their own kernels.
                    kernel = val["kernel"]
                    d = (
                        delta(a_mod["kernel"], b_mod["kernel"], kernel)
                        * scale
                    )
                    out[key] = {
                        **val, "kernel": kernel + d.astype(kernel.dtype)
                    }
                else:
                    # Raw-array layout (Mixtral expert stacks): base and
                    # adapters are bare [E, ...] arrays side by side.
                    d = delta(a_mod, b_mod, val) * scale
                    out[key] = val + d.astype(val.dtype)
                merged_any.append(key)
            else:
                out[key] = walk(val)
        return out

    merged = walk(params)
    if not merged_any:
        # Defensive: merging a tree with no adapters is a caller bug.
        raise ValueError("merge_lora: no *_lora_a/_lora_b modules found")
    return merged
