"""Gemma-2 family on the shared decoder machinery (tpufw.models.llama).

The reference has no ML layer at all (its workload is ``nvidia-smi``,
reference README.md:314); Gemma-2 extends the additive model zoo beyond
the BASELINE-mandated Llama/Mixtral/ResNet using the same trunk
(``decoder_lm``), attention entry point, and logical-axis sharding —
only the block differs. Gemma-2 specifics, all HF-parity-pinned
(tests/test_gemma.py):

- sandwich norms: pre AND post RMSNorm around both attention and MLP,
  all in the (1 + w) offset parameterization (zeros-init weights);
- GeGLU MLP (tanh-approximate gelu gate, cfg.mlp_activation);
- sqrt(d_model) embedding scaling (cfg.embed_scale);
- attention logit soft-cap (50.0) and final logit soft-cap (30.0) —
  both run inside the flash kernel / chunked-CE paths, not just xla;
- alternating local/global attention: even layers use a sliding window
  (cfg.sliding_window), odd layers attend globally. The layer stack
  scans PAIRS (one local + one global block) so ``nn.scan`` still sees
  a uniform unit; ``n_layers`` must be even;
- query scaling by query_pre_attn_scalar**-0.5 instead of
  head_dim**-0.5 (equal for 2b/9b, differs for 27b).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp
from jax import ad_checkpoint
from flax import linen as nn

from tpufw.models.llama import (
    MLP,
    Attention,
    RMSNorm,
    decoder_lm,
)

Dtype = Any


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    vocab_size: int = 256_000
    d_model: int = 2304
    n_layers: int = 26
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 256
    d_ff: int = 9216
    rope_theta: float = 10_000.0
    rms_eps: float = 1e-6
    max_seq_len: int = 8192
    tie_embeddings: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    attention_backend: str = "xla"
    remat: bool = True
    remat_policy: str = "dots"
    scan_layers: bool = True
    decode: bool = False
    # Gemma-2 specifics (read by the shared trunk/blocks via getattr).
    attn_logit_soft_cap: Optional[float] = 50.0
    final_logit_soft_cap: Optional[float] = 30.0
    sliding_window: Optional[int] = 4096
    query_pre_attn_scalar: Optional[float] = 256.0
    mlp_activation: str = "gelu_tanh"
    embed_scale: bool = True
    rms_offset: bool = True
    # LoRA adapters on attention/MLP projections (see LlamaConfig).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Weight-only int8 serving form (see LlamaConfig / tpufw.ops.quant).
    quantized_weights: bool = False

    def decode_config(self) -> "GemmaConfig":
        """Inference dress: KV cache on, remat off, xla attention."""
        return dataclasses.replace(
            self, decode=True, remat=False, attention_backend="xla"
        )

    def n_params(self, include_embed: bool = True) -> int:
        d, l = self.d_model, self.n_layers
        attn = l * (
            d * self.n_heads * self.head_dim
            + 2 * d * self.n_kv_heads * self.head_dim
            + self.n_heads * self.head_dim * d
        )
        mlp = l * 3 * d * self.d_ff
        norms = (4 * l + 1) * d  # sandwich: 4 norms per layer + final
        total = attn + mlp + norms
        if include_embed:
            total += self.vocab_size * d  # head tied
            if not self.tie_embeddings:
                total += d * self.vocab_size
        return total

    def flops_per_token(self, seq_len: int) -> float:
        d, l = self.d_model, self.n_layers
        n_matmul = (
            l
            * (
                d * self.n_heads * self.head_dim
                + 2 * d * self.n_kv_heads * self.head_dim
                + self.n_heads * self.head_dim * d
                + 3 * d * self.d_ff
            )
            + d * self.vocab_size
        )
        # Attention score FLOPs: global layers see the full causal
        # triangle (~seq/2 keys per query); local layers at most the
        # window. Half the layers each.
        global_keys = seq_len / 2
        local_keys = min(
            float(self.sliding_window or seq_len), seq_len / 2
        )
        attn_score = (
            6.0
            * self.n_heads
            * self.head_dim
            * (l / 2)
            * 2.0  # QK^T and AV
            * (global_keys + local_keys)
        )
        return 6.0 * n_matmul + attn_score


class GemmaBlock(nn.Module):
    """One Gemma-2 block: sandwich-normed attention + GeGLU MLP."""

    cfg: GemmaConfig
    window: Optional[int] = None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        norm = lambda name: RMSNorm(  # noqa: E731
            cfg.rms_eps, offset=True, name=name
        )
        a = Attention(cfg, window=self.window, name="attn")(
            norm("pre_attn_norm")(x), positions, segment_ids
        )
        # Tag for remat_policy="attn_out" (no-op under other policies).
        a = ad_checkpoint.checkpoint_name(a, "attn_out")
        x = x + norm("post_attn_norm")(a)
        m = MLP(cfg, name="mlp")(norm("pre_mlp_norm")(x))
        x = x + norm("post_mlp_norm")(m)
        return nn.with_logical_constraint(
            x, ("batch", "act_seq", "act_embed")
        )


class GemmaPair(nn.Module):
    """The scanned unit: local (sliding-window) block then global block —
    Gemma-2's alternation with layer 0 local, matching HF's layer_types
    (even index -> sliding_window)."""

    cfg: GemmaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        x = GemmaBlock(
            cfg, window=cfg.sliding_window, name="local"
        )(x, positions, segment_ids)
        x = GemmaBlock(cfg, window=None, name="global")(
            x, positions, segment_ids
        )
        return x


class Gemma(nn.Module):
    """Decoder-only Gemma-2 LM. Returns logits [B, T, vocab]."""

    cfg: GemmaConfig

    @nn.compact
    def __call__(
        self, tokens, positions=None, segment_ids=None, return_hidden=False
    ):
        # Validated here, not in the (frozen) config's __post_init__ —
        # the pair-halving replace() below would re-trigger a post-init
        # check and reject any pair count that is itself odd (26-layer
        # 2b, 42-layer 9b).
        if self.cfg.n_layers % 2:
            raise ValueError(
                f"Gemma-2 alternates local/global layers; n_layers must "
                f"be even, got {self.cfg.n_layers}"
            )
        # The trunk scans pairs: halve n_layers for the scan length.
        trunk_cfg = dataclasses.replace(
            self.cfg, n_layers=self.cfg.n_layers // 2
        )
        out = decoder_lm(
            trunk_cfg, GemmaPair, tokens, positions, segment_ids, False,
            return_hidden=return_hidden,
        )
        cap = self.cfg.final_logit_soft_cap
        if cap is not None and not return_hidden:
            # Hidden-states callers (the chunked-vocab CE path) apply the
            # cap per chunk in tpufw.ops.loss.chunked_cross_entropy.
            from tpufw.ops.attention import tanh_soft_cap

            out = tanh_soft_cap(out, cap)
        return out


GEMMA_CONFIGS: dict[str, GemmaConfig] = {
    "gemma2_2b": GemmaConfig(attention_backend="flash"),
    # 2.6B: the HF google/gemma-2-2b shape
    "gemma2_9b": GemmaConfig(
        d_model=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        d_ff=14_336,
        attention_backend="flash",
    ),
    "gemma2_tiny": GemmaConfig(
        vocab_size=256,
        d_model=64,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        max_seq_len=128,
        sliding_window=32,
        query_pre_attn_scalar=16.0,
        attn_logit_soft_cap=50.0,
        final_logit_soft_cap=30.0,
        remat=False,
    ),
}
