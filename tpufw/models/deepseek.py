"""DeepSeek-V2 family: Multi-head Latent Attention (MLA), TPU-first.

The reference has no ML layer at all (its workload is ``nvidia-smi``,
reference ``README.md:314``); this family joins Llama/Mistral/Qwen/
Mixtral/Gemma-2 because MLA is THE architecture whose win is
memory-system-shaped — exactly what a TPU framework should exploit:

- **Latent KV cache.** Attention keys/values are low-rank: one shared
  latent ``c_kv = x @ W_dkv`` of ``kv_lora_rank`` dims (plus a small
  decoupled-RoPE key) is cached instead of per-head K and V. For the
  V2-Lite shape the cache is ``(512 + 64)`` floats/token vs Llama-8B's
  ``2 * 8 * 128 = 2048`` — 3.6x less HBM, and decode is HBM-bound.
- **Absorbed decode.** The decode path never expands the latents back
  to per-head K/V: ``W_uk`` is absorbed into the query (scores are
  taken IN latent space against the cached ``c_kv``) and ``W_uv`` is
  applied once to the attention-weighted latents — per step the cache
  traffic is the latent, not H-times-expanded tensors. Training uses
  the expanded form (one big MXU-friendly einsum per projection);
  tests/test_deepseek.py pins prefill-vs-decode equivalence between
  the two forms.
- **Decoupled RoPE.** Rotary position goes through a separate
  ``qk_rope_head_dim`` slice (queries per head, ONE shared key slice),
  because a position rotation applied to the latent would break its
  low-rank factorization. DeepSeek rotates INTERLEAVED pairs (HF
  ``view_as_complex`` layout), unlike Llama's split-half — matched
  here exactly for checkpoint parity.

Structure mirrors tpufw.models.llama (same decoder trunk, RMSNorm,
SwiGLU MLP, remat policies, logical sharding axes) so every trainer,
parallelism mode, and tool that consumes the trunk applies unchanged.
The MoE FFN (DeepSeek's fine-grained routed experts + always-on shared
experts) rides the Mixtral einsum dispatch (tpufw.models.mixtral
MoEMLP) with the V2 gate conventions: raw softmax top-k mass (no
renormalization — matching the HF reference's executed behavior) times
``routed_scaling_factor``, plus group-limited selection (the 236B/Chat
``topk_method="group_limited_greedy"`` — ``n_group``/``topk_group``)
and yarn long-context rope scaling. Remaining import rejections
(tools/import_hf.py): other topk_methods (e.g. V3's noaux_tc),
non-softmax scoring, sparse ``moe_layer_freq``, and attention bias.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.ad_checkpoint import checkpoint_name

from tpufw.models.llama import (
    MLP,
    Dtype,
    RMSNorm,
    decoder_lm,
    projection,
)
from tpufw.models.mixtral import MoEMLP
from tpufw.ops.attention import multi_head_attention
from tpufw.ops.quant import dequantize_kv, quantize_kv


@dataclasses.dataclass(frozen=True)
class DeepseekConfig:
    """DeepSeek-V2 MLA decoder. Field names follow the HF config where
    the concepts coincide (cited: huggingface
    ``DeepseekV2Config`` / ``modeling_deepseek_v2.py``)."""

    vocab_size: int = 32_768
    d_model: int = 2048
    n_layers: int = 12
    n_heads: int = 16
    # None = full-rank q projection (the V2-Lite choice); an int adds
    # the compressed q path (q_a -> norm -> q_b, the V2 236B choice).
    q_lora_rank: Optional[int] = None
    kv_lora_rank: int = 512
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    d_ff: int = 8192
    rope_theta: float = 10_000.0
    # Yarn long-context scaling (V2/V2-Lite checkpoints); None = plain.
    rope_scaling: Optional["YarnScaling"] = None
    max_seq_len: int = 4096
    rms_eps: float = 1e-6
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    # "xla" (einsum, the correctness reference), "flash" (Pallas
    # kernel), "ring" (sequence-parallel neighbor exchange), or
    # "ulysses" (head/sequence all-to-all) over the `sequence` mesh
    # axis: MLA's v head dim is smaller than qk's, so the non-xla
    # backends zero-pad v up to qk_head_dim and slice the output back
    # — exact (padded value columns contribute zeros) at ~dv/qk_dim
    # extra v memory.
    attention_backend: str = "xla"
    # MoE dispatch implementation — see MixtralConfig.moe_dispatch
    # ("einsum" shards over the expert axis; "sorted" runs grouped
    # ragged_dot matmuls for single-device/data-sharded training).
    moe_dispatch: str = "einsum"
    remat: bool = True
    remat_policy: str = "dots"
    scan_layers: bool = True
    decode: bool = False
    tie_embeddings: bool = False
    # int8 weight-only serving (tpufw.ops.quant): projections and
    # routed/shared experts go int8; kv_b and routers stay fp.
    quantized_weights: bool = False
    # Paged latent-KV cache — same contract as tpufw.models.llama
    # LlamaConfig.kv_page/kv_pages/kv_quant, applied to the c_kv/k_pe
    # latent arenas (tpufw.infer.pages).
    kv_page: int = 0
    kv_pages: int = 0
    kv_quant: str = ""
    # --- DeepSeek MoE FFN (0 routed experts = dense everywhere) ---
    # Fine-grained routed experts per MoE layer.
    n_routed_experts: int = 0
    experts_per_token: int = 6
    # Width of EACH routed/shared expert (HF moe_intermediate_size) —
    # much narrower than the dense d_ff.
    moe_d_ff: int = 1408
    # Always-on shared experts (one fused MLP of n_shared * moe_d_ff).
    n_shared_experts: int = 2
    # Layers [0, first_k_dense) keep the dense MLP (HF
    # first_k_dense_replace). > 0 requires scan_layers=False — a scan
    # needs homogeneous layers.
    first_k_dense: int = 0
    # Multiplier on the routed output (HF routed_scaling_factor).
    routed_scaling_factor: float = 1.0
    # Renormalize top-k gate mass (False = V2 convention: raw softmax).
    norm_topk_prob: bool = False
    # Group-limited selection (HF topk_method="group_limited_greedy",
    # the 236B/Chat routing): experts partition into n_group groups,
    # only the topk_group best groups (by max score) are routable.
    # n_group=0 disables (plain greedy, the V2-Lite choice).
    n_group: int = 0
    topk_group: int = 0
    # GShard capacity discipline for the einsum dispatch; imports
    # default to dropless (n_routed_experts) like Mixtral's.
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    router_z_weight: float = 1e-3

    @property
    def n_experts(self) -> int:
        """Alias: tpufw.models.mixtral.MoEMLP reads ``cfg.n_experts``."""
        return self.n_routed_experts

    @property
    def moe(self) -> bool:
        return self.n_routed_experts > 0

    def __post_init__(self):
        if self.moe and self.first_k_dense > 0 and self.scan_layers:
            raise ValueError(
                "first_k_dense > 0 mixes dense and MoE layers — "
                "nn.scan needs homogeneous layers; set "
                "scan_layers=False (imports do this automatically)"
            )

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    def decode_config(self) -> "DeepseekConfig":
        """Inference twin: latent KV cache on, remat off. The backend
        resets to "xla" to honor the family-wide decode contract
        (llama/gemma do the same): the absorbed-latent decode path
        hand-rolls its attention and never reads the field today, but
        a flash-defaulted train preset must not leak "flash" into a
        decode config that future code may consult."""
        return dataclasses.replace(
            self, decode=True, remat=False, attention_backend="xla"
        )

    def n_params(self, include_embed: bool = True) -> int:
        d, l, h = self.d_model, self.n_layers, self.n_heads
        if self.q_lora_rank is None:
            q = d * h * self.qk_head_dim
            q_norms = 0
        else:
            q = self.q_lora_rank * (d + h * self.qk_head_dim)
            q_norms = self.q_lora_rank
        kv_a = d * (self.kv_lora_rank + self.qk_rope_head_dim)
        kv_b = self.kv_lora_rank * h * (
            self.qk_nope_head_dim + self.v_head_dim
        )
        o = h * self.v_head_dim * d
        attn = l * (q + kv_a + kv_b + o)
        n_moe_layers = (
            max(0, l - self.first_k_dense) if self.moe else 0
        )
        n_dense_layers = l - n_moe_layers
        mlp = n_dense_layers * 3 * d * self.d_ff
        if n_moe_layers:
            per_layer = (
                3 * d * self.moe_d_ff * self.n_routed_experts  # routed
                + d * self.n_routed_experts  # router
                + 3 * d * self.moe_d_ff * self.n_shared_experts  # shared
            )
            mlp += n_moe_layers * per_layer
        norms = (2 * l + 1) * d + l * (self.kv_lora_rank + q_norms)
        total = attn + mlp + norms
        if include_embed:
            head = 0 if self.tie_embeddings else self.vocab_size * d
            total += self.vocab_size * d + head
        return total

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token: 6*N_active_matmul + attention score
        FLOPs (causal-halved, x3 fwd+bwd, both QK^T and AV matmuls) —
        same convention as Llama/MixtralConfig.flops_per_token. Under
        MoE only experts_per_token routed experts run per token."""
        n_matmul = (
            self.n_params(include_embed=False)
            # norms aren't matmuls; head is.
            - (2 * self.n_layers + 1) * self.d_model
            - self.n_layers * (
                self.kv_lora_rank
                + (self.q_lora_rank or 0)
            )
            + self.d_model * self.vocab_size
        )
        if self.moe:
            # Swap total routed weights for the ACTIVE k experts.
            n_moe_layers = max(0, self.n_layers - self.first_k_dense)
            routed = 3 * self.d_model * self.moe_d_ff
            n_matmul -= n_moe_layers * routed * (
                self.n_routed_experts - self.experts_per_token
            )
        keys = seq_len / 2
        score = (
            6.0 * self.n_layers * self.n_heads
            * (self.qk_head_dim + self.v_head_dim) * keys
        )
        return 6.0 * n_matmul + score


@dataclasses.dataclass(frozen=True)
class YarnScaling:
    """Yarn long-context rope scaling (arXiv 2309.00071), matching the
    transformers reference EXACTLY (modeling_rope_utils.py
    _compute_yarn_parameters): per-dimension ramp between interpolated
    (freq / factor) and extrapolated (unscaled) frequencies, plus an
    ``attention_factor`` multiplied into cos/sin. Note the reference's
    executed behavior: when ``mscale == mscale_all_dim`` (DeepSeek-
    V2-Lite publishes 0.707 for both) the factor is exactly 1.0, and
    transformers applies NO mscale^2 to the softmax scale — parity
    targets what the reference runs, not the original repo's
    remote-code variant."""

    factor: float = 40.0
    original_max_position_embeddings: int = 4096
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    # 0.0 = unset (falsy): the ratio branch of the attention factor
    # needs BOTH mscale fields, exactly like the transformers gate.
    mscale: float = 0.0
    mscale_all_dim: float = 0.0
    attention_factor: Optional[float] = None  # None = derive below
    truncate: bool = True

    def resolved_attention_factor(self) -> float:
        import math

        def get_mscale(scale, m=1.0):
            if scale <= 1:
                return 1.0
            return 0.1 * m * math.log(scale) + 1.0

        if self.attention_factor is not None:
            return float(self.attention_factor)
        if self.mscale and self.mscale_all_dim:
            return get_mscale(self.factor, self.mscale) / get_mscale(
                self.factor, self.mscale_all_dim
            )
        return get_mscale(self.factor)


def _yarn_freqs(d: int, theta: float, s: YarnScaling) -> jax.Array:
    """[d/2] yarn inverse frequencies (transformers
    _compute_yarn_parameters, truncate semantics included)."""
    import math

    pos_freqs = theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    inv_extra = 1.0 / pos_freqs
    inv_inter = 1.0 / (s.factor * pos_freqs)

    def correction_dim(n_rot: float) -> float:
        return (
            d
            * math.log(
                s.original_max_position_embeddings / (n_rot * 2 * math.pi)
            )
        ) / (2 * math.log(theta))

    low = correction_dim(s.beta_fast)
    high = correction_dim(s.beta_slow)
    if s.truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, d - 1)
    if low == high:
        high += 0.001
    ramp = jnp.clip(
        (jnp.arange(d // 2, dtype=jnp.float32) - low) / (high - low),
        0.0,
        1.0,
    )
    extrapolation_factor = 1.0 - ramp
    return (
        inv_inter * (1.0 - extrapolation_factor)
        + inv_extra * extrapolation_factor
    )


def apply_rope_interleaved(
    x: jax.Array,
    positions: jax.Array,
    theta: float,
    scaling: Optional[YarnScaling] = None,
) -> jax.Array:
    """DeepSeek rotary: INTERLEAVED pairs (x[2i], x[2i+1]) form the
    complex components (HF ``view_as_complex`` layout,
    modeling_deepseek_v2.py apply_rotary_emb) — NOT Llama's split-half.
    x: [B, T, H, D], positions: [B, T]. With yarn ``scaling``, the
    frequencies follow the ramp and the rotated output is multiplied by
    the attention factor (the reference multiplies cos/sin; rotation is
    linear, so scaling the output is identical)."""
    d = x.shape[-1]
    if scaling is None:
        freqs = 1.0 / (
            theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
        )
        att = 1.0
    else:
        freqs = _yarn_freqs(d, theta, scaling)
        att = scaling.resolved_attention_factor()
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., 0::2], xf[..., 1::2]
    out = jnp.stack(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).reshape(x.shape)
    if att != 1.0:
        out = out * att
    return out.astype(x.dtype)


class MLAttention(nn.Module):
    """Multi-head Latent Attention: expanded form for training,
    absorbed latent form for KV-cache decode."""

    cfg: DeepseekConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h, dn, dr, dv = (
            cfg.n_heads,
            cfg.qk_nope_head_dim,
            cfg.qk_rope_head_dim,
            cfg.v_head_dim,
        )

        # Queries: full-rank, or compressed (q_a -> norm -> q_b).
        if cfg.q_lora_rank is None:
            q = projection(
                cfg, x, (h, cfg.qk_head_dim), -1,
                ("embed",), ("q_heads", "head_dim"), "q",
            )
        else:
            cq = projection(
                cfg, x, cfg.q_lora_rank, -1,
                ("embed",), ("q_latent",), "q_a",
            )
            cq = RMSNorm(cfg.rms_eps, name="q_a_norm")(cq)
            q = projection(
                cfg, cq, (h, cfg.qk_head_dim), -1,
                ("q_latent",), ("q_heads", "head_dim"), "q_b",
            )
        q_nope, q_pe = q[..., :dn], q[..., dn:]
        q_pe = apply_rope_interleaved(
            q_pe, positions, cfg.rope_theta, cfg.rope_scaling
        )

        # Shared KV latent + decoupled-rope key (one "head").
        ckv_kr = projection(
            cfg, x, cfg.kv_lora_rank + dr, -1,
            ("embed",), ("kv_latent",), "kv_a",
        )
        c_kv = RMSNorm(cfg.rms_eps, name="kv_a_norm")(
            ckv_kr[..., : cfg.kv_lora_rank]
        )
        k_pe = apply_rope_interleaved(
            ckv_kr[..., cfg.kv_lora_rank:][:, :, None, :],
            positions,
            cfg.rope_theta,
            cfg.rope_scaling,
        )  # [B, T, 1, dr]

        # The latent up-projection W_ukv as a RAW kernel: the absorbed
        # decode path contracts its W_uk / W_uv halves separately, so
        # both paths must read the same parameter.
        kv_b = self.param(
            "kv_b_kernel",
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(),
                ("kv_latent", "q_heads", "head_dim"),
            ),
            (cfg.kv_lora_rank, h, dn + dv),
            cfg.param_dtype,
        )

        if cfg.decode:
            out = self._absorbed_cached_attention(
                q_nope, q_pe, c_kv, k_pe[:, :, 0, :], kv_b, segment_ids
            )
        else:
            kv = jnp.einsum(
                "btr,rhd->bthd",
                c_kv.astype(cfg.dtype),
                kv_b.astype(cfg.dtype),
            )
            k_nope, v = kv[..., :dn], kv[..., dn:]
            k = jnp.concatenate(
                [k_nope, jnp.broadcast_to(k_pe, (*k_nope.shape[:3], dr))],
                axis=-1,
            )
            q = jnp.concatenate([q_nope, q_pe], axis=-1)
            q = nn.with_logical_constraint(
                q, ("batch", "act_seq", "act_heads", "head_dim")
            )
            k = nn.with_logical_constraint(
                k, ("batch", "act_seq", "act_heads", "head_dim")
            )
            v = nn.with_logical_constraint(
                v, ("batch", "act_seq", "act_heads", "head_dim")
            )
            # Scale is qk_head_dim**-0.5 everywhere — the backends
            # derive it from q's last dim, which IS qk_head_dim here.
            if cfg.attention_backend == "xla":
                out = multi_head_attention(
                    q, k, v, causal=True, segment_ids=segment_ids,
                    backend="xla",
                )
            elif cfg.attention_backend in ("flash", "ring", "ulysses"):
                # Zero-pad v to the qk head dim: softmax(QK^T) @ [v|0]
                # = [out|0], so slicing recovers the exact result; the
                # kernels then see ONE head dim everywhere (ulysses
                # additionally all-to-alls the padded head axis — the
                # decoupled-rope key is already broadcast per head, so
                # the exchange sees plain [B,T,H,D] tensors). Dispatch
                # through the shared entry point (ops.attention) so
                # backend plumbing can't drift per-model.
                v_pad = jnp.pad(
                    v, ((0, 0), (0, 0), (0, 0), (0, cfg.qk_head_dim - dv))
                )
                out = multi_head_attention(
                    q, k, v_pad, causal=True, segment_ids=segment_ids,
                    backend=cfg.attention_backend,
                )[..., :dv]
            else:
                raise NotImplementedError(
                    "MLA attention backends: 'xla', 'flash', 'ring', "
                    f"or 'ulysses'; got {cfg.attention_backend!r}"
                )
        return projection(
            cfg, out, cfg.d_model, (-2, -1),
            ("heads", "head_dim"), ("embed",), "o",
        )

    def _absorbed_cached_attention(
        self, q_nope, q_pe, c_kv, k_pe, kv_b, segment_ids
    ):
        """Decode with the latent cache and absorbed up-projections.

        Cache holds ``c_kv`` [B, S, kvr] + roped ``k_pe`` [B, S, dr]
        (the MLA memory win). Scores: W_uk is folded into the query
        (``q_lat = q_nope @ W_uk``), so nope-scores contract in latent
        space; the output contracts attention-weighted latents with
        W_uv once. Slot-ordered causality + segment masking follow
        tpufw.models.llama Attention._cached_attention exactly.
        """
        cfg = self.cfg
        b, t = q_nope.shape[:2]
        kvr, dr = cfg.kv_lora_rank, cfg.qk_rope_head_dim
        dn = cfg.qk_nope_head_dim

        seg = (
            jnp.ones((b, t), jnp.int32) if segment_ids is None
            else segment_ids.astype(jnp.int32)
        )
        if getattr(cfg, "kv_page", 0):
            # Paged latent arenas — layout/masking contract mirrors
            # llama Attention._paged_cached_attention (page 0 reserved,
            # gather reconstructs the logical row in slot order, junk
            # beyond the cursor dies in the causal fill below; t > 1
            # is the speculative verify block, same slot-ordered
            # causality over the just-scattered tokens). Prefill runs
            # contiguous and is paged at insert (tpufw.infer.pages).
            page, n_pages = cfg.kv_page, cfg.kv_pages
            if cfg.max_seq_len % page:
                raise ValueError(
                    f"kv_page={page} must divide "
                    f"max_seq_len={cfg.max_seq_len}"
                )
            per_row = cfg.max_seq_len // page
            quant = cfg.kv_quant == "int8"
            kv_dtype = jnp.int8 if quant else cfg.dtype
            cc = self.variable(
                "cache", "cached_ckv",
                jnp.zeros, (n_pages, page, kvr), kv_dtype,
            )
            cp = self.variable(
                "cache", "cached_kpe",
                jnp.zeros, (n_pages, page, dr), kv_dtype,
            )
            cseg = self.variable(
                "cache", "cached_segment_ids",
                jnp.zeros, (n_pages, page), jnp.int32,
            )
            table = self.variable(
                "cache", "page_table", jnp.zeros, (b, per_row), jnp.int32
            )
            cursor = self.variable(
                "cache", "cache_index", jnp.zeros, (b,), jnp.int32
            )
            if quant:
                ccs = self.variable(
                    "cache", "cached_ckv_scale",
                    jnp.zeros, (n_pages, page), jnp.float32,
                )
                cps = self.variable(
                    "cache", "cached_kpe_scale",
                    jnp.zeros, (n_pages, page), jnp.float32,
                )
            cur = cursor.value
            cur_w = jnp.minimum(cur, cfg.max_seq_len - t)
            wslot = cur_w[:, None] + jnp.arange(t)[None, :]  # [B, t]
            phys = table.value[jnp.arange(b)[:, None], wslot // page]
            off = wslot % page
            if quant:
                qc, sc = quantize_kv(c_kv, n_feat=1)
                qp, sp = quantize_kv(k_pe, n_feat=1)
                cc.value = cc.value.at[phys, off].set(qc)
                cp.value = cp.value.at[phys, off].set(qp)
                ccs.value = ccs.value.at[phys, off].set(sc)
                cps.value = cps.value.at[phys, off].set(sp)
            else:
                cc.value = cc.value.at[phys, off].set(
                    c_kv.astype(cfg.dtype)
                )
                cp.value = cp.value.at[phys, off].set(
                    k_pe.astype(cfg.dtype)
                )
            cseg.value = cseg.value.at[phys, off].set(seg)
            cursor.value = cur + t
            idx = table.value
            s = cfg.max_seq_len
            if quant:
                ckv_all = dequantize_kv(
                    cc.value[idx], ccs.value[idx], cfg.dtype
                ).reshape(b, s, kvr)
                kpe_all = dequantize_kv(
                    cp.value[idx], cps.value[idx], cfg.dtype
                ).reshape(b, s, dr)
            else:
                ckv_all = cc.value[idx].reshape(b, s, kvr)
                kpe_all = cp.value[idx].reshape(b, s, dr)
            cseg_all = cseg.value[idx].reshape(b, s)
        else:
            cc = self.variable(
                "cache", "cached_ckv",
                jnp.zeros, (b, cfg.max_seq_len, kvr), cfg.dtype,
            )
            cp = self.variable(
                "cache", "cached_kpe",
                jnp.zeros, (b, cfg.max_seq_len, dr), cfg.dtype,
            )
            cseg = self.variable(
                "cache", "cached_segment_ids",
                jnp.zeros, (b, cfg.max_seq_len), jnp.int32,
            )
            cursor = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            cur = cursor.value
            if cur.ndim == 0:
                cc.value = jax.lax.dynamic_update_slice(
                    cc.value, c_kv.astype(cfg.dtype), (0, cur, 0)
                )
                cp.value = jax.lax.dynamic_update_slice(
                    cp.value, k_pe.astype(cfg.dtype), (0, cur, 0)
                )
                cseg.value = jax.lax.dynamic_update_slice(
                    cseg.value, seg, (0, cur)
                )
                cur_w = cur
            else:
                # Per-row cursors [B] (tpufw.infer.slots pool decode) —
                # see llama Attention._cached_attention for the clamp
                # rationale.
                cur_w = jnp.minimum(cur, cfg.max_seq_len - t)
                rows = jnp.arange(b)[:, None]
                cols = cur_w[:, None] + jnp.arange(t)[None, :]
                cc.value = cc.value.at[rows, cols].set(
                    c_kv.astype(cfg.dtype)
                )
                cp.value = cp.value.at[rows, cols].set(
                    k_pe.astype(cfg.dtype)
                )
                cseg.value = cseg.value.at[rows, cols].set(seg)
            cursor.value = cur + t
            ckv_all, kpe_all, cseg_all = cc.value, cp.value, cseg.value

        w_uk, w_uv = kv_b[..., :dn], kv_b[..., dn:]  # [kvr, H, dn/dv]
        # Absorb W_uk into the query: [B,T,H,dn] x [kvr,H,dn] -> latent
        # queries [B,T,H,kvr].
        q_lat = jnp.einsum(
            "bthd,rhd->bthr",
            q_nope.astype(cfg.dtype),
            w_uk.astype(cfg.dtype),
        )
        s = cfg.max_seq_len
        logits = (
            jnp.einsum(
                "bthr,bsr->bhts", q_lat, ckv_all,
                preferred_element_type=jnp.float32,
            )
            + jnp.einsum(
                "bthd,bsd->bhts", q_pe.astype(cfg.dtype), kpe_all,
                preferred_element_type=jnp.float32,
            )
        ) * (float(cfg.qk_head_dim) ** -0.5)
        # Causality over cache SLOTS (RoPE positions lag slots under
        # left-padding); never-written slots keep segment 0. With
        # per-row cursors this is [B,T,1] instead of [1,T,1].
        slot_pos = (cur_w[..., None] + jnp.arange(t))[..., None]
        mask = slot_pos >= jnp.arange(s)  # [.,T,S]
        if mask.ndim == 2:
            mask = mask[None]
        seg_mask = seg[:, :, None] == cseg_all[:, None, :]  # [B,T,S]
        logits = jnp.where(
            (mask & seg_mask)[:, None, :, :], logits, -1e30
        )
        probs = jax.nn.softmax(logits, axis=-1).astype(cfg.dtype)
        # Attention-weighted latents, then ONE W_uv application.
        ctx_lat = jnp.einsum("bhts,bsr->bthr", probs, ckv_all)
        return jnp.einsum(
            "bthr,rhd->bthd", ctx_lat, w_uv.astype(cfg.dtype)
        )


class DeepseekMoE(nn.Module):
    """DeepSeek MoE FFN: fine-grained routed experts (einsum dispatch,
    tpufw.models.mixtral.MoEMLP with the V2 gate conventions) plus
    always-on shared experts fused into one wide SwiGLU. Returns
    (y, aux_loss)."""

    cfg: DeepseekConfig

    @nn.compact
    def __call__(self, x, valid=None):
        cfg = self.cfg
        routed, aux = MoEMLP(
            cfg,
            d_ff=cfg.moe_d_ff,
            norm_topk=cfg.norm_topk_prob,
            group_limit=(
                (cfg.n_group, cfg.topk_group) if cfg.n_group else None
            ),
            name="routed",
        )(x, valid=valid)
        y = routed * cfg.routed_scaling_factor
        if cfg.n_shared_experts:
            y = y + MLP(
                cfg,
                d_ff=cfg.moe_d_ff * cfg.n_shared_experts,
                name="shared",
            )(x)
        return y, aux


class DeepseekBlock(nn.Module):
    cfg: DeepseekConfig

    def _layer_index(self) -> Optional[int]:
        """Unscanned layers are named ``layer_{i}`` by decoder_lm; the
        scanned stack shares one set of weights across layers and has
        no index (homogeneous by construction)."""
        name = self.name or ""
        if name.startswith("layer_"):
            return int(name.split("_", 1)[1])
        return None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        attn_out = MLAttention(cfg, name="attn")(
            RMSNorm(cfg.rms_eps, name="attn_norm")(x), positions, segment_ids
        )
        x = x + checkpoint_name(attn_out, "attn_out")
        h = RMSNorm(cfg.rms_eps, name="mlp_norm")(x)
        idx = self._layer_index()
        use_moe = cfg.moe and (idx is None or idx >= cfg.first_k_dense)
        if use_moe:
            y, aux = DeepseekMoE(cfg, name="moe")(
                h,
                valid=None if segment_ids is None else segment_ids > 0,
            )
        else:
            y, aux = MLP(cfg, name="mlp")(h), jnp.zeros((), jnp.float32)
        x = nn.with_logical_constraint(
            x + y, ("batch", "act_seq", "act_embed")
        )
        return (x, aux) if cfg.moe else x


class Deepseek(nn.Module):
    """Decoder-only DeepSeek-V2 LM (dense or MoE FFN). Returns logits,
    or (logits, aux_loss) for MoE configs when ``return_aux`` (the
    Mixtral contract — train_step adds aux into the objective)."""

    cfg: DeepseekConfig

    @nn.compact
    def __call__(
        self, tokens, positions=None, segment_ids=None, return_aux=True,
        return_hidden=False,
    ):
        cfg = self.cfg
        out = decoder_lm(
            cfg, DeepseekBlock, tokens, positions, segment_ids, cfg.moe,
            return_hidden=return_hidden,
        )
        if not cfg.moe:
            return out
        logits, aux = out
        if return_aux:
            return logits, aux / cfg.n_layers
        return logits


DEEPSEEK_CONFIGS: dict[str, DeepseekConfig] = {
    # Test-scale config (CPU mesh, parity tests).
    "deepseek_tiny": DeepseekConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        kv_lora_rank=32,
        qk_nope_head_dim=16,
        qk_rope_head_dim=8,
        v_head_dim=16,
        d_ff=128,
        max_seq_len=128,
        remat=False,
    ),
    # Same, exercising the compressed-q path (V2-236B style).
    "deepseek_tiny_qlora": DeepseekConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        q_lora_rank=24,
        kv_lora_rank=32,
        qk_nope_head_dim=16,
        qk_rope_head_dim=8,
        v_head_dim=16,
        d_ff=128,
        max_seq_len=128,
        remat=False,
    ),
    # MoE test preset: 4 fine-grained routed experts top-2 + 1 shared,
    # all-MoE (scan-compatible), V2 gate conventions.
    "deepseek_moe_tiny": DeepseekConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        kv_lora_rank=32,
        qk_nope_head_dim=16,
        qk_rope_head_dim=8,
        v_head_dim=16,
        d_ff=128,
        n_routed_experts=4,
        experts_per_token=2,
        moe_d_ff=48,
        n_shared_experts=1,
        capacity_factor=4.0,  # dropless at test scale
        max_seq_len=128,
        remat=False,
    ),
    # V2-Lite attention geometry (HF deepseek-ai/DeepSeek-V2-Lite:
    # d=2048, 16 heads, kv_lora 512, 128/64/128 head dims) with a dense
    # FFN sized to one v5e chip — NOT checkpoint-compatible with
    # V2-Lite (whose FFN is MoE and whose rope is yarn); it is the
    # bench shape for the MLA attention path.
    "deepseek_mla_bench": DeepseekConfig(
        vocab_size=32_768,
        d_model=2048,
        n_layers=10,
        n_heads=16,
        kv_lora_rank=512,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
        d_ff=6144,
        max_seq_len=4096,
        attention_backend="flash",
    ),
}
