from tpufw.models.gemma import (  # noqa: F401
    GEMMA_CONFIGS,
    Gemma,
    GemmaConfig,
)
from tpufw.models.llama import Llama, LlamaConfig, LLAMA_CONFIGS  # noqa: F401
from tpufw.models.mixtral import (  # noqa: F401
    MIXTRAL_CONFIGS,
    Mixtral,
    MixtralConfig,
    MoEMLP,
)
from tpufw.models.resnet import ResNet, ResNetConfig, resnet50  # noqa: F401
from tpufw.models.lora import (  # noqa: F401
    has_lora,
    lora_mask,
    merge_lora,
)
