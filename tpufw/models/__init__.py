from tpufw.models.deepseek import (  # noqa: F401
    DEEPSEEK_CONFIGS,
    Deepseek,
    DeepseekConfig,
)
from tpufw.models.gemma import (  # noqa: F401
    GEMMA_CONFIGS,
    Gemma,
    GemmaConfig,
)
from tpufw.models.llama import (  # noqa: F401
    Llama,
    LlamaConfig,
    LLAMA_CONFIGS,
    RopeScaling,
    unstack_layer_params,
)
from tpufw.models.mixtral import (  # noqa: F401
    MIXTRAL_CONFIGS,
    Mixtral,
    MixtralConfig,
    MoEMLP,
)
from tpufw.models.resnet import ResNet, ResNetConfig, resnet50  # noqa: F401
from tpufw.models.vit import (  # noqa: F401
    VIT_CONFIGS,
    ViT,
    ViTConfig,
    vit_b16,
)
from tpufw.models.lora import (  # noqa: F401
    has_lora,
    lora_mask,
    merge_lora,
)


def model_for_config(cfg):
    """Model class instance for a config dataclass — the ONE
    config->architecture dispatch (serving, eval tools)."""
    from tpufw.models.deepseek import DeepseekConfig
    from tpufw.models.gemma import GemmaConfig
    from tpufw.models.mixtral import MixtralConfig
    from tpufw.models.resnet import ResNetConfig

    if isinstance(cfg, ResNetConfig):
        raise ValueError(
            "model_for_config covers the LM families; vision runs use "
            "tpufw.train.VisionTrainer / workloads.train_resnet"
        )
    if isinstance(cfg, DeepseekConfig):
        return Deepseek(cfg)
    if isinstance(cfg, MixtralConfig):
        return Mixtral(cfg)
    if isinstance(cfg, GemmaConfig):
        return Gemma(cfg)
    if isinstance(cfg, LlamaConfig):
        return Llama(cfg)
    raise TypeError(f"unknown model config type {type(cfg).__name__}")
