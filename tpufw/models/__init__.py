from tpufw.models.llama import Llama, LlamaConfig, LLAMA_CONFIGS  # noqa: F401
