"""Vision Transformer (ViT), TPU-first — the MXU-native vision family.

The reference ships no ML workloads at all (its proof is ``nvidia-smi``,
reference ``README.md:303-335``); ResNet-50 covers BASELINE config 2's
conv path, and ViT extends the vision zoo with the architecture TPUs
are actually built for: patchify turns the image into a short token
sequence and EVERYTHING downstream is a large batched matmul. Measured
motivation: ResNet's strided-conv backward holds it to ~16% MFU on v5e
(docs/PERF.md) while transformer blocks of the same FLOP budget run at
40%+ on the same chip.

TPU-first choices, mirroring the LM trunk (tpufw.models.llama):
- patch embedding as reshape + one [P*P*3, D] matmul (NOT a conv — the
  identical computation, but it lowers to a plain MXU GEMM with no
  im2col window machinery);
- bf16 activations / f32 params, f32 LayerNorm arithmetic;
- logical axis names shared with the LM families ("embed", "mlp",
  "q_heads", "kv") so `tpufw.mesh.logical_axis_rules` shards it for
  fsdp/tensor with zero model edits;
- `nn.scan` over blocks + optional remat, same knobs as LlamaConfig;
- attention is plain bidirectional softmax(QK^T)V via einsum: at ViT
  sequence lengths (197 tokens for 224px/16) the score matrix is tiny
  and XLA fuses it; the flash kernel's tiling would only add overhead.

Works with the shared ``VisionTrainer`` (images/labels batches, MFU
metering, checkpoint/preemption) — ViT simply has no batch_stats.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32
    # "cls" = classify from the [CLS] token (canonical ViT);
    # "mean" = mean-pool patch tokens (no extra token).
    pool: str = "cls"
    remat: bool = False
    scan_layers: bool = True

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}"
            )
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide into n_heads")
        if self.pool not in ("cls", "mean"):
            raise ValueError(f"pool must be 'cls'|'mean', got {self.pool!r}")

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.n_patches + (1 if self.pool == "cls" else 0)

    def n_params(self) -> int:
        d, l, f = self.d_model, self.n_layers, self.d_ff
        patch = (self.patch_size**2 * 3) * d + d
        pos = self.seq_len * d + (d if self.pool == "cls" else 0)
        attn = l * (4 * d * d + 4 * d)  # qkvo kernels + biases
        mlp = l * (2 * d * f + f + d)
        norms = l * 2 * 2 * d + 2 * d  # 2 LN/block + final, scale+bias
        head = d * self.num_classes + self.num_classes
        return patch + pos + attn + mlp + norms + head

    def flops_per_image(self, image_size: Optional[int] = None) -> float:
        """Training FLOPs per image: 3x (fwd + bwd@2x) the forward
        matmul FLOPs (2 per MAC). Covers patchify, per-token block
        matmuls, the bidirectional QK^T/AV score matmuls (t keys per
        query — no causal halving), and the head."""
        del image_size  # signature-compatible with ResNetConfig
        d, l, t, f = self.d_model, self.n_layers, self.seq_len, self.d_ff
        macs = (
            self.n_patches * (self.patch_size**2 * 3 * d)  # patchify
            + l * t * (4 * d * d + 2 * d * f)  # qkvo + MLP
            + 2 * l * t * t * d  # QK^T and AV
            + d * self.num_classes  # head (pooled: one token)
        )
        return 3.0 * 2.0 * macs


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feat, axes, name: nn.Dense(  # noqa: E731
            feat,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), axes
            ),
            name=name,
        )
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            dtype=jnp.float32, param_dtype=cfg.param_dtype, name=name
        )
        d, h = cfg.d_model, cfg.n_heads
        hd = d // h

        # --- bidirectional self-attention ---
        y = ln("attn_norm")(x).astype(cfg.dtype)
        q = dense(d, ("embed", "q_heads"), "q")(y)
        k = dense(d, ("embed", "kv"), "k")(y)
        v = dense(d, ("embed", "kv"), "v")(y)
        b, t = y.shape[0], y.shape[1]
        q = q.reshape(b, t, h, hd)
        k = k.reshape(b, t, h, hd)
        v = v.reshape(b, t, h, hd)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * (hd**-0.5)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, t, d)
        x = x + dense(d, ("q_heads", "embed"), "o")(o)

        # --- MLP ---
        y = ln("mlp_norm")(x).astype(cfg.dtype)
        y = dense(cfg.d_ff, ("embed", "mlp"), "up")(y)
        y = nn.gelu(y, approximate=True)
        x = x + dense(d, ("mlp", "embed"), "down")(y)
        return nn.with_logical_constraint(
            x, ("batch", "act_seq", "act_embed")
        )


class ViT(nn.Module):
    """ViT classifier. Input NHWC float images, returns [B, num_classes]
    (f32). ``train`` is accepted for VisionTrainer signature parity; the
    model is deterministic either way (no dropout, no batch stats)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = False):
        del train
        cfg = self.cfg
        b = images.shape[0]
        p, g = cfg.patch_size, cfg.image_size // cfg.patch_size
        x = images.astype(cfg.dtype)
        # Patchify as reshape->transpose->matmul: [B,H,W,C] ->
        # [B, g*g, p*p*C] @ [p*p*C, D]. Identical math to a stride-p
        # conv, but lowers to one clean MXU GEMM.
        x = x.reshape(b, g, p, g, p, 3)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p * p * 3)
        x = nn.Dense(
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.xavier_uniform(), ("patch_in", "embed")
            ),
            name="patch_embed",
        )(x)
        if cfg.pool == "cls":
            cls = self.param(
                "cls_token",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), (None, None, "embed")
                ),
                (1, 1, cfg.d_model),
                cfg.param_dtype,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, cfg.d_model)).astype(x.dtype), x],
                axis=1,
            )
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (None, "act_seq", "embed")
            ),
            (1, cfg.seq_len, cfg.d_model),
            cfg.param_dtype,
        )
        x = x + pos.astype(x.dtype)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))

        block_cls = ViTBlock
        if cfg.remat:
            block_cls = nn.remat(
                block_cls,
                policy=jax.checkpoint_policies.nothing_saveable,
                prevent_cse=not cfg.scan_layers,
            )
        if cfg.scan_layers:

            def body(mdl, carry, _):
                return mdl(carry), None

            x, _ = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block_cls(cfg, name="blocks"), x, None)
        else:
            for i in range(cfg.n_layers):
                x = block_cls(cfg, name=f"block{i}")(x)

        x = nn.LayerNorm(
            dtype=jnp.float32, param_dtype=cfg.param_dtype, name="final_norm"
        )(x)
        pooled = x[:, 0] if cfg.pool == "cls" else jnp.mean(x, axis=1)
        return nn.Dense(
            cfg.num_classes,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), ("embed", "vocab")
            ),
            name="head",
        )(pooled)


# Production presets remat: without it the layer scan saves every
# block's f32 [B, H, T, T] attention probabilities for backward —
# 3.5 GB at ViT-B batch 128 alone, a measured compile-OOM on one
# 15.75G v5e chip; with remat, ViT-B trains at 34.7% MFU at batch 256
# (docs/PERF.md, r5).
VIT_CONFIGS: dict[str, ViTConfig] = {
    "vit_b16": ViTConfig(remat=True),  # ViT-Base/16: 86M params
    "vit_l16": ViTConfig(
        d_model=1024, n_layers=24, n_heads=16, d_ff=4096, remat=True
    ),  # ViT-Large/16: 304M
    "vit_s16": ViTConfig(
        d_model=384, n_layers=12, n_heads=6, d_ff=1536, remat=True
    ),  # ViT-Small/16: 22M
}


def vit_b16(num_classes: int = 1000, **kw) -> ViT:
    # Delegates to the preset so the factory and VIT_CONFIGS["vit_b16"]
    # cannot drift (both carry the production remat default).
    return ViT(
        dataclasses.replace(
            VIT_CONFIGS["vit_b16"], num_classes=num_classes, **kw
        )
    )
