"""Mixtral-8x7B MoE family — BASELINE config 5 (expert parallel, stretch).

Sparse mixture-of-experts with top-k routing, built the GSPMD way: routing
is pure einsum algebra over a capacity-bounded dispatch tensor, expert
weights carry an ``expert`` logical axis that tpufw.mesh maps onto the
``expert`` mesh axis, and XLA's partitioner emits the all-to-alls. No
per-expert Python loops, no send/recv — the dispatch einsum IS the
communication, which is exactly how expert parallelism should look on an
ICI-connected TPU mesh (vs. the NCCL alltoall wiring a GPU MoE stack
hand-rolls; the reference itself has no parallelism at all, SURVEY.md §2c).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import ad_checkpoint
from flax import linen as nn

from tpufw.models.llama import (
    Attention,
    LlamaConfig,
    RMSNorm,
    decoder_lm,
    reject_quant_lora,
)
from tpufw.ops.moe import (
    expert_capacity,
    route_topk_capacity,
    route_topk_sorted,
)


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    n_experts: int = 8
    experts_per_token: int = 2
    # Per-expert buffer = capacity_factor * (tokens * k / n_experts).
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    router_z_weight: float = 1e-3
    # "einsum": one-hot dispatch/combine contractions — the tensors ARE
    # the communication when the expert axis is sharded (EP). "sorted":
    # token-sorted grouped matmuls via jax.lax.ragged_dot — O(k*G*d)
    # gather/scatter instead of O(G*E*C*d) one-hot FLOPs (measured 5x
    # the expert compute at bench scale, docs/PERF.md) — for
    # single-device or data-sharded training where experts stay whole.
    moe_dispatch: str = "einsum"

    def n_params(self, include_embed: bool = True) -> int:
        d, l = self.d_model, self.n_layers
        attn = l * (
            d * self.n_heads * self.head_dim
            + 2 * d * self.n_kv_heads * self.head_dim
            + self.n_heads * self.head_dim * d
        )
        moe = l * (3 * d * self.d_ff * self.n_experts + d * self.n_experts)
        norms = (2 * l + 1) * d
        total = attn + moe + norms
        if include_embed:
            total += self.vocab_size * d
            if not self.tie_embeddings:
                total += d * self.vocab_size
        return total

    def flops_per_token(self, seq_len: int) -> float:
        """Active-parameter FLOPs: only k experts run per token."""
        d, l, k = self.d_model, self.n_layers, self.experts_per_token
        n_active = (
            l
            * (
                d * self.n_heads * self.head_dim
                + 2 * d * self.n_kv_heads * self.head_dim
                + self.n_heads * self.head_dim * d
                + 3 * d * self.d_ff * k
                + d * self.n_experts
            )
            + d * self.vocab_size
        )
        return 6.0 * n_active + self._attn_score_flops(seq_len)


MIXTRAL_CONFIGS: dict[str, MixtralConfig] = {
    "mixtral_8x7b": MixtralConfig(
        vocab_size=32_000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        head_dim=128,
        d_ff=14_336,
        rope_theta=1e6,
        max_seq_len=32_768,
        n_experts=8,
        experts_per_token=2,
        attention_backend="flash",
    ),
    "mixtral_tiny": MixtralConfig(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=16,
        d_ff=128,
        max_seq_len=128,
        n_experts=4,
        experts_per_token=2,
        remat=False,
    ),
}


class QuantExpertKernel(nn.Module):
    """int8 expert-stacked kernel [E, in, out] + per-(expert,
    out-channel) fp32 scale — the MoE serving twin of
    ``llama.QuantDenseGeneral``. Param shapes match what
    ``tpufw.ops.quant.quantize_params`` emits for the raw expert
    stacks; logical axes mirror the fp weights so sharded serving lays
    out identically (expert axis stays on the ``expert`` mesh axis)."""

    shape: tuple  # (E, d_in, d_out)
    names: tuple  # logical axes of the fp kernel
    dtype: Any

    @nn.compact
    def __call__(self, xe: jax.Array) -> jax.Array:
        e, _, d_out = self.shape
        q = self.param(
            "q_kernel",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), self.names
            ),
            self.shape,
            jnp.int8,
        )
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(
                nn.initializers.ones_init(), (self.names[0], self.names[2])
            ),
            (e, d_out),
            jnp.float32,
        )
        y = jnp.einsum("eci,eio->eco", xe, q.astype(self.dtype))
        return y * scale[:, None, :].astype(y.dtype)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts with capacity-bounded einsum dispatch.

    Returns (y, aux_loss): aux = load-balance loss (Switch-style fraction *
    probability product) + router z-loss, pre-weighted by the config.

    ``d_ff`` overrides the per-expert width (DeepSeek's fine-grained
    experts are narrower than the dense cfg.d_ff); ``norm_topk=False``
    keeps raw softmax combine weights (DeepSeek-V2). The ``cfg`` only
    needs the MoE fields (n_experts, experts_per_token,
    capacity_factor, router_*_weight) plus dtypes — DeepseekConfig
    passes a compatible view.
    """

    cfg: MixtralConfig
    d_ff: Optional[int] = None
    norm_topk: bool = True
    # (n_group, topk_group): DeepSeek-236B group-limited selection —
    # passed straight to tpufw.ops.moe.route_topk_capacity.
    group_limit: Optional[tuple] = None

    def _expert_matmul(
        self, name: str, xe: jax.Array, shape: tuple, names: tuple
    ) -> jax.Array:
        """One expert-stacked contraction [E,C,in] @ [E,in,out] ->
        [E,C,out], through whichever weight form the config declares:

        - fp kernel (training default), with optional per-expert LoRA
          (``cfg.lora_rank``): A [E,in,r] fan-in init, B [E,r,out] zero
          init — step 0 equals the base model, exactly like the shared
          ``lora_delta`` on attention projections. Params land as
          ``{name}_lora_a/b`` RAW-array siblings of the base stack
          (models/lora.py merges both layouts).
        - int8 + per-(expert, out-channel) scale for serving
          (``cfg.quantized_weights``; shapes match ``quantize_params``).
        """
        cfg = self.cfg
        if getattr(cfg, "quantized_weights", False):
            reject_quant_lora(cfg)
            sub = QuantExpertKernel(
                shape=shape, names=names, dtype=cfg.dtype, name=name
            )
            return sub(xe)
        w, a, bw = self._expert_weights(name, shape, names)
        y = jnp.einsum("eci,eio->eco", xe, w.astype(cfg.dtype))
        if a is not None:
            lo = jnp.einsum("eci,eir->ecr", xe, a.astype(cfg.dtype))
            y = y + jnp.einsum(
                "ecr,ero->eco", lo, bw.astype(cfg.dtype)
            ) * (
                getattr(cfg, "lora_alpha", 16.0)
                / getattr(cfg, "lora_rank", 0)
            )
        return y

    def _expert_weights(self, name: str, shape: tuple, names: tuple):
        """The fp expert weight stack (+ optional LoRA pair) — ONE
        param-creation site shared by the einsum and sorted dispatch
        paths, so both produce identical checkpoints."""
        cfg = self.cfg
        e, d_in, d_out = shape
        w = self.param(
            name,
            nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), names
            ),
            shape,
            cfg.param_dtype,
        )
        a = bw = None
        r = getattr(cfg, "lora_rank", 0)
        if r:
            a = self.param(
                f"{name}_lora_a",
                nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(),
                    (names[0], names[1], "lora"),
                ),
                (e, d_in, r),
                cfg.param_dtype,
            )
            bw = self.param(
                f"{name}_lora_b",
                nn.with_logical_partitioning(
                    nn.initializers.zeros_init(),
                    (names[0], "lora", names[2]),
                ),
                (e, r, d_out),
                cfg.param_dtype,
            )
        return w, a, bw

    def _sorted_experts(self, x, router_logits, capacity, valid, d_ff):
        """Sorted-dispatch expert compute: gather tokens into expert
        order and run grouped matmuls (``jax.lax.ragged_dot``) instead
        of contracting one-hot [G, E, C] dispatch tensors. The one-hot
        einsums cost O(G*E*C*d) FLOPs — measured 5x the expert matmuls
        themselves at bench scale, capping MoE training at 10% MFU on
        the v5e chip (docs/PERF.md) — while this path's gather/scatter
        moves O(k*G*d) bytes. Semantics (selection, capacity drops,
        aux losses) are pinned identical to the einsum path by
        ``tests/test_moe_sorted.py``.

        Single-device / data-sharded only: the expert weight stacks
        stay whole. Sharding the ``expert`` mesh axis needs the einsum
        path, whose dispatch tensors ARE the all-to-all (module doc of
        tpufw.ops.moe)."""
        cfg = self.cfg
        b, t, d = x.shape
        e, k = cfg.n_experts, cfg.experts_per_token
        g = b * t
        token, group_sizes, gates, aux, z = route_topk_sorted(
            router_logits, k, capacity,
            valid=None if valid is None else valid.reshape(g),
            dtype=x.dtype,
            norm_topk=self.norm_topk,
            group_limit=self.group_limit,
        )
        xs = x.reshape(g, d).astype(cfg.dtype)[token]  # [k*G, d]

        def pad(stack):
            # Sentinel group E (invalid-token assignments) multiplies
            # against one zero expert; ragged_dot needs sum(group
            # sizes) == rows, so the group must exist.
            return jnp.concatenate(
                [
                    stack.astype(cfg.dtype),
                    jnp.zeros((1, *stack.shape[1:]), cfg.dtype),
                ]
            )

        def grouped(name, shape, names, inp):
            w, a, bw = self._expert_weights(name, shape, names)
            y = jax.lax.ragged_dot(inp, pad(w), group_sizes)
            if a is not None:
                lo = jax.lax.ragged_dot(inp, pad(a), group_sizes)
                y = y + jax.lax.ragged_dot(
                    lo, pad(bw), group_sizes
                ) * (
                    getattr(cfg, "lora_alpha", 16.0)
                    / getattr(cfg, "lora_rank", 0)
                )
            return y

        gate_out = grouped(
            "w_gate", (e, d, d_ff),
            ("expert", "embed", "expert_mlp"), xs,
        )
        up_out = grouped(
            "w_up", (e, d, d_ff),
            ("expert", "embed", "expert_mlp"), xs,
        )
        h = nn.silu(gate_out) * up_out
        ys = grouped(
            "w_down", (e, d_ff, d),
            ("expert", "expert_mlp", "embed"), h,
        )
        yw = ys * gates[:, None].astype(cfg.dtype)
        y = (
            jnp.zeros((g, d), cfg.dtype).at[token].add(yw)
        ).reshape(b, t, d)
        return y, aux, z

    @nn.compact
    def __call__(self, x, valid=None):
        """x: [B,T,d]; valid: optional [B,T] bool — False rows (padding in
        packed batches) are excluded from routing, capacity, and the aux
        statistics so pads can't evict real tokens from experts."""
        cfg = self.cfg
        d_ff = self.d_ff if self.d_ff is not None else cfg.d_ff
        b, t, d = x.shape
        e, k = cfg.n_experts, cfg.experts_per_token
        g = b * t
        capacity = expert_capacity(g, k, e, cfg.capacity_factor)

        router_logits = nn.DenseGeneral(
            features=e,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "expert")
            ),
            name="router",
        )(x.astype(jnp.float32))
        router_logits = router_logits.reshape(g, e)

        mode = getattr(cfg, "moe_dispatch", "einsum")
        if mode == "sorted" and getattr(cfg, "quantized_weights", False):
            # int8 expert stacks are einsum-shaped (QuantExpertKernel);
            # serving keeps the einsum path.
            mode = "einsum"
        if mode == "sorted":
            y, aux, z = self._sorted_experts(
                x, router_logits, capacity, valid, d_ff
            )
            return y, (
                cfg.router_aux_weight * aux + cfg.router_z_weight * z
            )
        if mode != "einsum":
            raise ValueError(
                f"moe_dispatch={mode!r}: choose 'einsum' (shardable "
                "over the expert axis) or 'sorted' (grouped "
                "ragged_dot, single-device/data-sharded)"
            )

        dispatch, combine, aux, z = route_topk_capacity(
            router_logits, k, capacity,
            valid=None if valid is None else valid.reshape(g),
            dtype=x.dtype,
            norm_topk=self.norm_topk,
            group_limit=self.group_limit,
        )

        xf = x.reshape(g, d)
        xe = jnp.einsum("gec,gd->ecd", dispatch, xf)  # [E, C, d]
        xe = nn.with_logical_constraint(xe, ("expert", None, "act_embed"))
        xe = xe.astype(cfg.dtype)

        gate_out = self._expert_matmul(
            "w_gate", xe, (e, d, d_ff),
            ("expert", "embed", "expert_mlp"),
        )
        up_out = self._expert_matmul(
            "w_up", xe, (e, d, d_ff),
            ("expert", "embed", "expert_mlp"),
        )
        h = nn.silu(gate_out) * up_out
        h = nn.with_logical_constraint(h, ("expert", None, "act_mlp"))
        out_e = self._expert_matmul(
            "w_down", h, (e, d_ff, d),
            ("expert", "expert_mlp", "embed"),
        )
        y = jnp.einsum("gec,ecd->gd", combine, out_e).reshape(b, t, d)

        aux_loss = (
            cfg.router_aux_weight * aux + cfg.router_z_weight * z
        )
        return y, aux_loss


class MixtralBlock(nn.Module):
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        attn_out = Attention(
            cfg, window=getattr(cfg, "sliding_window", None), name="attn"
        )(
            RMSNorm(cfg.rms_eps, name="attn_norm")(x), positions, segment_ids
        )
        # Tag for remat_policy="attn_out" (no-op under other policies).
        x = x + ad_checkpoint.checkpoint_name(attn_out, "attn_out")
        y, aux = MoEMLP(cfg, name="moe")(
            RMSNorm(cfg.rms_eps, name="moe_norm")(x),
            valid=None if segment_ids is None else segment_ids > 0,
        )
        x = nn.with_logical_constraint(
            x + y, ("batch", "act_seq", "act_embed")
        )
        return x, aux


class Mixtral(nn.Module):
    """Decoder-only MoE LM. Returns (logits, aux_loss) when return_aux else
    logits — train_step adds aux_loss into the objective."""

    cfg: MixtralConfig

    @nn.compact
    def __call__(
        self, tokens, positions=None, segment_ids=None, return_aux=True,
        return_hidden=False,
    ):
        cfg = self.cfg
        logits, aux = decoder_lm(
            cfg, MixtralBlock, tokens, positions, segment_ids, True,
            return_hidden=return_hidden,
        )
        if return_aux:
            return logits, aux / cfg.n_layers
        return logits
