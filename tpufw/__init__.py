"""tpufw — a TPU-native cluster-enablement + JAX training framework.

Capability-parity build for ``mysticrenji/kubernetes-with-nvidia-gpu``
(see SURVEY.md): the reference is a layered, health-gated recipe that takes a
bare machine to a Kubernetes cluster where one ``kubectl apply`` runs an
accelerator workload with log-visible proof (reference ``README.md:303-335``).
This package is the TPU-side half of that capability: the JAX/XLA workloads
(BASELINE configs 1-5), the device-mesh parallelism layer that replaces
NCCL-env wiring, and the multi-host bootstrap that replaces single-node
assumptions. The cluster-side half (C++ device plugin, Helm chart, recipe,
verify gates) lives in ``deviceplugin/``, ``deploy/``, ``recipe/``,
``verify/`` at the repo root.

Subpackages
-----------
- ``mesh``     — device mesh construction, named axes, logical sharding rules
- ``models``   — Flax model families: Llama-3, Mixtral (MoE), ResNet-50
- ``ops``      — Pallas TPU kernels (flash attention, fused norms) + fallbacks
- ``parallel`` — sequence/context parallelism (ring attention), shard_map utils
- ``train``    — train loop, train state, metrics (tokens/sec/chip, MFU), ckpt
- ``cluster``  — jax.distributed bootstrap from JobSet/GKE pod environment
- ``configs``  — dataclass configs + the YAML-of-record per BASELINE config
- ``utils``    — hardware specs (peak FLOPs/HBM per chip), logging, trees
"""

__version__ = "0.1.0"
