"""CLI: held-out perplexity of a checkpoint over a packed corpus.

Closes the evaluate-a-checkpoint workflow without a training run::

    python -m tpufw.tools.eval_ppl --model llama3_8b \\
        --params base/ --data corpus \\
        --batch-size 8 --seq-len 2048 --batches 64

(``--params``: bare params from import_hf/merge_lora; ``--data``: a
pack_corpus .bin/.idx prefix.)

``--checkpoint`` instead of ``--params`` evaluates a training
TrainState dir (latest step). Prints ONE JSON line with the same
token-weighted numbers the trainers report in-loop (shared
``run_evaluation`` loop — the objective cannot drift from training).
"""

from __future__ import annotations

import json


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpufw.tools.eval_ppl",
        description="checkpoint + packed corpus -> token-weighted ppl",
    )
    ap.add_argument("--model", required=True,
                    help="model preset or run-config YAML path")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--params", help="bare-params Orbax dir")
    src.add_argument("--checkpoint",
                     help="training checkpoint dir (latest TrainState)")
    ap.add_argument("--data", required=True,
                    help="pack_corpus output prefix (.bin/.idx)")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--batches", type=int, default=64,
                    help="number of eval batches (0 = whole corpus)")
    ap.add_argument("--loss-chunk-size", type=int, default=512,
                    help="chunked-vocab CE chunk (0 = full logits)")
    args = ap.parse_args(argv)

    from tpufw.utils.profiling import enable_compile_cache

    enable_compile_cache()

    if args.model.endswith((".yaml", ".yml")):
        from tpufw.configs.loader import load_run_config

        model_cfg = load_run_config(args.model).model_cfg
    else:
        from tpufw.configs.loader import resolve_model_preset

        model_cfg = resolve_model_preset(args.model)

    from tpufw.models import model_for_config

    model = model_for_config(model_cfg)  # loud on non-LM configs

    import optax

    from tpufw.train import TokenCorpus, Trainer, TrainerConfig

    trainer = Trainer(
        model,
        TrainerConfig(
            batch_size=args.batch_size,
            seq_len=args.seq_len,
            loss_chunk_size=args.loss_chunk_size or None,
            checkpoint_dir=args.checkpoint,
            handle_preemption=False,  # no step loop to stop
        ),
        # --params: stateless optimizer, so forward-only evaluation
        # never allocates AdamW moments (~2x params of dead fp32 at
        # 8B). --checkpoint must keep the default tx: maybe_restore's
        # abstract tree must match the SAVED TrainState (which carries
        # the moments).
        tx=optax.identity() if args.params else None,
    )
    if args.params:
        trainer.init_from_params(args.params)
    else:
        if not trainer.maybe_restore():
            raise SystemExit(
                f"no checkpoint found under {args.checkpoint!r}"
            )

    data = iter(
        TokenCorpus(
            args.data, args.batch_size, args.seq_len,
            shuffle=False, epochs=1,
        )
    )
    result = trainer.evaluate(data, args.batches or None)
    result["model_params"] = model_cfg.n_params()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
