"""Import HuggingFace Llama checkpoints into tpufw parameter trees.

Interoperability path: users coming from the torch/HF ecosystem load
their existing Llama weights (e.g. Meta-Llama-3-8B) straight into the
tpufw trainer/server. The reference has no model layer to import into
(its workload is ``nvidia-smi``, reference README.md:314); this is part
of the additive ML stack.

The mapping is purely structural (no numerics): HF ``nn.Linear`` stores
``weight`` as [out, in] while flax DenseGeneral kernels are [in, ...out],
so projections transpose; per-layer tensors stack onto the leading
``layers`` axis of the ``nn.scan`` trunk. RoPE conventions already agree
(HF's rotate_half == tpufw.models.llama.apply_rope half-split), which is
what makes logits-level parity possible — pinned by
tests/test_import_hf.py against a real ``transformers`` forward.

Works from an in-memory HF model / state_dict (tests) or a checkpoint
directory with ``*.safetensors`` (production).
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Mapping

import numpy as np

from tpufw.models.llama import LlamaConfig


def _to_np(t: Any) -> np.ndarray:
    """torch.Tensor / np.ndarray -> float32 numpy (bf16-safe)."""
    if isinstance(t, np.ndarray):
        return t.astype(np.float32)
    # torch tensor (possibly bf16, which numpy can't represent directly).
    return t.detach().to("cpu").float().numpy()


def _rope_scaling_from_hf(rs: Any):
    """HF ``rope_scaling`` dict -> tpufw RopeScaling (or None).

    ``rope_type == "llama3"`` (Llama-3.1/3.3) and ``"linear"``
    (position interpolation, common on long-context Llama-2 fine-tunes)
    import directly. Rejected loudly: "dynamic" (NTK-aware scaling is a
    function of the RUNTIME sequence length, so the frequencies change
    per call — tpufw's static-shape decode caches bake frequencies at
    trace time) and "longrope" (per-dimension learned scaling vectors
    with a short/long context switch; not implemented). A
    silently-dropped transform would import a model whose logits drift
    with position."""
    if not rs:
        return None
    from tpufw.models.llama import RopeScaling

    get = rs.get if isinstance(rs, Mapping) else lambda k, d=None: getattr(
        rs, k, d
    )
    # transformers renamed "type" -> "rope_type"; accept both.
    rtype = get("rope_type") or get("type")
    if rtype == "linear":
        return RopeScaling(
            factor=float(get("factor")), rope_type="linear"
        )
    if rtype != "llama3":
        raise NotImplementedError(
            f"rope_scaling rope_type={rtype!r} is not implemented "
            "('llama3' and 'linear' are; 'dynamic' scales with runtime "
            "sequence length, 'longrope' needs learned per-dim "
            "vectors); importing would silently change rotary "
            "frequencies"
        )
    return RopeScaling(
        factor=float(get("factor")),
        low_freq_factor=float(get("low_freq_factor")),
        high_freq_factor=float(get("high_freq_factor")),
        original_max_position_embeddings=int(
            get("original_max_position_embeddings")
        ),
    )


def config_from_hf(hf_config: Any) -> LlamaConfig:
    """tpufw config from a transformers Llama/Mixtral config (object or
    dict). ``model_type == "mixtral"`` yields a MixtralConfig."""
    get = (
        hf_config.get
        if isinstance(hf_config, Mapping)
        else lambda k, d=None: getattr(hf_config, k, d)
    )
    if get("model_type") == "gemma2":
        return _gemma_config_from_hf(get)
    if get("model_type") == "deepseek_v2":
        return _deepseek_config_from_hf(get)
    is_qwen2 = get("model_type") == "qwen2"
    is_mistral = get("model_type") == "mistral"
    is_mixtral = get("model_type") == "mixtral"
    if is_qwen2 and get("use_sliding_window"):
        raise NotImplementedError(
            "Qwen2 import: use_sliding_window=True (layer-windowed "
            "attention) is not implemented"
        )
    # Reject, loudly, configs whose architecture tpufw doesn't implement —
    # importing them would produce silently wrong logits.
    unsupported = {
        # Qwen2 carries qkv biases by construction; Llama-family configs
        # with attention_bias remain rejected (their bias is on ALL four
        # projections, which the blocks don't implement).
        "attention_bias": lambda v: bool(v) and not is_qwen2,
        "mlp_bias": bool,
        "hidden_act": lambda v: v not in (None, "silu"),
        "sliding_window": lambda v: bool(v)
        and not (is_qwen2 or is_mistral or is_mixtral),
    }
    bad = {
        k: get(k) for k, is_bad in unsupported.items() if is_bad(get(k))
    }
    if bad:
        raise NotImplementedError(
            f"HF config uses features tpufw's Llama/Mixtral don't "
            f"implement: {bad}; importing would silently change the "
            "model's math"
        )
    d_model = get("hidden_size")
    n_heads = get("num_attention_heads")
    common = dict(
        rope_scaling=_rope_scaling_from_hf(get("rope_scaling")),
        vocab_size=get("vocab_size"),
        d_model=d_model,
        n_layers=get("num_hidden_layers"),
        n_heads=n_heads,
        n_kv_heads=get("num_key_value_heads") or n_heads,
        head_dim=get("head_dim") or d_model // n_heads,
        d_ff=get("intermediate_size"),
        rope_theta=float(get("rope_theta") or 10_000.0),
        rms_eps=float(get("rms_norm_eps") or 1e-5),
        max_seq_len=get("max_position_embeddings") or 8192,
        tie_embeddings=bool(get("tie_word_embeddings") or False),
        attention_qkv_bias=bool(is_qwen2),
        # Mistral/Mixtral: one window on every layer (None when the
        # checkpoint disabled it, as Mistral v0.2+ and Mixtral do).
        sliding_window=(
            get("sliding_window")
            if (is_mistral or is_mixtral)
            else None
        ),
    )
    if is_mixtral:
        from tpufw.models.mixtral import MixtralConfig

        return MixtralConfig(
            **common,
            n_experts=get("num_local_experts"),
            experts_per_token=get("num_experts_per_tok"),
            # HF Mixtral routes dropless (dense top-k gather); default
            # imported checkpoints to a capacity that can't drop tokens
            # so served outputs match the checkpoint's semantics. Users
            # fine-tuning at scale can lower this explicitly.
            capacity_factor=float(get("num_local_experts")),
        )
    return LlamaConfig(**common)


def _deepseek_config_from_hf(get):
    """tpufw DeepseekConfig from a transformers DeepseekV2Config.

    Routed experts (DeepSeek MoE FFN), group-limited selection
    (topk_method="group_limited_greedy", n_group/topk_group), and yarn
    rope scaling import directly. Rejects, loudly, what tpufw's MLA
    blocks don't implement: other topk_methods, non-softmax scoring,
    sparse moe_layer_freq, and attention bias — importing them would
    produce silently wrong logits."""
    from tpufw.models.deepseek import DeepseekConfig

    bad = {}
    n_layers = get("num_hidden_layers")
    # Layers >= first_k_dense_replace use the MoE FFN
    # (modeling_deepseek_v2.py DeepseekV2DecoderLayer); all-dense
    # checkpoints set it past the last layer.
    first_moe = get("first_k_dense_replace") or 0
    has_moe = bool(get("n_routed_experts")) and first_moe < n_layers
    group_kwargs = {}
    if has_moe:
        # V2-Lite routes plain greedy-softmax; the 236B/Chat models'
        # group-limited selection imports via n_group/topk_group.
        topk_method = get("topk_method") or "greedy"
        if topk_method == "group_limited_greedy":
            # Validate at the IMPORT boundary like every other gap —
            # a malformed group spec must not surface as a ValueError
            # deep inside the first jit trace.
            ng, tg = get("n_group"), get("topk_group")
            e, k = get("n_routed_experts"), get("num_experts_per_tok")
            ok = (
                ng and tg and e % ng == 0
                and (tg >= ng or k <= tg * (e // ng))
            )
            if ok:
                group_kwargs = dict(n_group=int(ng), topk_group=int(tg))
            else:
                bad["group_limited_greedy"] = {
                    "n_group": ng,
                    "topk_group": tg,
                    "n_routed_experts": e,
                    "num_experts_per_tok": k,
                }
        elif topk_method != "greedy":
            bad["topk_method"] = topk_method
        if (get("scoring_func") or "softmax") != "softmax":
            bad["scoring_func"] = get("scoring_func")
        if (get("moe_layer_freq") or 1) != 1:
            bad["moe_layer_freq"] = get("moe_layer_freq")
    yarn = None
    rs = get("rope_scaling")
    if rs:
        rs_get = rs.get if isinstance(rs, Mapping) else (
            lambda k, d=None: getattr(rs, k, d)
        )
        rtype = rs_get("rope_type") or rs_get("type")
        if rtype != "yarn":
            bad["rope_scaling"] = rs
        else:
            from tpufw.models.deepseek import YarnScaling

            yarn = YarnScaling(
                factor=float(rs_get("factor")),
                original_max_position_embeddings=int(
                    rs_get("original_max_position_embeddings")
                    or get("max_position_embeddings")
                    or 4096
                ),
                beta_fast=float(rs_get("beta_fast") or 32),
                beta_slow=float(rs_get("beta_slow") or 1),
                # Unset stays FALSY: the reference's attention-factor
                # derivation gates on `mscale and mscale_all_dim` — a
                # 1.0 default would flip a mscale_all_dim-only config
                # into the ratio branch (wrong factor).
                mscale=float(rs_get("mscale") or 0.0),
                mscale_all_dim=float(rs_get("mscale_all_dim") or 0.0),
                attention_factor=rs_get("attention_factor"),
                truncate=bool(
                    True if rs_get("truncate") is None
                    else rs_get("truncate")
                ),
            )
    if get("attention_bias"):
        bad["attention_bias"] = get("attention_bias")
    if get("hidden_act") not in (None, "silu"):
        bad["hidden_act"] = get("hidden_act")
    if bad:
        raise NotImplementedError(
            f"DeepseekV2 import: unsupported features {bad}; tpufw's "
            "MLA family implements greedy and group-limited-greedy "
            "softmax MoE and default+yarn rope (non-softmax scoring, "
            "sparse moe_layer_freq, and attention bias are the known "
            "gaps)"
        )
    moe_kwargs = {}
    if has_moe:
        moe_kwargs = dict(
            n_routed_experts=get("n_routed_experts"),
            experts_per_token=get("num_experts_per_tok"),
            moe_d_ff=get("moe_intermediate_size"),
            n_shared_experts=get("n_shared_experts") or 0,
            first_k_dense=first_moe,
            routed_scaling_factor=float(
                get("routed_scaling_factor") or 1.0
            ),
            # The HF reference STORES norm_topk_prob but never applies
            # it (modeling_deepseek_v2.py MoEGate.forward returns raw
            # softmax topk mass * scaling, no renormalization branch) —
            # parity means matching the executed behavior, not the
            # config flag.
            norm_topk_prob=False,
            # Dropless: HF routes without capacity bounds, so imported
            # checkpoints must not drop tokens (Mixtral convention).
            capacity_factor=float(get("n_routed_experts")),
            # Mixed dense/MoE stacks can't scan (homogeneity).
            scan_layers=first_moe == 0,
            **group_kwargs,
        )
    return DeepseekConfig(
        vocab_size=get("vocab_size"),
        d_model=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        q_lora_rank=get("q_lora_rank"),
        kv_lora_rank=get("kv_lora_rank"),
        qk_nope_head_dim=get("qk_nope_head_dim"),
        qk_rope_head_dim=get("qk_rope_head_dim"),
        v_head_dim=get("v_head_dim"),
        d_ff=get("intermediate_size"),
        rope_theta=float(get("rope_theta") or 10_000.0),
        rms_eps=float(get("rms_norm_eps") or 1e-6),
        max_seq_len=get("max_position_embeddings") or 4096,
        tie_embeddings=bool(get("tie_word_embeddings") or False),
        rope_scaling=yarn,
        **moe_kwargs,
    )


def _deepseek_from_hf(sd, cfg, dt) -> dict:
    """HF DeepseekV2 state dict -> tpufw Deepseek param tree.

    MLA projections (modeling_deepseek_v2.py DeepseekV2Attention):
    kv_a_proj_with_mqa packs [kv_lora_rank + qk_rope_head_dim, D];
    kv_b_proj packs [H * (qk_nope_head_dim + v_head_dim), kv_lora_rank].
    The rope slices need NO permutation — DeepSeek's rotary is the
    interleaved complex layout, which apply_rope_interleaved matches.
    """
    import jax
    import jax.numpy as jnp

    d, h = cfg.d_model, cfg.n_heads
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim

    def take(key: str, target=None):
        if key not in sd:
            raise KeyError(
                f"HF checkpoint is missing {key!r}; not a DeepseekV2 "
                "state dict?"
            )
        return jnp.asarray(_to_np(sd[key]), target or dt)

    def block(i: int) -> dict:
        pre = f"layers.{i}."
        ap = pre + "self_attn."
        attn: dict = {
            "kv_a": {
                "kernel": take(ap + "kv_a_proj_with_mqa.weight").T
            },
            "kv_a_norm": {
                "scale": take(ap + "kv_a_layernorm.weight", jnp.float32)
            },
            "kv_b_kernel": take(ap + "kv_b_proj.weight")
            .T.reshape(cfg.kv_lora_rank, h, dn + dv),
            "o": {
                "kernel": take(ap + "o_proj.weight").T.reshape(h, dv, d)
            },
        }
        if cfg.q_lora_rank is None:
            attn["q"] = {
                "kernel": take(ap + "q_proj.weight")
                .T.reshape(d, h, dn + dr)
            }
        else:
            attn["q_a"] = {"kernel": take(ap + "q_a_proj.weight").T}
            attn["q_a_norm"] = {
                "scale": take(ap + "q_a_layernorm.weight", jnp.float32)
            }
            attn["q_b"] = {
                "kernel": take(ap + "q_b_proj.weight")
                .T.reshape(cfg.q_lora_rank, h, dn + dr)
            }
        out = {
            "attn_norm": {
                "scale": take(pre + "input_layernorm.weight", jnp.float32)
            },
            "attn": attn,
            "mlp_norm": {
                "scale": take(
                    pre + "post_attention_layernorm.weight", jnp.float32
                )
            },
        }
        if cfg.moe and i >= cfg.first_k_dense:
            mp = pre + "mlp."

            def experts(w: str):
                return jnp.stack(
                    [
                        take(f"{mp}experts.{e}.{w}_proj.weight").T
                        for e in range(cfg.n_routed_experts)
                    ],
                    axis=0,
                )

            moe = {
                "routed": {
                    "router": {"kernel": take(mp + "gate.weight").T},
                    "w_gate": experts("gate"),  # [E, D, F]
                    "w_up": experts("up"),
                    "w_down": experts("down"),  # [E, F, D]
                },
            }
            if cfg.n_shared_experts:
                moe["shared"] = {
                    "gate": {
                        "kernel": take(
                            mp + "shared_experts.gate_proj.weight"
                        ).T
                    },
                    "up": {
                        "kernel": take(
                            mp + "shared_experts.up_proj.weight"
                        ).T
                    },
                    "down": {
                        "kernel": take(
                            mp + "shared_experts.down_proj.weight"
                        ).T
                    },
                }
            out["moe"] = moe
        else:
            out["mlp"] = {
                "gate": {"kernel": take(pre + "mlp.gate_proj.weight").T},
                "up": {"kernel": take(pre + "mlp.up_proj.weight").T},
                "down": {"kernel": take(pre + "mlp.down_proj.weight").T},
            }
        return out

    layers = [block(i) for i in range(cfg.n_layers)]
    params: dict = {
        "embed": {"embedding": take("embed_tokens.weight")},
        "final_norm": {"scale": take("norm.weight", jnp.float32)},
    }
    if cfg.scan_layers:
        params["layers"] = jax.tree.map(
            lambda *xs: jnp.stack(xs, axis=0), *layers
        )
    else:
        for i, lp in enumerate(layers):
            params[f"layer_{i}"] = lp
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": take("lm_head.weight").T}
    return params


def _gemma_config_from_hf(get) -> "GemmaConfig":
    """tpufw GemmaConfig from a transformers Gemma2Config.

    Rejects non-Gemma-2 feature combos loudly (same policy as the
    Llama path): tpufw implements exactly HF Gemma2's architecture —
    gelu_pytorch_tanh GeGLU, sandwich norms, alternating sliding
    window on even layers, logit soft-caps, tied embeddings.
    """
    from tpufw.models.gemma import GemmaConfig

    act = get("hidden_activation") or get("hidden_act")
    if act not in (None, "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"Gemma2 import supports gelu_pytorch_tanh only, got {act!r}"
        )
    if bool(get("attention_bias")):
        # Same reject-loudly policy as the Llama path: the weight mapper
        # reads only the keys it knows, so bias tensors would be DROPPED
        # silently — wrong logits, not an error.
        raise NotImplementedError(
            "Gemma2 import does not implement attention_bias=True"
        )
    if not (get("tie_word_embeddings") is None or
            bool(get("tie_word_embeddings"))):
        raise NotImplementedError(
            "Gemma2 import assumes tied embeddings (all released "
            "Gemma-2 checkpoints tie them)"
        )
    d_model = get("hidden_size")
    n_heads = get("num_attention_heads")
    return GemmaConfig(
        vocab_size=get("vocab_size"),
        d_model=d_model,
        n_layers=get("num_hidden_layers"),
        n_heads=n_heads,
        n_kv_heads=get("num_key_value_heads") or n_heads,
        head_dim=get("head_dim") or d_model // n_heads,
        d_ff=get("intermediate_size"),
        rope_theta=float(get("rope_theta") or 10_000.0),
        rms_eps=float(get("rms_norm_eps") or 1e-6),
        max_seq_len=get("max_position_embeddings") or 8192,
        tie_embeddings=True,
        attn_logit_soft_cap=get("attn_logit_softcapping"),
        final_logit_soft_cap=get("final_logit_softcapping"),
        sliding_window=get("sliding_window"),
        query_pre_attn_scalar=float(
            get("query_pre_attn_scalar") or
            (get("head_dim") or d_model // n_heads)
        ),
    )


def _gemma_from_hf(sd, cfg, dt) -> dict:
    """HF Gemma2 state dict -> tpufw Gemma param tree (pairs layout).

    HF layer 2p (sliding) -> pair p "local"; layer 2p+1 -> "global".
    Norm weights copy directly: both sides store the offset-from-1.
    """
    import jax
    import jax.numpy as jnp

    d, h, kh, dh = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def take(key: str, target=None):
        if key not in sd:
            raise KeyError(
                f"HF checkpoint is missing {key!r}; not a Gemma-2 "
                "state dict?"
            )
        return jnp.asarray(_to_np(sd[key]), target or dt)

    def block(i: int) -> dict:
        pre = f"layers.{i}."
        return {
            "pre_attn_norm": {
                "scale": take(pre + "input_layernorm.weight", jnp.float32)
            },
            "post_attn_norm": {
                "scale": take(
                    pre + "post_attention_layernorm.weight", jnp.float32
                )
            },
            "pre_mlp_norm": {
                "scale": take(
                    pre + "pre_feedforward_layernorm.weight", jnp.float32
                )
            },
            "post_mlp_norm": {
                "scale": take(
                    pre + "post_feedforward_layernorm.weight", jnp.float32
                )
            },
            "attn": {
                "q": {
                    "kernel": take(pre + "self_attn.q_proj.weight")
                    .T.reshape(d, h, dh)
                },
                "k": {
                    "kernel": take(pre + "self_attn.k_proj.weight")
                    .T.reshape(d, kh, dh)
                },
                "v": {
                    "kernel": take(pre + "self_attn.v_proj.weight")
                    .T.reshape(d, kh, dh)
                },
                "o": {
                    "kernel": take(pre + "self_attn.o_proj.weight")
                    .T.reshape(h, dh, d)
                },
            },
            "mlp": {
                "gate": {"kernel": take(pre + "mlp.gate_proj.weight").T},
                "up": {"kernel": take(pre + "mlp.up_proj.weight").T},
                "down": {"kernel": take(pre + "mlp.down_proj.weight").T},
            },
        }

    pairs = [
        {"local": block(2 * p), "global": block(2 * p + 1)}
        for p in range(cfg.n_layers // 2)
    ]
    params: dict = {
        "embed": {"embedding": take("embed_tokens.weight")},
        "final_norm": {"scale": take("norm.weight", jnp.float32)},
    }
    if cfg.scan_layers:
        params["layers"] = jax.tree.map(
            lambda *xs: jnp.stack(xs, axis=0), *pairs
        )
    else:
        for i, lp in enumerate(pairs):
            params[f"layer_{i}"] = lp
    return params


def _load_state_dict(path: str | os.PathLike) -> dict[str, np.ndarray]:
    """Read every ``*.safetensors`` shard in a checkpoint directory."""
    from safetensors import safe_open

    path = pathlib.Path(path)
    shards = sorted(path.glob("*.safetensors"))
    if not shards:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    out: dict[str, np.ndarray] = {}
    for shard in shards:
        with safe_open(str(shard), framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def from_hf(
    source: Any,
    cfg: LlamaConfig,
    dtype: Any = None,
) -> dict:
    """Convert HF Llama/Mixtral weights to the tpufw param tree.

    ``source``: a transformers model (has ``.state_dict()``), a state
    dict, or a checkpoint directory path. ``dtype`` defaults to
    ``cfg.param_dtype``. Returns the raw (unboxed) param pytree the
    trainer/apply path consumes; layout matches ``cfg.scan_layers``.
    A MixtralConfig maps the block_sparse_moe experts (w1=gate, w3=up,
    w2=down, gate=router) onto the stacked [E, ...] expert weights.
    """
    import jax.numpy as jnp

    from tpufw.models.gemma import GemmaConfig
    from tpufw.models.mixtral import MixtralConfig

    is_moe = isinstance(cfg, MixtralConfig)

    if isinstance(source, (str, os.PathLike)):
        sd = _load_state_dict(source)
    elif hasattr(source, "state_dict"):
        sd = source.state_dict()
    else:
        sd = dict(source)
    sd = {k.removeprefix("model."): v for k, v in sd.items()}

    dt = jnp.dtype(dtype if dtype is not None else cfg.param_dtype)
    if isinstance(cfg, GemmaConfig):
        return _gemma_from_hf(sd, cfg, dt)
    from tpufw.models.deepseek import DeepseekConfig

    if isinstance(cfg, DeepseekConfig):
        return _deepseek_from_hf(sd, cfg, dt)
    d, h, kh, dh = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def take(key: str, target=None):
        """One tensor, cast straight to its final dtype — per-tensor
        conversion keeps the host-memory peak at ~one checkpoint copy
        (an 8B bf16 import must not balloon to 3x through fp32
        intermediates). Norm scales default to fp32 (RMSNorm convention).
        """
        if key not in sd:
            raise KeyError(
                f"HF checkpoint is missing {key!r} (have "
                f"{sorted(sd)[:8]}...); not a Llama-family state dict?"
            )
        return jnp.asarray(_to_np(sd[key]), target or dt)

    def layer(i: int) -> dict:
        pre = f"layers.{i}."
        out = {
            "attn_norm": {
                "scale": take(
                    pre + "input_layernorm.weight", jnp.float32
                )
            },
            "attn": {
                "q": {
                    "kernel": take(pre + "self_attn.q_proj.weight")
                    .T.reshape(d, h, dh)
                },
                "k": {
                    "kernel": take(pre + "self_attn.k_proj.weight")
                    .T.reshape(d, kh, dh)
                },
                "v": {
                    "kernel": take(pre + "self_attn.v_proj.weight")
                    .T.reshape(d, kh, dh)
                },
                "o": {
                    "kernel": take(pre + "self_attn.o_proj.weight")
                    .T.reshape(h, dh, d)
                },
            },
        }
        if getattr(cfg, "attention_qkv_bias", False):
            # Qwen2: biases on q/k/v only, stored flat [H*dh] in HF.
            attn_out = out["attn"]
            attn_out["q"]["bias"] = take(
                pre + "self_attn.q_proj.bias", jnp.float32
            ).reshape(h, dh)
            attn_out["k"]["bias"] = take(
                pre + "self_attn.k_proj.bias", jnp.float32
            ).reshape(kh, dh)
            attn_out["v"]["bias"] = take(
                pre + "self_attn.v_proj.bias", jnp.float32
            ).reshape(kh, dh)
        post_norm = take(
            pre + "post_attention_layernorm.weight", jnp.float32
        )
        if is_moe:
            moe_pre = pre + "block_sparse_moe."

            def experts(w: str) -> Any:
                return jnp.stack(
                    [
                        take(f"{moe_pre}experts.{e}.{w}.weight").T
                        for e in range(cfg.n_experts)
                    ],
                    axis=0,
                )

            out["moe_norm"] = {"scale": post_norm}
            out["moe"] = {
                "router": {"kernel": take(moe_pre + "gate.weight").T},
                "w_gate": experts("w1"),  # [E, D, F]
                "w_up": experts("w3"),
                "w_down": experts("w2"),  # [E, F, D]
            }
        else:
            out["mlp_norm"] = {"scale": post_norm}
            out["mlp"] = {
                "gate": {"kernel": take(pre + "mlp.gate_proj.weight").T},
                "up": {"kernel": take(pre + "mlp.up_proj.weight").T},
                "down": {"kernel": take(pre + "mlp.down_proj.weight").T},
            }
        return out

    layers = [layer(i) for i in range(cfg.n_layers)]
    params: dict = {
        "embed": {"embedding": take("embed_tokens.weight")},
        "final_norm": {"scale": take("norm.weight", jnp.float32)},
    }
    if cfg.scan_layers:
        import jax

        params["layers"] = jax.tree.map(
            lambda *xs: jnp.stack(xs, axis=0), *layers
        )
    else:
        for i, lp in enumerate(layers):
            params[f"layer_{i}"] = lp
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": take("lm_head.weight").T}
    return params


#: Back-compat alias (the function now also handles Mixtral).
from_hf_llama = from_hf


# ----------------------------------------------------------------------
# Export: tpufw params -> HF state dict / checkpoint dir
# ----------------------------------------------------------------------


def hf_config_dict(cfg: LlamaConfig) -> dict:
    """The transformers config.json contents for a tpufw config."""
    from tpufw.models.deepseek import DeepseekConfig
    from tpufw.models.mixtral import MixtralConfig

    if isinstance(cfg, DeepseekConfig):
        out = {
            "model_type": "deepseek_v2",
            "architectures": ["DeepseekV2ForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_heads,
            "q_lora_rank": cfg.q_lora_rank,
            "kv_lora_rank": cfg.kv_lora_rank,
            "qk_nope_head_dim": cfg.qk_nope_head_dim,
            "qk_rope_head_dim": cfg.qk_rope_head_dim,
            # transformers' rotary sizes itself from head_dim, which
            # for MLA is the ROPE slice.
            "head_dim": cfg.qk_rope_head_dim,
            "v_head_dim": cfg.v_head_dim,
            "intermediate_size": cfg.d_ff,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.rms_eps,
            "max_position_embeddings": cfg.max_seq_len,
            "tie_word_embeddings": cfg.tie_embeddings,
            "attention_bias": False,
            "hidden_act": "silu",
            "torch_dtype": "float32",
            # All layers below first_k_dense_replace are dense; a
            # dense-FFN export pushes it past the last layer (the
            # routed-expert fields then never construct).
            "first_k_dense_replace": (
                cfg.first_k_dense if cfg.moe else cfg.n_layers
            ),
        }
        if cfg.moe:
            out.update(
                n_routed_experts=cfg.n_routed_experts,
                num_experts_per_tok=cfg.experts_per_token,
                moe_intermediate_size=cfg.moe_d_ff,
                n_shared_experts=cfg.n_shared_experts or None,
                routed_scaling_factor=cfg.routed_scaling_factor,
                norm_topk_prob=False,
                scoring_func="softmax",
                moe_layer_freq=1,
                **(
                    {
                        "topk_method": "group_limited_greedy",
                        "n_group": cfg.n_group,
                        "topk_group": cfg.topk_group,
                    }
                    if cfg.n_group
                    else {"topk_method": "greedy"}
                ),
            )
        ys = getattr(cfg, "rope_scaling", None)
        if ys is not None:
            out["rope_scaling"] = {
                "rope_type": "yarn",
                "factor": ys.factor,
                "original_max_position_embeddings": (
                    ys.original_max_position_embeddings
                ),
                "beta_fast": ys.beta_fast,
                "beta_slow": ys.beta_slow,
                **(
                    {"mscale": ys.mscale} if ys.mscale else {}
                ),
                **(
                    {"mscale_all_dim": ys.mscale_all_dim}
                    if ys.mscale_all_dim
                    else {}
                ),
                # Both read back by _compute_yarn_parameters; dropping
                # them would silently change every cos/sin on reload.
                **(
                    {"attention_factor": ys.attention_factor}
                    if ys.attention_factor is not None
                    else {}
                ),
                **({} if ys.truncate else {"truncate": False}),
            }
        return out

    out = {
        "model_type": "llama",
        "architectures": ["LlamaForCausalLM"],
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.d_ff,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_eps,
        **(
            {
                "rope_scaling": (
                    {
                        "rope_type": "linear",
                        "factor": cfg.rope_scaling.factor,
                    }
                    if cfg.rope_scaling.rope_type == "linear"
                    else {
                        "rope_type": "llama3",
                        "factor": cfg.rope_scaling.factor,
                        "low_freq_factor": (
                            cfg.rope_scaling.low_freq_factor
                        ),
                        "high_freq_factor": (
                            cfg.rope_scaling.high_freq_factor
                        ),
                        "original_max_position_embeddings": (
                            cfg.rope_scaling
                            .original_max_position_embeddings
                        ),
                    }
                )
            }
            if getattr(cfg, "rope_scaling", None) is not None
            else {}
        ),
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": cfg.tie_embeddings,
        "attention_bias": False,
        "mlp_bias": False,
        "hidden_act": "silu",
        "torch_dtype": "float32",
    }
    from tpufw.models.gemma import GemmaConfig as _GemmaConfig

    if isinstance(cfg, MixtralConfig):
        out.update(
            model_type="mixtral",
            architectures=["MixtralForCausalLM"],
            num_local_experts=cfg.n_experts,
            num_experts_per_tok=cfg.experts_per_token,
        )
        if getattr(cfg, "sliding_window", None):
            # HF Mixtral carries the field too (it descends from
            # Mistral); the tpufw blocks honor it, so export must.
            out["sliding_window"] = cfg.sliding_window
        out.pop("mlp_bias")
    elif (
        getattr(cfg, "sliding_window", None)
        and not getattr(cfg, "attention_qkv_bias", False)
        and not isinstance(cfg, _GemmaConfig)
    ):
        out.update(
            model_type="mistral",
            architectures=["MistralForCausalLM"],
            sliding_window=cfg.sliding_window,
        )
        out.pop("mlp_bias", None)
    if getattr(cfg, "attention_qkv_bias", False):
        if getattr(cfg, "sliding_window", None):
            raise NotImplementedError(
                "export of qkv-bias + sliding_window is not implemented "
                "(the qwen2 branch would silently write "
                "use_sliding_window=False, changing the attention math)"
            )
        if isinstance(cfg, MixtralConfig):
            # Mixtral shares llama.Attention so the COMBINATION trains,
            # but no HF architecture expresses MoE + qkv-bias — export
            # would emit a nonsense config.
            raise NotImplementedError(
                "export of a Mixtral config with attention_qkv_bias is "
                "not representable as an HF architecture"
            )
        if cfg.head_dim != cfg.d_model // cfg.n_heads:
            # Qwen2Config has no head_dim field: transformers recomputes
            # it as hidden_size // num_attention_heads, so any other
            # value would export a checkpoint from_pretrained cannot
            # load (size mismatch at reload, long after this "success").
            raise NotImplementedError(
                f"Qwen2 export requires head_dim == d_model//n_heads "
                f"({cfg.d_model}//{cfg.n_heads}="
                f"{cfg.d_model // cfg.n_heads}), got {cfg.head_dim}"
            )
        out.update(
            model_type="qwen2",
            architectures=["Qwen2ForCausalLM"],
            use_sliding_window=False,
        )
        out.pop("attention_bias", None)
        out.pop("mlp_bias", None)
        out.pop("head_dim", None)
    from tpufw.models.gemma import GemmaConfig

    if isinstance(cfg, GemmaConfig):
        out.update(
            model_type="gemma2",
            architectures=["Gemma2ForCausalLM"],
            hidden_activation="gelu_pytorch_tanh",
            attn_logit_softcapping=cfg.attn_logit_soft_cap,
            final_logit_softcapping=cfg.final_logit_soft_cap,
            sliding_window=cfg.sliding_window,
            query_pre_attn_scalar=cfg.query_pre_attn_scalar,
            tie_word_embeddings=True,
        )
        out.pop("mlp_bias")
        out.pop("hidden_act")
    return out


def to_hf(params: dict, cfg: LlamaConfig) -> dict[str, np.ndarray]:
    """Inverse of ``from_hf``: tpufw param tree -> HF-keyed state dict
    (numpy fp32, HF [out, in] Linear layout, ``model.``-prefixed keys).
    Accepts both scan-stacked and per-layer trees."""
    from tpufw.models.deepseek import DeepseekConfig
    from tpufw.models.gemma import GemmaConfig
    from tpufw.models.lora import has_lora
    from tpufw.models.mixtral import MixtralConfig

    if isinstance(cfg, DeepseekConfig):
        return _deepseek_to_hf(params, cfg)
    if has_lora(params):
        # The emitters read only base kernels; exporting an un-merged
        # LoRA tree would silently ship the FROZEN base and drop the
        # entire fine-tune.
        raise ValueError(
            "to_hf/export_hf on a LoRA tree: run "
            "tpufw.tools.merge_lora first (adapters must fold into the "
            "kernels they modify)"
        )
    if isinstance(cfg, GemmaConfig):
        return _gemma_to_hf(params, cfg)
    is_moe = isinstance(cfg, MixtralConfig)
    d = cfg.d_model

    np32 = _np32

    def layer_tree(i: int) -> Mapping:
        return _slice_stack(params, cfg.scan_layers, i)

    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np32(params["embed"]["embedding"]),
        "model.norm.weight": np32(params["final_norm"]["scale"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np32(params["lm_head"]["kernel"]).T
    for i in range(cfg.n_layers):
        lp = layer_tree(i)
        pre = f"model.layers.{i}."
        sd[pre + "input_layernorm.weight"] = np32(
            lp["attn_norm"]["scale"]
        )
        _emit_attn(sd, pre, lp, d)
        norm_key = "moe_norm" if is_moe else "mlp_norm"
        sd[pre + "post_attention_layernorm.weight"] = np32(
            lp[norm_key]["scale"]
        )
        if is_moe:
            moe = lp["moe"]
            sd[pre + "block_sparse_moe.gate.weight"] = np32(
                moe["router"]["kernel"]
            ).T
            for e in range(cfg.n_experts):
                ep = pre + f"block_sparse_moe.experts.{e}."
                sd[ep + "w1.weight"] = np32(moe["w_gate"][e]).T
                sd[ep + "w3.weight"] = np32(moe["w_up"][e]).T
                sd[ep + "w2.weight"] = np32(moe["w_down"][e]).T
        else:
            _emit_mlp(sd, pre, lp)
    return sd


def _np32(x) -> np.ndarray:
    return np.asarray(x, np.float32)


def _slice_stack(params: dict, scan_layers: bool, i: int):
    """Layer/pair ``i`` of the (possibly scan-stacked) block params."""
    if scan_layers:
        import jax

        return jax.tree.map(lambda x: x[i], params["layers"])
    return params[f"layer_{i}"]


def _emit_attn(sd: dict, pre: str, lp: Mapping, d: int) -> None:
    """q/k/v/o -> HF [out, in] keys; ONE copy for every export branch."""
    attn = lp["attn"]
    sd[pre + "self_attn.q_proj.weight"] = (
        _np32(attn["q"]["kernel"]).reshape(d, -1).T
    )
    sd[pre + "self_attn.k_proj.weight"] = (
        _np32(attn["k"]["kernel"]).reshape(d, -1).T
    )
    sd[pre + "self_attn.v_proj.weight"] = (
        _np32(attn["v"]["kernel"]).reshape(d, -1).T
    )
    sd[pre + "self_attn.o_proj.weight"] = (
        _np32(attn["o"]["kernel"]).reshape(-1, d).T
    )
    for p in ("q", "k", "v"):
        if "bias" in attn[p]:
            sd[pre + f"self_attn.{p}_proj.bias"] = _np32(
                attn[p]["bias"]
            ).reshape(-1)


def _emit_mlp(sd: dict, pre: str, lp: Mapping) -> None:
    """Dense gate/up/down -> HF keys (Llama and Gemma blocks)."""
    mlp = lp["mlp"]
    sd[pre + "mlp.gate_proj.weight"] = _np32(mlp["gate"]["kernel"]).T
    sd[pre + "mlp.up_proj.weight"] = _np32(mlp["up"]["kernel"]).T
    sd[pre + "mlp.down_proj.weight"] = _np32(mlp["down"]["kernel"]).T


def _deepseek_to_hf(params: dict, cfg) -> dict[str, np.ndarray]:
    """Inverse of ``_deepseek_from_hf``: MLA (+ optional MoE) param
    tree -> DeepseekV2-keyed state dict."""
    d, h = cfg.d_model, cfg.n_heads
    np32 = _np32
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np32(params["embed"]["embedding"]),
        "model.norm.weight": np32(params["final_norm"]["scale"]),
    }
    if not cfg.tie_embeddings:
        sd["lm_head.weight"] = np32(params["lm_head"]["kernel"]).T
    for i in range(cfg.n_layers):
        lp = _slice_stack(params, cfg.scan_layers, i)
        pre = f"model.layers.{i}."
        ap = pre + "self_attn."
        attn = lp["attn"]
        sd[pre + "input_layernorm.weight"] = np32(
            lp["attn_norm"]["scale"]
        )
        if cfg.q_lora_rank is None:
            sd[ap + "q_proj.weight"] = (
                np32(attn["q"]["kernel"]).reshape(d, -1).T
            )
        else:
            sd[ap + "q_a_proj.weight"] = np32(attn["q_a"]["kernel"]).T
            sd[ap + "q_a_layernorm.weight"] = np32(
                attn["q_a_norm"]["scale"]
            )
            sd[ap + "q_b_proj.weight"] = (
                np32(attn["q_b"]["kernel"])
                .reshape(cfg.q_lora_rank, -1)
                .T
            )
        sd[ap + "kv_a_proj_with_mqa.weight"] = np32(
            attn["kv_a"]["kernel"]
        ).T
        sd[ap + "kv_a_layernorm.weight"] = np32(
            attn["kv_a_norm"]["scale"]
        )
        sd[ap + "kv_b_proj.weight"] = (
            np32(attn["kv_b_kernel"]).reshape(cfg.kv_lora_rank, -1).T
        )
        sd[ap + "o_proj.weight"] = (
            np32(attn["o"]["kernel"]).reshape(h * cfg.v_head_dim, d).T
        )
        sd[pre + "post_attention_layernorm.weight"] = np32(
            lp["mlp_norm"]["scale"]
        )
        if cfg.moe and i >= cfg.first_k_dense:
            mp = pre + "mlp."
            moe = lp["moe"]
            routed = moe["routed"]
            sd[mp + "gate.weight"] = np32(routed["router"]["kernel"]).T
            for e in range(cfg.n_routed_experts):
                ep = mp + f"experts.{e}."
                sd[ep + "gate_proj.weight"] = np32(
                    routed["w_gate"][e]
                ).T
                sd[ep + "up_proj.weight"] = np32(routed["w_up"][e]).T
                sd[ep + "down_proj.weight"] = np32(
                    routed["w_down"][e]
                ).T
            if cfg.n_shared_experts:
                sh = moe["shared"]
                sp = mp + "shared_experts."
                sd[sp + "gate_proj.weight"] = np32(
                    sh["gate"]["kernel"]
                ).T
                sd[sp + "up_proj.weight"] = np32(sh["up"]["kernel"]).T
                sd[sp + "down_proj.weight"] = np32(
                    sh["down"]["kernel"]
                ).T
        else:
            _emit_mlp(sd, pre, lp)
    return sd


def _gemma_to_hf(params: dict, cfg) -> dict[str, np.ndarray]:
    """Inverse of ``_gemma_from_hf``: pair p "local" -> HF layer 2p,
    "global" -> 2p+1; norm offsets copy directly (both sides store the
    offset-from-1); tied embeddings mean no lm_head tensor."""
    d = cfg.d_model
    np32 = _np32

    if not cfg.tie_embeddings:
        raise NotImplementedError(
            "Gemma export assumes tied embeddings (every released "
            "Gemma-2 checkpoint ties them); exporting an untied tree "
            "would silently re-tie the head to the embedding"
        )

    def pair_tree(p: int) -> Mapping:
        return _slice_stack(params, cfg.scan_layers, p)

    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np32(params["embed"]["embedding"]),
        "model.norm.weight": np32(params["final_norm"]["scale"]),
    }
    norms = {
        "pre_attn_norm": "input_layernorm",
        "post_attn_norm": "post_attention_layernorm",
        "pre_mlp_norm": "pre_feedforward_layernorm",
        "post_mlp_norm": "post_feedforward_layernorm",
    }
    for p in range(cfg.n_layers // 2):
        pt = pair_tree(p)
        for which, i in (("local", 2 * p), ("global", 2 * p + 1)):
            lp = pt[which]
            pre = f"model.layers.{i}."
            for ours, theirs in norms.items():
                sd[pre + theirs + ".weight"] = np32(lp[ours]["scale"])
            _emit_attn(sd, pre, lp, d)
            _emit_mlp(sd, pre, lp)
    return sd


def export_hf(params: dict, cfg: LlamaConfig, out_dir: str) -> dict:
    """Write an HF checkpoint dir (config.json + model.safetensors) that
    ``transformers.*ForCausalLM.from_pretrained`` loads directly."""
    from safetensors.numpy import save_file

    # Map BEFORE touching the filesystem: a validation error (e.g. an
    # untied Gemma tree) must not leave a half-written dir with a
    # config.json that from_pretrained then fails on confusingly.
    sd = to_hf(params, cfg)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_config_dict(cfg), f, indent=2)
    # ascontiguousarray: to_hf returns transposed VIEWS, and safetensors
    # serializes raw buffers — a non-contiguous view would be written in
    # its underlying (un-transposed) byte order, silently scrambling
    # every projection (caught by the transformers-reload parity test).
    # Replace per key so each fp32 base buffer is dropped as soon as its
    # contiguous copy exists (peak ~one model copy, not two).
    for k in list(sd):
        sd[k] = np.ascontiguousarray(sd[k])
    save_file(sd, os.path.join(out_dir, "model.safetensors"))
    return {
        "out": out_dir,
        "n_tensors": len(sd),
        "n_params": int(sum(v.size for v in sd.values())),
    }


def main(argv=None) -> int:
    """CLI. Default: HF checkpoint dir -> Orbax params dir. With
    ``--export MODEL``: the reverse — an Orbax bare-params dir (or a
    training TrainState checkpoint step dir) -> an HF checkpoint dir
    ``from_pretrained`` loads."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpufw.tools.import_hf",
        description="HF checkpoint <-> tpufw params (Orbax)",
    )
    ap.add_argument(
        "src",
        help="HF checkpoint dir (config.json + *.safetensors); with "
             "--export, an Orbax params / TrainState checkpoint dir",
    )
    ap.add_argument("--out", required=True, help="output dir")
    ap.add_argument(
        "--export",
        metavar="MODEL",
        default=None,
        help="reverse direction: export the Orbax tree at SRC as an HF "
             "checkpoint; MODEL names the architecture preset "
             "(LLAMA_CONFIGS / MIXTRAL_CONFIGS / GEMMA_CONFIGS / DEEPSEEK_CONFIGS)",
    )
    args = ap.parse_args(argv)

    import orbax.checkpoint as ocp

    if args.export:
        if args.export.endswith((".yaml", ".yml")):
            # YAML of record: honors model.overrides, so the exported
            # config.json matches what was actually trained (a bare
            # preset name would silently drop e.g. a rope_theta
            # override).
            from tpufw.configs.loader import load_run_config

            cfg = load_run_config(args.export).model_cfg
        else:
            from tpufw.configs.loader import resolve_model_preset

            cfg = resolve_model_preset(args.export)
        src = os.path.abspath(args.src)
        if os.path.isdir(os.path.join(src, "default")):
            src = os.path.join(src, "default")  # CheckpointManager step
        with ocp.StandardCheckpointer() as ckptr:
            meta = ckptr.metadata(src)
            # orbax >= 0.11 wraps the tree in CheckpointMetadata
            # (.item_metadata.tree); 0.x returns the metadata pytree
            # (a dict of ArrayMetadata) directly.
            item = getattr(meta, "item_metadata", None)
            meta_tree = item.tree if item is not None else meta
        if isinstance(meta_tree, dict) and "params" in meta_tree:
            # TrainState checkpoint: restore ONLY the params item —
            # PLACEHOLDER leaves (step, Adam moments, ~2x params) are
            # skipped, keeping peak memory at one model copy. Arrays
            # come back as host numpy (no device or sharding needed —
            # export is a host-side serialization job).
            import jax

            def abstract(m):
                return jax.ShapeDtypeStruct(tuple(m.shape), m.dtype)

            placeholder = getattr(ocp, "PLACEHOLDER", None)
            if placeholder is not None:
                target = {
                    k: jax.tree.map(
                        abstract if k == "params"
                        else (lambda _: placeholder),
                        v,
                    )
                    for k, v in meta_tree.items()
                }

                def rargs(x):
                    if x is placeholder:
                        return ocp.RestoreArgs()
                    return ocp.ArrayRestoreArgs(restore_type=np.ndarray)

                restore_args = jax.tree.map(
                    rargs, target, is_leaf=lambda x: x is placeholder
                )
                with ocp.PyTreeCheckpointer() as ckptr:
                    params = ckptr.restore(
                        src,
                        ocp.args.PyTreeRestore(
                            item=target, restore_args=restore_args
                        ),
                    )["params"]
            else:
                # orbax without PLACEHOLDER (< 0.11): partial restore
                # via transforms — item names ONLY the params subtree
                # and transforms={} drops every checkpoint key absent
                # from it, so step/opt-state bytes never leave disk.
                target = {
                    "params": jax.tree.map(abstract, meta_tree["params"])
                }
                restore_args = jax.tree.map(
                    lambda _: ocp.ArrayRestoreArgs(restore_type=np.ndarray),
                    target,
                )
                with ocp.PyTreeCheckpointer() as ckptr:
                    params = ckptr.restore(
                        src,
                        ocp.args.PyTreeRestore(
                            item=target,
                            restore_args=restore_args,
                            transforms={},
                        ),
                    )["params"]
        else:
            with ocp.StandardCheckpointer() as ckptr:
                params = ckptr.restore(src)
        info = export_hf(params, cfg, args.out)
        print(json.dumps(info))
        return 0

    with open(os.path.join(args.src, "config.json")) as f:
        cfg = config_from_hf(json.load(f))
    params = from_hf_llama(args.src, cfg)

    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(args.out), params)
    ckptr.wait_until_finished()
    n = sum(x.size for x in __import__("jax").tree.leaves(params))
    print(json.dumps({"out": args.out, "n_params": int(n)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
