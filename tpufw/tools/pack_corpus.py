"""Corpus prep CLI: text files -> packed token corpus (<prefix>.bin/.idx).

The reference ships no data tooling (its workload is a diagnostic CLI,
reference README.md:314); tpufw's training path consumes the native
corpus format documented in native/dataloader/dataloader.h. This tool is
the missing first step: tokenize raw text into that format so
``TPUFW_DATA_PREFIX`` points at something a user can actually produce.

    python -m tpufw.tools.pack_corpus --out /data/corpus \
        --tokenizer meta-llama/Meta-Llama-3-8B file1.txt file2.jsonl

Tokenizers:
- ``--tokenizer <hf-name-or-path>``: HuggingFace AutoTokenizer
  (transformers is an optional dependency — a clear error tells you if
  it's missing). Token ids must fit the corpus format's uint32.
- ``--tokenizer bytes`` (default): dependency-free byte-level ids
  (utf-8 byte + 1; 0 is reserved for padding) — enough for smoke tests
  and the unit suite, deterministic everywhere.

Documents: one per line for ``.jsonl`` (key ``text``) / ``.txt`` files
with ``--per-line``; otherwise whole file = one document. Empty docs are
dropped (zero-length docs would emit empty segments).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Callable, Iterator, List, Sequence


def byte_tokenizer(text: str) -> List[int]:
    """utf-8 byte ids shifted by 1 so id 0 stays the pad id."""
    return [b + 1 for b in text.encode("utf-8")]


def hf_tokenizer(name: str) -> Callable[[str], List[int]]:
    try:
        from transformers import AutoTokenizer
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise SystemExit(
            "--tokenizer requires the 'transformers' package for "
            f"anything but 'bytes' (got {name!r}): {e}"
        )
    tok = AutoTokenizer.from_pretrained(name)

    def encode(text: str) -> List[int]:
        return tok.encode(text)

    return encode


def iter_documents(
    paths: Sequence[str], per_line: bool = False
) -> Iterator[str]:
    """Yield raw document strings from .txt / .jsonl inputs."""
    for p in paths:
        path = pathlib.Path(p)
        if path.suffix == ".jsonl":
            with path.open() as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    doc = json.loads(ln)
                    text = doc["text"] if isinstance(doc, dict) else str(doc)
                    if text:
                        yield text
        elif per_line:
            with path.open() as f:
                for ln in f:
                    if ln.strip():
                        yield ln.rstrip("\n")
        else:
            text = path.read_text()
            if text:
                yield text


def pack_corpus(
    inputs: Sequence[str],
    out_prefix: str,
    tokenizer: str = "bytes",
    per_line: bool = False,
) -> dict:
    """Tokenize and write the corpus; returns summary stats."""
    from tpufw.train.native_data import write_token_corpus

    encode = (
        byte_tokenizer if tokenizer == "bytes" else hf_tokenizer(tokenizer)
    )
    docs: List[List[int]] = []
    for text in iter_documents(inputs, per_line=per_line):
        ids = encode(text)
        if not ids:
            continue
        if any(i < 0 or i >= 2**32 for i in ids):
            raise ValueError(
                f"tokenizer {tokenizer!r} produced ids outside uint32"
            )
        docs.append(ids)
    if not docs:
        raise SystemExit("no non-empty documents found")
    bin_path, idx_path = write_token_corpus(out_prefix, docs)
    return {
        "bin": bin_path,
        "idx": idx_path,
        "n_docs": len(docs),
        "n_tokens": sum(len(d) for d in docs),
        "tokenizer": tokenizer,
    }


def main(argv: Sequence[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpufw.tools.pack_corpus", description=__doc__.splitlines()[0]
    )
    ap.add_argument("inputs", nargs="+", help=".txt / .jsonl files")
    ap.add_argument(
        "--out", required=True,
        help="output prefix (writes <out>.bin and <out>.idx)",
    )
    ap.add_argument(
        "--tokenizer", default="bytes",
        help="'bytes' (default) or a HuggingFace tokenizer name/path",
    )
    ap.add_argument(
        "--per-line", action="store_true",
        help="treat each line of .txt inputs as its own document",
    )
    args = ap.parse_args(argv)
    stats = pack_corpus(
        args.inputs, args.out, args.tokenizer, args.per_line
    )
    print(json.dumps(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main())
