"""Analytic per-device HBM estimate for a training or decode config.

The round-2/3 bench sweeps found the winning batch/remat point by
OOM-ladder trial on hardware (docs/PERF.md); this tool is the
paper-napkin version users run FIRST: params + optimizer + gradient +
activation (per remat policy) + logits/CE + KV-cache bytes, divided
over the mesh the way tpufw actually shards them, against the chip's
usable HBM. Estimates are first-order (XLA fusion/padding/temp buffers
add real variance) — the point is choosing a starting batch size and
remat policy, not replacing the measured ladder.

    python -m tpufw.tools.estimate_memory --model llama3_8b \
        --batch 16 --seq 2048 --fsdp 16
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Optional


def _bytes(dtype) -> int:
    """Itemsize for numpy/jax dtypes AND their string names (ml_dtypes
    registers bfloat16 with numpy, so np.dtype handles all of them)."""
    import numpy as np

    try:
        return np.dtype(dtype).itemsize
    except TypeError:
        import jax.numpy as jnp

        return jnp.dtype(dtype).itemsize


def _attn_geometry(cfg) -> tuple[float, float]:
    """(per-token attention projection terms, cached floats per token).

    MHA/GQA (Llama-family): q + o-input (H*dh each) + k + v (K*dh
    each); cache = 2 * K * dh. MLA (DeepSeek): q [H*(dn+dr)], the
    packed latent [kvr+dr], the expanded k/v [H*(dn+dv)], o-input
    [H*dv]; cache = the LATENT kvr + dr — the 3.6x-smaller figure that
    is the family's point (tpufw.models.deepseek)."""
    if hasattr(cfg, "kv_lora_rank"):
        h = cfg.n_heads
        dn, dr, dv = (
            cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        )
        terms = (
            h * (dn + dr)          # q
            + cfg.kv_lora_rank + dr  # packed latent
            + h * (dn + dv)        # expanded k_nope + v
            + h * dv               # o input
        )
        if getattr(cfg, "q_lora_rank", None):
            terms += cfg.q_lora_rank
        return float(terms), float(cfg.kv_lora_rank + dr)
    h_dh = cfg.n_heads * cfg.head_dim
    kv_dh = cfg.n_kv_heads * cfg.head_dim
    return float(2 * h_dh + 2 * kv_dh), float(2 * kv_dh)


@dataclasses.dataclass(frozen=True)
class MemoryEstimate:
    """Per-device byte totals (floats are bytes; names say what)."""

    params: float
    optimizer: float
    gradients: float
    activations: float
    logits_ce: float
    kv_cache: float

    def total(self) -> float:
        return (
            self.params + self.optimizer + self.gradients
            + self.activations + self.logits_ce + self.kv_cache
        )

    def as_dict(self) -> dict:
        d = {k: round(v / 2**30, 3) for k, v in dataclasses.asdict(self).items()}
        d["total_gib"] = round(self.total() / 2**30, 3)
        return d


def estimate_train(
    cfg,
    batch_size: int,
    seq_len: int,
    n_shards: int = 1,
    remat_policy: Optional[str] = None,
    loss_chunk_size: Optional[int] = None,
    adam_mu_dtype: Optional[str] = None,
    grad_accum: int = 1,
) -> MemoryEstimate:
    """Training-step footprint per device — the programmatic entry point
    (the autotuner's HBM pruning oracle, tpufw.tune.space); the CLI below
    is a thin JSON printer over it.

    ``n_shards`` is the param/optimizer sharding degree (the ``fsdp``
    axis; ZeRO-3 layout — tpufw/mesh). The batch dim is assumed sharded
    over the same data x fsdp product, so activation rows divide by it
    too. ``grad_accum`` > 1 further divides activation/logits rows by
    the microbatch count: each microbatch's fwd+bwd completes inside the
    accumulation scan, so only one microbatch's activations are live
    (tpufw.train.trainer.train_step) — at the cost of one extra fp32
    gradient accumulator tree. Mirrors the trainer's actual layout:

    - params in ``cfg.param_dtype``, sharded over fsdp;
    - AdamW mu (``adam_mu_dtype`` or fp32) + nu (fp32), sharded;
    - one full gradient tree materialized between bwd and the update
      (param_dtype), sharded;
    - activations: scan-over-layers saves the per-layer block INPUT
      [B, T, D] in cfg.dtype (all policies), plus per-layer residents
      by policy — "dots" adds the projection outputs (q/k/v/o
      [B,T,H*dh] x4 and gate/up [B,T,f] x2 + down input [B,T,f]),
      "everything" ~2x that, "nothing" adds only one transient block's
      worth;
    - logits/CE: chunked CE holds [B, chunk, V] fp32 (+ bwd double);
      full logits hold [B, T-1, V].
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    p_bytes = _bytes(cfg.param_dtype)
    a_bytes = _bytes(cfg.dtype)
    n_params = cfg.n_params()
    params = n_params * p_bytes / n_shards
    mu_bytes = _bytes(adam_mu_dtype or "float32")
    optimizer = n_params * (mu_bytes + 4) / n_shards
    gradients = n_params * p_bytes / n_shards
    if grad_accum > 1:
        # The accumulation scan carries a full fp32 gradient tree next
        # to each microbatch's own gradients (train_step's zero_g).
        gradients += n_params * 4 / n_shards

    rows = batch_size / max(n_shards, 1) / grad_accum
    t = seq_len
    d, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
    attn_terms, _ = _attn_geometry(cfg)
    policy = remat_policy or getattr(cfg, "remat_policy", "dots")

    boundary = l * rows * t * d * a_bytes  # saved scan carries
    g_tokens = rows * t
    mlp_terms = 3 * f  # gate, up, down-input (dense MLP)
    moe_terms = 0.0
    if getattr(cfg, "n_experts", 0):
        # Einsum-dispatch MoE (tpufw.models.mixtral): the expert
        # buffers replace the dense MLP — xe [E,C,d] + gate/up
        # [E,C,f] x2 with E*C = capacity_factor * G * k tokens-worth —
        # and the dispatch/combine tensors are [G, E, C] =
        # cf * k * G^2 elements EACH, the quadratic-in-group-size term
        # that dominates at large per-device batch (the reason MoE
        # configs shard the routing group hard).
        k = cfg.experts_per_token
        cf = cfg.capacity_factor
        # DeepSeek's fine-grained experts are moe_d_ff wide (and its
        # shared experts add a dense n_shared * moe_d_ff MLP).
        f_e = getattr(cfg, "moe_d_ff", f)
        mlp_terms = cf * k * (d + 2 * f_e)
        n_shared = getattr(cfg, "n_shared_experts", 0)
        if n_shared:
            mlp_terms += 3 * n_shared * f_e
        moe_terms = 2 * cf * k * g_tokens  # dispatch+combine, per token
    per_layer_dots = g_tokens * (
        attn_terms            # projection outputs (arch-specific)
        + mlp_terms
        + moe_terms
        + 2 * d               # two norm outputs
    ) * a_bytes
    if policy == "nothing":
        live = per_layer_dots  # one block recomputed at a time
    elif policy == "attn_out":
        # "nothing" plus one saved [rows, T, D] attention output per
        # layer (tpufw.models.llama _REMAT_POLICIES).
        live = per_layer_dots + l * g_tokens * d * a_bytes
    elif policy == "dots":
        live = l * per_layer_dots
    elif policy == "everything":
        # Attention internals too (scores dominate).
        live = l * (
            per_layer_dots
            + rows * cfg.n_heads * t * t * a_bytes
        )
    else:
        raise ValueError(
            f"unknown remat_policy {policy!r}; choose from "
            "dots|nothing|attn_out|everything"
        )
    activations = boundary + live

    v = cfg.vocab_size
    if loss_chunk_size:
        logits_ce = 2 * rows * min(loss_chunk_size, t) * v * 4
    else:
        logits_ce = 2 * rows * (t - 1) * v * 4

    return MemoryEstimate(
        params=params,
        optimizer=optimizer,
        gradients=gradients,
        activations=activations,
        logits_ce=logits_ce,
        kv_cache=0.0,
    )


def estimate_decode(
    cfg,
    batch_size: int,
    cache_len: Optional[int] = None,
    weights_dtype: Optional[str] = None,
    n_shards: int = 1,
) -> MemoryEstimate:
    """Serving footprint per device: weights (cast per
    ``weights_dtype`` — the TPUFW_DECODE_DTYPE lever) + the KV cache
    [B, cache_len] in cfg.dtype across every layer. ``n_shards``
    divides both (sharded-params decode shards weights over fsdp and
    batch rows over the same devices)."""
    w_bytes = _bytes(weights_dtype or cfg.param_dtype)
    a_bytes = _bytes(cfg.dtype)
    s = cache_len or cfg.max_seq_len
    _, kv_per_token = _attn_geometry(cfg)
    kv = cfg.n_layers * batch_size * s * kv_per_token * a_bytes
    return MemoryEstimate(
        params=cfg.n_params() * w_bytes / n_shards,
        optimizer=0.0,
        gradients=0.0,
        activations=0.0,
        logits_ce=batch_size * cfg.vocab_size * 4 / n_shards,
        kv_cache=kv / n_shards,
    )


def main(argv=None) -> int:
    from tpufw.models import (
        DEEPSEEK_CONFIGS,
        GEMMA_CONFIGS,
        LLAMA_CONFIGS,
        MIXTRAL_CONFIGS,
    )

    from tpufw.configs import bench_model_config

    presets = {
        **LLAMA_CONFIGS,
        **MIXTRAL_CONFIGS,
        **GEMMA_CONFIGS,
        **DEEPSEEK_CONFIGS,
        # The bench's own headline config — this tool's stated purpose
        # is picking its batch/remat point before the OOM ladder does.
        "llama3_600m_bench": bench_model_config(),
    }
    ap = argparse.ArgumentParser(
        description="Analytic per-device HBM estimate (training or decode)"
    )
    ap.add_argument("--model", required=True, help=f"one of {sorted(presets)}")
    ap.add_argument("--batch", type=int, required=True)
    ap.add_argument("--seq", type=int, default=None, help="train seq len")
    ap.add_argument("--fsdp", type=int, default=1, help="param shards")
    ap.add_argument(
        "--remat", default=None,
        choices=["dots", "nothing", "everything"],
    )
    ap.add_argument("--ce-chunk", type=int, default=None)
    ap.add_argument("--grad-accum", type=int, default=1)
    ap.add_argument("--adam-mu-dtype", default=None)
    ap.add_argument(
        "--decode", action="store_true",
        help="serving estimate instead of training",
    )
    ap.add_argument("--cache-len", type=int, default=None)
    ap.add_argument(
        "--decode-dtype", default=None,
        help="weights dtype at decode (TPUFW_DECODE_DTYPE)",
    )
    ap.add_argument(
        "--chip", default="v5e",
        help="chip spec to compare against (static table; 'auto' "
        "queries the live backend, which can block on a wedged one)",
    )
    args = ap.parse_args(argv)
    if args.model not in presets:
        ap.error(f"unknown --model {args.model!r}")
    cfg = presets[args.model]
    from tpufw.utils.hardware import CHIP_SPECS

    if args.chip != "auto" and args.chip not in CHIP_SPECS:
        ap.error(
            f"unknown --chip {args.chip!r}; choose from "
            f"{sorted(CHIP_SPECS)} or 'auto'"
        )

    if args.decode:
        est = estimate_decode(
            cfg, args.batch, args.cache_len, args.decode_dtype,
            n_shards=args.fsdp,
        )
    else:
        est = estimate_train(
            cfg,
            args.batch,
            args.seq or cfg.max_seq_len,
            n_shards=args.fsdp,
            remat_policy=args.remat,
            loss_chunk_size=args.ce_chunk,
            adam_mu_dtype=args.adam_mu_dtype,
            grad_accum=args.grad_accum,
        )
    from tpufw.utils.hardware import detect_chip

    # Static chip table by default: the estimate is pure arithmetic and
    # must not block on (or require) a live accelerator backend.
    chip = (
        detect_chip() if args.chip == "auto" else CHIP_SPECS[args.chip]
    )
    out = {
        "model": args.model,
        "mode": "decode" if args.decode else "train",
        **est.as_dict(),
        "chip": chip.name,
        "chip_hbm_gib": round(chip.hbm_bytes / 2**30, 1),
        "fits": est.total() < chip.hbm_bytes,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
