"""CLI: fold trained LoRA adapters into base kernels for serving/export.

Completes the parameter-efficient fine-tune loop::

    python -m tpufw.tools.import_hf <hf-dir> --out base/   # base params
    TPUFW_INIT_FROM=base/ TPUFW_LORA_RANK=16 \\
        python -m tpufw.workloads.train_llama                # adapters
    python -m tpufw.tools.merge_lora <ckpt> --out merged/ \\
        --rank 16 --alpha 16
    TPUFW_CHECKPOINT_DIR=... tpufw.workloads.serve           # or export_hf

Accepts either a bare-params tree (tpufw.tools.import_hf output shape)
or a full TrainState checkpoint (what Trainer.run saves — its
``params`` subtree is used; step/opt_state are dropped, as a merged
model starts a fresh serving/export life).
"""

from __future__ import annotations

import json
import os


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpufw.tools.merge_lora",
        description="LoRA checkpoint -> merged base-model params (Orbax)",
    )
    ap.add_argument("src", help="Orbax checkpoint dir (bare params or TrainState)")
    ap.add_argument("--out", required=True, help="merged Orbax params dir")
    ap.add_argument("--rank", type=int, default=None,
                    help="the model's lora_rank (default: inferred from "
                         "the adapters; if given it is validated)")
    ap.add_argument("--alpha", type=float, required=True,
                    help="the model's lora_alpha — REQUIRED: unlike rank "
                         "it is not recoverable from the adapters, and a "
                         "wrong value silently mis-scales every kernel")
    args = ap.parse_args(argv)

    import orbax.checkpoint as ocp

    from tpufw.models.lora import merge_lora

    src = os.path.abspath(args.src)
    # A CheckpointManager step dir nests the tree under its item name
    # ("default"); a bare StandardCheckpointer dir holds it directly.
    if os.path.isdir(os.path.join(src, "default")):
        src = os.path.join(src, "default")

    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(src)
        params = tree.get("params", tree) if isinstance(tree, dict) else tree
        merged = merge_lora(params, rank=args.rank, alpha=args.alpha)
        ckptr.save(os.path.abspath(args.out), merged)
        ckptr.wait_until_finished()
    import jax

    n = sum(x.size for x in jax.tree.leaves(merged))
    print(json.dumps({"out": args.out, "n_params": int(n)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
