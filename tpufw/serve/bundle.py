"""Page-bundle wire format: the serialized form of one slot's KV
pages + cursors (``PagedSlotPool.export_slot``'s state dict).

Layout (all integers big-endian):

    MAGIC(4) VERSION(u16) HEADER_LEN(u32) HEADER(json, utf-8)
    BODY (concatenated C-order array bytes, header-manifest order)
    CRC32(u4)  — zlib.crc32 over MAGIC..BODY

The header carries everything needed to reject a bundle cleanly
BEFORE touching an arena: format version, page geometry, kv_quant,
and a per-array manifest (path, shape, dtype). int8 arenas ship their
int8 codes + fp32 page-structured scales raw — the splice is
bit-identical storage and the wire stays ~4x cheaper than bf16.

bfloat16 has no stdlib numpy name; dtypes are stored by name and
resolved through ml_dtypes (a jax dependency) when numpy alone can't.

Stdlib + numpy only — importable by the router, which never loads
jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
import zlib
from typing import Any, Dict, List, Sequence

import numpy as np

MAGIC = b"TPFB"
VERSION = 1

#: The single source of truth for the bundle header: key -> (python
#: type, since-version, required). encode_bundle builds the header
#: from this table, decode_bundle validates presence + type against
#: it (required keys are rejected uniformly when missing), and
#: peek_trace takes its type check from the same row — so producer,
#: consumer, and tpulint TPU015 all read one schema. Unknown header
#: keys are ignored on decode (forward compatibility: a newer
#: producer may add optional keys without a version bump); a key only
#: becomes load-bearing by gaining a row here.
# wire: schema bundle-header
HEADER_SCHEMA: Dict[str, tuple] = {
    "version": (int, 1, True),
    "arrays": (list, 1, True),
    "page": (int, 1, True),
    "kv_quant": (str, 1, True),
    "n_pages": (int, 1, True),
    "token": (int, 1, True),
    "pos": (int, 1, True),
    "remaining": (int, 1, True),
    "done": (bool, 1, True),
    "cache_index": (int, 1, True),
    "trace": (dict, 1, False),
    # Prompt token ids, optional: a decode replica running
    # speculative self-drafting (TPUFW_SERVE_SPEC_K) needs the
    # request's history to mine n-gram proposals from; bundles from
    # producers that predate the field still splice fine — the slot
    # just drafts from its generated tokens alone.
    "prompt": (list, 1, False),
    # KV-fabric session resumption fields, optional (VERSION stays 1;
    # old decoders splice these bundles unchanged and simply start the
    # emitted-token list from `token` alone):
    # - "session": the router's sticky session id, stamped at prefill
    #   and carried through drain bundles so the router can re-home a
    #   killed replica's sessions by name.
    # - "tokens": every token the ORIGIN replica already emitted (the
    #   last one == `token`). A resuming replica seeds its emitted
    #   list from this so the client receives the full, divergence-
    #   free sequence across the migration seam.
    "session": (str, 1, False),
    "tokens": (list, 1, False),
}

#: Non-array metadata fields copied between state dict and header
#: verbatim — derived from the schema, not a second hand-maintained
#: list ("trace" is optional and handled separately).
_META_FIELDS = tuple(
    k for k in HEADER_SCHEMA if k not in ("version", "arrays", "trace")
)


class BundleError(ValueError):
    """A malformed/mismatched bundle, rejected before any arena
    write."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; covers bfloat16 et al.

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise BundleError(f"unknown array dtype {name!r}") from None


def encode_bundle(state: Dict[str, Any]) -> bytes:
    """Serialize an ``export_slot`` state dict. The optional ``seen``
    row (repetition-penalty mask) travels as one more manifest entry
    under the reserved path ``"seen"``. An optional ``trace`` dict
    (request-trace meta + per-stage timings, tpufw.obs.reqtrace)
    rides in the header; decoders that predate it ignore unknown
    header keys, so VERSION stays 1."""
    # wire: produces bundle-header via header
    arrays = [np.ascontiguousarray(a) for a in state["arrays"]]
    paths = [str(p) for p in state["paths"]]
    if state.get("seen") is not None:
        arrays.append(np.ascontiguousarray(state["seen"]))
        paths.append("seen")
    manifest = [
        {
            "path": p,
            "shape": list(a.shape),
            "dtype": a.dtype.name,
        }
        for p, a in zip(paths, arrays)
    ]
    header = {"version": VERSION, "arrays": manifest}
    for key, (typ, _since, required) in HEADER_SCHEMA.items():
        if key in header:
            continue  # built above
        if required:
            header[key] = state[key]
        elif isinstance(state.get(key), typ):
            header[key] = state[key]
    hjson = json.dumps(header, sort_keys=True).encode("utf-8")
    parts = [MAGIC, struct.pack(">HI", VERSION, len(hjson)), hjson]
    parts.extend(a.tobytes() for a in arrays)
    payload = b"".join(parts)
    return payload + struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF)


def decode_bundle(data: bytes) -> Dict[str, Any]:
    """Parse bundle bytes back into an ``export_slot``-shaped state
    dict; raises BundleError on any magic/version/manifest/checksum
    mismatch — a tampered or truncated bundle must never reach the
    arena. Header fields are validated (presence AND type) against
    HEADER_SCHEMA, the same table encode_bundle writes from."""
    # wire: consumes bundle-header via header
    if len(data) < 14:
        raise BundleError(f"bundle truncated ({len(data)} bytes)")
    if data[:4] != MAGIC:
        raise BundleError(f"bad magic {data[:4]!r} (want {MAGIC!r})")
    body, (crc,) = data[:-4], struct.unpack(">I", data[-4:])
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise BundleError("checksum mismatch — bundle corrupt in flight")
    version, hlen = struct.unpack(">HI", data[4:10])
    if version != VERSION:
        raise BundleError(
            f"bundle version {version} != supported {VERSION}"
        )
    if 10 + hlen > len(body):
        raise BundleError("header overruns bundle body")
    try:
        header = json.loads(body[10:10 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise BundleError(f"unparseable header: {e}") from None
    offset = 10 + hlen
    arrays = []
    for entry in header.get("arrays", []):
        dtype = _np_dtype(str(entry["dtype"]))
        shape = tuple(int(d) for d in entry["shape"])
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if offset + nbytes > len(body):
            raise BundleError(
                f"array {entry.get('path')!r} overruns bundle body"
            )
        arrays.append(
            np.frombuffer(
                body, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
                offset=offset,
            ).reshape(shape)
        )
        offset += nbytes
    if offset != len(body):
        raise BundleError(
            f"{len(body) - offset} trailing bytes after last array"
        )
    paths = [str(e["path"]) for e in header.get("arrays", [])]
    seen = None
    if paths and paths[-1] == "seen":
        seen = arrays.pop()
        paths.pop()
    for key, (typ, _since, required) in HEADER_SCHEMA.items():
        if key not in header:
            if required:
                raise BundleError(
                    f"header missing required field {key!r}"
                )
            continue
        value = header[key]
        # bool is an int subclass; "done" must be the only bool field.
        if typ is int and isinstance(value, bool):
            raise BundleError(
                f"header field {key!r} must be an integer, got bool"
            )
        if not isinstance(value, typ):
            raise BundleError(
                f"header field {key!r} must be {typ.__name__}, got "
                f"{type(value).__name__}"
            )
    if header["version"] != version:
        raise BundleError(
            f"header version {header['version']} disagrees with frame "
            f"prefix {version} — producer drift"
        )
    state: Dict[str, Any] = {}
    for k in _META_FIELDS:
        # Optional fields (schema required=False) decode to None when
        # the producer predates them; required ones were proven
        # present by the schema pass above.
        state[k] = header.get(k)
    state["paths"] = paths
    state["arrays"] = arrays
    state["seen"] = seen
    # Absent on bundles from pre-trace producers — still a valid
    # bundle, the request just has no cross-role correlation. When
    # present the schema pass above already proved it a dict.
    state["trace"] = header.get("trace")
    return state


def peek_trace(data: bytes) -> "Dict[str, Any] | None":
    """Header-only read of the trace meta — no array parsing, no CRC
    walk over the (multi-MB) body, never raises. The router uses this
    to pull engine-reported stage timings out of a bundle it otherwise
    treats as opaque bytes, including bundles that would fail full
    decode (so a request that dies in flight still gets attributed)."""
    # wire: consumes bundle-header via header
    try:
        if data[:4] != MAGIC:
            return None
        _version, hlen = struct.unpack(">HI", data[4:10])
        header = json.loads(data[10:10 + hlen].decode("utf-8"))
        trace = header.get("trace")
        # Same type row decode_bundle enforces — one schema, two
        # consumers.
        if isinstance(trace, HEADER_SCHEMA["trace"][0]):
            return trace
        return None
    except Exception:
        return None


# --------------------------------------------------- prefix digests
#
# The affinity identity both sides of the wire agree on: a cumulative
# blake2b chain over page-aligned token chunks — EXACTLY the radix
# trie's chunking (tpufw.infer.prefix splits at full pages and drops
# the tail), so digest i names the same KV a trie path of depth i+1
# holds. Replicas advertise the digests of their resident (and
# spilled-but-restorable) trie paths in signals(); the router hashes
# an incoming prompt the same way and steers to the deepest match.
# Cumulative chaining means a digest commits to the WHOLE path, never
# a lone chunk — matching the trie's path-is-the-unit-of-reuse rule.

#: Digest width: 8 bytes / 16 hex chars. Affinity is a routing hint
#: backed by an exact token-compare in the trie, so collisions cost a
#: misrouted request, never a wrong token.
PREFIX_DIGEST_SIZE = 8


def chunk_digests(
    tokens: Sequence[int], page: int, k: int
) -> List[str]:
    """Cumulative digests of the first ``min(k, full-pages)`` page-
    aligned chunks of ``tokens``; digest i covers chunks 0..i. Pure
    stdlib — the router calls this per request and never loads jax."""
    out: List[str] = []
    if page <= 0 or k <= 0:
        return out
    h = hashlib.blake2b(digest_size=PREFIX_DIGEST_SIZE)
    n_full = len(tokens) // page
    for i in range(min(int(k), n_full)):
        chunk = tokens[i * page:(i + 1) * page]
        h.update(",".join(str(int(t)) for t in chunk).encode())
        h.update(b"|")  # chunk boundary: len(chunk) is fixed, but be explicit
        out.append(h.hexdigest())
    return out


# ----------------------------------------------------- session store
#
# The cross-process half of the spill tier (tpufw.infer.spill): a
# drained replica writes each live session's bundle to a shared
# directory (TPUFW_KV_SPILL_DIR), and the ROUTER — which never loads
# jax, hence these helpers living here — reads it back to re-home the
# session onto a surviving replica. File names match SpillTier's
# directory tier (kind "session"), so an engine-side spill and a
# drain write land on the same path.


def session_path(directory: str, session: str) -> str:
    """On-disk path for one session's spill bundle — blake2b of the
    id keeps arbitrary session strings filesystem-safe."""
    h = hashlib.blake2b(session.encode("utf-8"), digest_size=16)
    return os.path.join(directory, f"session-{h.hexdigest()}.tpfb")


def store_session(directory: str, session: str, data: bytes) -> str:
    """Atomically persist a session bundle (temp file + rename: a
    concurrently re-homing router never sees a torn bundle)."""
    # wire: produces session-bundle via file
    os.makedirs(directory, exist_ok=True)
    path = session_path(directory, session)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def load_session(directory: str, session: str) -> "bytes | None":
    """Fetch a session bundle, or None when the session was never
    drained (the caller falls back to a plain 502)."""
    # wire: consumes session-bundle via file
    try:
        with open(session_path(directory, session), "rb") as f:
            return f.read()
    except OSError:
        return None


def drop_session(directory: str, session: str) -> None:
    """Delete a consumed session bundle — a re-homed session must not
    resurrect from a stale spill file on its next failover."""
    try:
        os.unlink(session_path(directory, session))
    except OSError:
        pass


# ------------------------------------------------------ spill wiring

def attach_spill(pool, tier, *, events=None, on_restore=None):
    """Wire ``tier`` (tpufw.infer.spill.SpillTier) into ``pool``'s
    trie-spill callbacks with this module's TPFB codec at the
    boundary: an evicted trie page is encoded exactly like a migration
    bundle (raw int8 codes + page-structured scales), and restore
    decodes into the same splice-shaped state ``import_pages``
    scatters back — so spill -> restore is bit-equal by construction.

    ``on_restore(seconds)`` feeds the ``tpufw_kv_restore_seconds``
    histogram where a metrics registry exists (host-side fetch +
    decode wall; the device scatter rides the admission's own admit
    stage). ``events`` (tpufw.obs.events API) gets one ``serve_spill``
    record per page moved across the HBM boundary."""

    def _spill(path_tokens, state):
        # wire: produces kv-spill-page via spill-tier
        data = encode_bundle(state)
        from tpufw.infer.spill import trie_key

        tier.put(
            "trie", trie_key(path_tokens), data, int(state["n_pages"])
        )
        if events is not None:
            events.emit(
                "serve_spill", entry="trie", direction="out",
                pages=int(state["n_pages"]), bytes=len(data),
            )

    def _restore(path_tokens):
        # wire: consumes kv-spill-page via spill-tier
        from tpufw.infer.spill import trie_key

        name = trie_key(path_tokens)
        t0 = time.perf_counter()
        data = tier.get("trie", name)
        if data is None:
            return None
        try:
            state = decode_bundle(data)
        except BundleError:
            tier.pop("trie", name)  # torn entry: never retry it
            return None
        # Consume the entry: its pages are back in the arena, and a
        # kept host copy would go stale the moment decode appends.
        tier.pop("trie", name)
        wall = time.perf_counter() - t0
        if on_restore is not None:
            on_restore(wall)
        if events is not None:
            events.emit(
                "serve_spill", entry="trie", direction="in",
                pages=int(state["n_pages"]), bytes=len(data),
                wall_s=round(wall, 6),
            )
        return state

    pool.trie_spill = _spill
    pool.trie_restore = _restore


def advertised_digests(pool, tier, k: int, cache: Dict[str, Any]):
    """The digest set a replica advertises in its ``signals()`` reply:
    one cumulative digest per resident trie path (every node IS a
    path, so every depth <= k is covered by enumeration) plus every
    cumulative depth of each spilled-but-restorable path. Cached in
    ``cache`` keyed on (trie version, spill counters, k) — recomputed
    only at chunk boundaries that actually changed the resident set,
    which is the "digest updates at chunk boundaries" contract."""
    prefix = getattr(pool, "prefix", None)
    ver = prefix.version if prefix is not None else -1
    stamp = None
    if tier is not None:
        stamp = (
            tier.spilled_pages_total,
            tier.restored_total,
            tier.dropped_total,
        )
    key = (ver, stamp, int(k))
    if cache.get("key") == key:
        return cache["digests"]
    page = int(pool.page)
    out: List[str] = []
    seen = set()
    if prefix is not None:
        for path in prefix.paths(int(k), limit=512):
            d = chunk_digests(path, page, k)
            if d and d[-1] not in seen:
                seen.add(d[-1])
                out.append(d[-1])
    if tier is not None:
        for name in tier.names("trie"):
            toks = [int(t) for t in name.split(",") if t]
            for h in chunk_digests(toks, page, k):
                if h not in seen:
                    seen.add(h)
                    out.append(h)
    out = out[:1024]
    cache["key"] = key
    cache["digests"] = out
    return out
