"""Page-bundle wire format: the serialized form of one slot's KV
pages + cursors (``PagedSlotPool.export_slot``'s state dict).

Layout (all integers big-endian):

    MAGIC(4) VERSION(u16) HEADER_LEN(u32) HEADER(json, utf-8)
    BODY (concatenated C-order array bytes, header-manifest order)
    CRC32(u4)  — zlib.crc32 over MAGIC..BODY

The header carries everything needed to reject a bundle cleanly
BEFORE touching an arena: format version, page geometry, kv_quant,
and a per-array manifest (path, shape, dtype). int8 arenas ship their
int8 codes + fp32 page-structured scales raw — the splice is
bit-identical storage and the wire stays ~4x cheaper than bf16.

bfloat16 has no stdlib numpy name; dtypes are stored by name and
resolved through ml_dtypes (a jax dependency) when numpy alone can't.

Stdlib + numpy only — importable by the router, which never loads
jax.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict

import numpy as np

MAGIC = b"TPFB"
VERSION = 1

#: The single source of truth for the bundle header: key -> (python
#: type, since-version, required). encode_bundle builds the header
#: from this table, decode_bundle validates presence + type against
#: it (required keys are rejected uniformly when missing), and
#: peek_trace takes its type check from the same row — so producer,
#: consumer, and tpulint TPU015 all read one schema. Unknown header
#: keys are ignored on decode (forward compatibility: a newer
#: producer may add optional keys without a version bump); a key only
#: becomes load-bearing by gaining a row here.
# wire: schema bundle-header
HEADER_SCHEMA: Dict[str, tuple] = {
    "version": (int, 1, True),
    "arrays": (list, 1, True),
    "page": (int, 1, True),
    "kv_quant": (str, 1, True),
    "n_pages": (int, 1, True),
    "token": (int, 1, True),
    "pos": (int, 1, True),
    "remaining": (int, 1, True),
    "done": (bool, 1, True),
    "cache_index": (int, 1, True),
    "trace": (dict, 1, False),
    # Prompt token ids, optional: a decode replica running
    # speculative self-drafting (TPUFW_SERVE_SPEC_K) needs the
    # request's history to mine n-gram proposals from; bundles from
    # producers that predate the field still splice fine — the slot
    # just drafts from its generated tokens alone.
    "prompt": (list, 1, False),
}

#: Non-array metadata fields copied between state dict and header
#: verbatim — derived from the schema, not a second hand-maintained
#: list ("trace" is optional and handled separately).
_META_FIELDS = tuple(
    k for k in HEADER_SCHEMA if k not in ("version", "arrays", "trace")
)


class BundleError(ValueError):
    """A malformed/mismatched bundle, rejected before any arena
    write."""


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax dependency; covers bfloat16 et al.

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise BundleError(f"unknown array dtype {name!r}") from None


def encode_bundle(state: Dict[str, Any]) -> bytes:
    """Serialize an ``export_slot`` state dict. The optional ``seen``
    row (repetition-penalty mask) travels as one more manifest entry
    under the reserved path ``"seen"``. An optional ``trace`` dict
    (request-trace meta + per-stage timings, tpufw.obs.reqtrace)
    rides in the header; decoders that predate it ignore unknown
    header keys, so VERSION stays 1."""
    # wire: produces bundle-header via header
    arrays = [np.ascontiguousarray(a) for a in state["arrays"]]
    paths = [str(p) for p in state["paths"]]
    if state.get("seen") is not None:
        arrays.append(np.ascontiguousarray(state["seen"]))
        paths.append("seen")
    manifest = [
        {
            "path": p,
            "shape": list(a.shape),
            "dtype": a.dtype.name,
        }
        for p, a in zip(paths, arrays)
    ]
    header = {"version": VERSION, "arrays": manifest}
    for key, (typ, _since, required) in HEADER_SCHEMA.items():
        if key in header:
            continue  # built above
        if required:
            header[key] = state[key]
        elif isinstance(state.get(key), typ):
            header[key] = state[key]
    hjson = json.dumps(header, sort_keys=True).encode("utf-8")
    parts = [MAGIC, struct.pack(">HI", VERSION, len(hjson)), hjson]
    parts.extend(a.tobytes() for a in arrays)
    payload = b"".join(parts)
    return payload + struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF)


def decode_bundle(data: bytes) -> Dict[str, Any]:
    """Parse bundle bytes back into an ``export_slot``-shaped state
    dict; raises BundleError on any magic/version/manifest/checksum
    mismatch — a tampered or truncated bundle must never reach the
    arena. Header fields are validated (presence AND type) against
    HEADER_SCHEMA, the same table encode_bundle writes from."""
    # wire: consumes bundle-header via header
    if len(data) < 14:
        raise BundleError(f"bundle truncated ({len(data)} bytes)")
    if data[:4] != MAGIC:
        raise BundleError(f"bad magic {data[:4]!r} (want {MAGIC!r})")
    body, (crc,) = data[:-4], struct.unpack(">I", data[-4:])
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise BundleError("checksum mismatch — bundle corrupt in flight")
    version, hlen = struct.unpack(">HI", data[4:10])
    if version != VERSION:
        raise BundleError(
            f"bundle version {version} != supported {VERSION}"
        )
    if 10 + hlen > len(body):
        raise BundleError("header overruns bundle body")
    try:
        header = json.loads(body[10:10 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise BundleError(f"unparseable header: {e}") from None
    offset = 10 + hlen
    arrays = []
    for entry in header.get("arrays", []):
        dtype = _np_dtype(str(entry["dtype"]))
        shape = tuple(int(d) for d in entry["shape"])
        nbytes = dtype.itemsize * int(np.prod(shape, dtype=np.int64))
        if offset + nbytes > len(body):
            raise BundleError(
                f"array {entry.get('path')!r} overruns bundle body"
            )
        arrays.append(
            np.frombuffer(
                body, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)),
                offset=offset,
            ).reshape(shape)
        )
        offset += nbytes
    if offset != len(body):
        raise BundleError(
            f"{len(body) - offset} trailing bytes after last array"
        )
    paths = [str(e["path"]) for e in header.get("arrays", [])]
    seen = None
    if paths and paths[-1] == "seen":
        seen = arrays.pop()
        paths.pop()
    for key, (typ, _since, required) in HEADER_SCHEMA.items():
        if key not in header:
            if required:
                raise BundleError(
                    f"header missing required field {key!r}"
                )
            continue
        value = header[key]
        # bool is an int subclass; "done" must be the only bool field.
        if typ is int and isinstance(value, bool):
            raise BundleError(
                f"header field {key!r} must be an integer, got bool"
            )
        if not isinstance(value, typ):
            raise BundleError(
                f"header field {key!r} must be {typ.__name__}, got "
                f"{type(value).__name__}"
            )
    if header["version"] != version:
        raise BundleError(
            f"header version {header['version']} disagrees with frame "
            f"prefix {version} — producer drift"
        )
    state: Dict[str, Any] = {}
    for k in _META_FIELDS:
        # Optional fields (schema required=False) decode to None when
        # the producer predates them; required ones were proven
        # present by the schema pass above.
        state[k] = header.get(k)
    state["paths"] = paths
    state["arrays"] = arrays
    state["seen"] = seen
    # Absent on bundles from pre-trace producers — still a valid
    # bundle, the request just has no cross-role correlation. When
    # present the schema pass above already proved it a dict.
    state["trace"] = header.get("trace")
    return state


def peek_trace(data: bytes) -> "Dict[str, Any] | None":
    """Header-only read of the trace meta — no array parsing, no CRC
    walk over the (multi-MB) body, never raises. The router uses this
    to pull engine-reported stage timings out of a bundle it otherwise
    treats as opaque bytes, including bundles that would fail full
    decode (so a request that dies in flight still gets attributed)."""
    # wire: consumes bundle-header via header
    try:
        if data[:4] != MAGIC:
            return None
        _version, hlen = struct.unpack(">HI", data[4:10])
        header = json.loads(data[10:10 + hlen].decode("utf-8"))
        trace = header.get("trace")
        # Same type row decode_bundle enforces — one schema, two
        # consumers.
        if isinstance(trace, HEADER_SCHEMA["trace"][0]):
            return trace
        return None
    except Exception:
        return None
